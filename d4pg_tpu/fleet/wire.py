"""Payload codecs for the fleet frames (HELLO / WINDOWS / WINDOWS_OK).

The frame layout itself — magic, version, type, req_id, length — is the
policy server's (``d4pg_tpu/serve/protocol.py``); this module only defines
what goes INSIDE the fleet frames:

``HELLO`` (JSON)
    The actor's opening handshake: ``{actor_id, env, obs_dim, action_dim,
    n_step, gamma, generation}``. The ingest server validates the data
    shape against its replay config — a dims/n-step/gamma mismatch is a
    config error that would silently corrupt training, so it is refused
    with ``ERROR`` before any window is accepted.

``HELLO_OK`` (JSON)
    ``{generation, max_windows_per_frame, max_inflight}`` — the learner's
    current bundle generation (so a freshly-connected actor knows whether
    its bundle is already stale) and the flow-control window: at most
    ``max_inflight`` unacknowledged WINDOWS frames per connection, each
    carrying at most ``max_windows_per_frame`` windows.

``WINDOWS`` (binary)
    ``u32 generation, u32 count`` then ``count`` rows of float32:
    ``obs[obs_dim] · action[action_dim] · reward · next_obs[obs_dim] ·
    discount`` — a COMPLETE n-step window per row, exactly the columns
    :class:`~d4pg_tpu.replay.uniform.Transition` stores (reward is the
    collapsed n-step return, discount is γ^m·(1−terminal)). Rewards are
    shipped f32 because the replay ring stores f32: the actor-side
    float64 accumulation rounds at exactly the same point the in-process
    writer path rounds (``ReplayBuffer.add_batch``'s cast), which is what
    makes fleet vs in-process replay content byte-identical.

``WINDOWS_OK`` (struct)
    ``u32 accepted, u32 dropped_stale`` — the per-frame account. A frame
    shed at admission (bounded queue full) is answered ``OVERLOADED``
    with reason ``queue_full`` instead, mirroring the serve batcher's
    explicit shed contract.

Deliberately JAX-free (numpy + stdlib): imported by actor hosts.
"""

from __future__ import annotations

import json
import struct
from typing import Tuple

import numpy as np

from d4pg_tpu.serve.protocol import MAX_PAYLOAD, ProtocolError

_WINDOWS_HEAD = struct.Struct("<II")   # generation, count
_WINDOWS_OK = struct.Struct("<II")     # accepted, dropped_stale


def window_row_floats(obs_dim: int, action_dim: int) -> int:
    """float32 slots per window row: obs + action + reward + next_obs +
    discount."""
    return 2 * obs_dim + action_dim + 2


def max_windows_per_frame(obs_dim: int, action_dim: int, cap: int = 256) -> int:
    """Largest window count per frame that fits ``MAX_PAYLOAD``, capped —
    a frame is also the shed/ack granularity, so unboundedly large frames
    would make admission control coarse."""
    fit = (MAX_PAYLOAD - _WINDOWS_HEAD.size) // (
        4 * window_row_floats(obs_dim, action_dim)
    )
    if fit < 1:
        raise ValueError(
            f"one window row (obs_dim={obs_dim}, action_dim={action_dim}) "
            f"exceeds MAX_PAYLOAD={MAX_PAYLOAD}; the fleet path is for flat "
            "observation vectors"
        )
    return max(1, min(cap, fit))


# ------------------------------------------------------------------ HELLO
def encode_hello(
    *,
    actor_id: str,
    env: str,
    obs_dim: int,
    action_dim: int,
    n_step: int,
    gamma: float,
    generation: int,
) -> bytes:
    return json.dumps(
        {
            "actor_id": actor_id,
            "env": env,
            "obs_dim": int(obs_dim),
            "action_dim": int(action_dim),
            "n_step": int(n_step),
            "gamma": float(gamma),
            "generation": int(generation),
        }
    ).encode()


def decode_hello(payload: bytes) -> dict:
    try:
        doc = json.loads(payload.decode())
        # coerce the required numeric keys so a missing one (KeyError) or
        # a wrong-typed one (TypeError: {"obs_dim": null}) fails HERE,
        # with a ProtocolError the reader answers, not deep in validation
        for k in ("obs_dim", "action_dim", "n_step"):
            doc[k] = int(doc[k])
        doc["gamma"] = float(doc["gamma"])
        doc["generation"] = int(doc.get("generation", 0))
        return doc
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise ProtocolError(f"malformed HELLO payload: {e}") from e


def encode_hello_ok(
    *, generation: int, max_windows: int, max_inflight: int
) -> bytes:
    return json.dumps(
        {
            "generation": int(generation),
            "max_windows_per_frame": int(max_windows),
            "max_inflight": int(max_inflight),
        }
    ).encode()


def decode_hello_ok(payload: bytes) -> dict:
    try:
        doc = json.loads(payload.decode())
        for k in ("generation", "max_windows_per_frame", "max_inflight"):
            doc[k] = int(doc[k])
        return doc
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise ProtocolError(f"malformed HELLO_OK payload: {e}") from e


# ---------------------------------------------------------------- WINDOWS
def encode_windows(
    generation: int,
    obs: np.ndarray,
    action: np.ndarray,
    reward: np.ndarray,
    next_obs: np.ndarray,
    discount: np.ndarray,
) -> bytes:
    """Pack ``n`` complete windows into one WINDOWS payload. Inputs are
    ``[n, obs_dim] / [n, action_dim] / [n] / [n, obs_dim] / [n]``."""
    obs = np.ascontiguousarray(obs, np.float32)
    action = np.ascontiguousarray(action, np.float32)
    n, obs_dim = obs.shape
    rowf = window_row_floats(obs_dim, action.shape[1])
    rows = np.empty((n, rowf), np.float32)
    c = 0
    rows[:, c : c + obs_dim] = obs
    c += obs_dim
    rows[:, c : c + action.shape[1]] = action
    c += action.shape[1]
    rows[:, c] = np.asarray(reward, np.float32)
    c += 1
    rows[:, c : c + obs_dim] = np.asarray(next_obs, np.float32)
    c += obs_dim
    rows[:, c] = np.asarray(discount, np.float32)
    payload = _WINDOWS_HEAD.pack(int(generation), n) + rows.tobytes()
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(
            f"WINDOWS payload {len(payload)} bytes > max {MAX_PAYLOAD}; "
            "send fewer windows per frame"
        )
    return payload


def decode_windows(
    payload: bytes, obs_dim: int, action_dim: int
) -> Tuple[int, dict]:
    """→ ``(generation, columns)`` where columns maps the Transition field
    names to fresh arrays. ProtocolError on any size inconsistency (the
    truncated/oversized-frame fault path)."""
    if len(payload) < _WINDOWS_HEAD.size:
        raise ProtocolError(
            f"WINDOWS payload {len(payload)} bytes < header "
            f"{_WINDOWS_HEAD.size}"
        )
    generation, count = _WINDOWS_HEAD.unpack_from(payload)
    rowf = window_row_floats(obs_dim, action_dim)
    want = _WINDOWS_HEAD.size + 4 * rowf * count
    if len(payload) != want:
        raise ProtocolError(
            f"WINDOWS payload is {len(payload)} bytes, header declares "
            f"{count} rows of {rowf} float32 = {want}"
        )
    rows = np.frombuffer(
        payload, np.float32, offset=_WINDOWS_HEAD.size
    ).reshape(count, rowf)
    c = 0
    obs = rows[:, c : c + obs_dim].copy()
    c += obs_dim
    action = rows[:, c : c + action_dim].copy()
    c += action_dim
    reward = rows[:, c].copy()
    c += 1
    next_obs = rows[:, c : c + obs_dim].copy()
    c += obs_dim
    discount = rows[:, c].copy()
    return int(generation), {
        "obs": obs,
        "action": action,
        "reward": reward,
        "next_obs": next_obs,
        "discount": discount,
    }


# ------------------------------------------------------------- WINDOWS_OK
def encode_windows_ok(accepted: int, dropped_stale: int = 0) -> bytes:
    return _WINDOWS_OK.pack(int(accepted), int(dropped_stale))


def decode_windows_ok(payload: bytes) -> Tuple[int, int]:
    if len(payload) != _WINDOWS_OK.size:
        raise ProtocolError(
            f"WINDOWS_OK payload is {len(payload)} bytes, "
            f"expected {_WINDOWS_OK.size}"
        )
    accepted, dropped_stale = _WINDOWS_OK.unpack(payload)
    return accepted, dropped_stale
