"""NumPy-only policy evaluation from a serving bundle.

The fleet actor host's contract is that its hot path NEVER imports JAX —
an actor host is a cheap CPU box running gymnasium + numpy, and pulling
the JAX runtime there costs memory, import seconds, and (on spawn'd
children) outright unsafety. So instead of ``serve.bundle.load_bundle``
(whose param restore goes through ``jax.tree_util``), this module reads
the SAME bundle directory with numpy + stdlib only:

- ``bundle.json`` is plain JSON (config, bounds, obs-norm stats, meta);
- ``actor_params.npz`` stores the actor leaves under zero-padded
  ``leaf_%05d`` keys in ``tree_flatten`` order. For the MLP actor that
  order is fully determined: flax dict keys flatten sorted, so leaves
  arrive as ``(bias, kernel)`` pairs per layer, layers in name order
  (``hidden_0 < hidden_1 < … < out``). The loader re-derives the layer
  structure from the declared ``hidden_sizes`` and validates every leaf
  shape against the chain — a scrambled order or a config/params
  mismatch is a hard load error, never a silently-garbage policy.

Pixel bundles (conv encoder) are refused: the fleet path is for flat
observation vectors (the conv forward belongs on an accelerator; a pixel
actor host would be serving-shaped, not fleet-shaped).

The forward is the exact acting-time data path the server runs —
normalize → MLP(relu) → tanh — in float32 numpy. Parity with the jitted
``act_deterministic`` is tested to ~1e-5 (XLA may reassociate float
reductions; exploration noise dwarfs that).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

# serve/bundle.py's layout constants, restated: importing that module pulls
# D4PGConfig (and with it the JAX runtime) at top level, which this module
# must never do. tests/test_fleet.py pins the two copies equal.
BUNDLE_VERSION = 1
PARAMS_FILE = "actor_params.npz"
META_FILE = "bundle.json"


class NumpyPolicy:
    """A loaded bundle evaluated in numpy. ``act`` maps ``[N, obs_dim]``
    observations to canonical (−1, 1) actions — the space host envs step
    in (``GymAdapter`` applies the affine to env bounds itself, so the
    bundle's bounds are carried for provenance, not applied here)."""

    def __init__(
        self,
        *,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        obs_dim: int,
        action_dim: int,
        n_step: int,
        gamma: float,
        env: Optional[str],
        generation: int,
        obs_norm: Optional[Tuple[np.ndarray, np.ndarray]],
        obs_clip: float = 5.0,
        mtime: Optional[float] = None,
        path: Optional[str] = None,
    ):
        self._layers = layers            # [(kernel [in, out], bias [out])]
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.n_step = n_step
        self.gamma = gamma
        self.env = env
        self.generation = generation
        self._obs_norm = obs_norm        # (mean_f32, std_f32_floored) | None
        self._obs_clip = obs_clip
        self.mtime = mtime               # bundle.json mtime at load
        self.path = path

    def act(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic forward: ``[N, obs_dim]`` → ``[N, action_dim]``
        in (−1, 1)."""
        x = np.asarray(obs, np.float32)
        if self._obs_norm is not None:
            mean, std = self._obs_norm
            x = np.clip((x - mean) / std, -self._obs_clip, self._obs_clip)
        last = len(self._layers) - 1
        for i, (kernel, bias) in enumerate(self._layers):
            x = x @ kernel + bias
            if i < last:
                np.maximum(x, 0.0, out=x)  # relu
        return np.tanh(x)


def _derive_obs_norm(
    stats: Optional[dict], obs_dim: int, eps: float = 1e-2
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(mean_f32, std_f32 floored at eps) from persisted Welford stats —
    the same derivation the serve batcher and RunningObsNorm apply."""
    if stats is None:
        return None
    count = float(stats["count"])
    mean = np.asarray(stats["mean"], np.float64)
    if mean.shape != (obs_dim,):
        raise ValueError(
            f"obs_norm stats are {mean.shape}-shaped, obs_dim is {obs_dim}"
        )
    m2 = np.asarray(stats["m2"], np.float64)
    std = (
        np.sqrt(np.maximum(m2 / count, 0.0)) if count > 0 else np.ones_like(mean)
    )
    return mean.astype(np.float32), np.maximum(std, eps).astype(np.float32)


def load_numpy_policy(bundle_dir: str) -> NumpyPolicy:
    """Load a serving bundle into a :class:`NumpyPolicy` without JAX.

    Raises ``ValueError`` on pixel bundles, unsupported layer counts,
    leaf-count/shape mismatches, or a bundle-version skew — the same
    fail-loudly contract as ``serve.bundle.load_bundle``.
    """
    meta_path = os.path.join(bundle_dir, META_FILE)
    mtime = os.stat(meta_path).st_mtime
    with open(meta_path) as f:
        doc = json.load(f)
    if doc.get("bundle_version") != BUNDLE_VERSION:
        raise ValueError(
            f"bundle_version {doc.get('bundle_version')!r} unsupported "
            f"(this code reads {BUNDLE_VERSION})"
        )
    agent = doc["agent"]
    if agent.get("pixel_shape"):
        raise ValueError(
            "pixel bundles (conv encoder) are not supported by the fleet "
            "actor's numpy policy; fleet hosts serve flat observations only"
        )
    obs_dim = int(agent["obs_dim"])
    action_dim = int(agent["action_dim"])
    hidden = [int(h) for h in agent.get("hidden_sizes", (256, 256, 256))]
    if len(hidden) > 9:
        # tree_flatten sorts layer names as STRINGS; hidden_10 would sort
        # before hidden_2 and scramble the leaf order this loader assumes.
        raise ValueError(
            f"{len(hidden)} hidden layers: the numpy loader supports at "
            "most 9 (flax name-sort order becomes ambiguous past that)"
        )
    with np.load(os.path.join(bundle_dir, PARAMS_FILE)) as z:
        leaves = [z[k] for k in sorted(z.files)]
    widths = hidden + [action_dim]
    if len(leaves) != 2 * len(widths):
        raise ValueError(
            f"bundle has {len(leaves)} param leaves, config implies "
            f"{2 * len(widths)} (MLP {hidden} → {action_dim})"
        )
    layers: List[Tuple[np.ndarray, np.ndarray]] = []
    prev = obs_dim
    for i, width in enumerate(widths):
        bias, kernel = leaves[2 * i], leaves[2 * i + 1]
        if bias.shape != (width,) or kernel.shape != (prev, width):
            raise ValueError(
                f"layer {i}: bundle leaves are bias{bias.shape} / "
                f"kernel{kernel.shape}, config implies bias({width},) / "
                f"kernel({prev}, {width}) — config/params mismatch"
            )
        layers.append(
            (np.asarray(kernel, np.float32), np.asarray(bias, np.float32))
        )
        prev = width
    meta = doc.get("meta") or {}
    return NumpyPolicy(
        layers=layers,
        obs_dim=obs_dim,
        action_dim=action_dim,
        n_step=int(agent.get("n_step", 1)),
        gamma=float(agent.get("gamma", 0.99)),
        env=meta.get("env"),
        generation=int(meta.get("generation", 0)),
        obs_norm=_derive_obs_norm(doc.get("obs_norm"), obs_dim),
        mtime=mtime,
        path=os.path.abspath(bundle_dir),
    )


def bundle_meta_mtime(bundle_dir: str) -> Optional[float]:
    """mtime of ``bundle.json`` (the hot-swap watch key — the exporter
    moves it into place LAST); None when absent."""
    try:
        return os.stat(os.path.join(bundle_dir, META_FILE)).st_mtime
    except FileNotFoundError:
        return None
