"""NumPy-only policy evaluation from a serving bundle.

The fleet actor host's contract is that its hot path NEVER imports JAX —
an actor host is a cheap CPU box running gymnasium + numpy, and pulling
the JAX runtime there costs memory, import seconds, and (on spawn'd
children) outright unsafety. So instead of ``serve.bundle.load_bundle``
(whose param restore goes through ``jax.tree_util``), this module reads
the SAME bundle directory with numpy + stdlib only:

- ``bundle.json`` is plain JSON (config, bounds, obs-norm stats, meta);
- ``actor_params.npz`` stores the actor leaves under zero-padded
  ``leaf_%05d`` keys in ``tree_flatten`` order. For the MLP actor that
  order is fully determined: flax dict keys flatten sorted, so leaves
  arrive as ``(bias, kernel)`` pairs per layer, layers in name order
  (``hidden_0 < hidden_1 < … < out``). The loader re-derives the layer
  structure from the declared ``hidden_sizes`` and validates every leaf
  shape against the chain — a scrambled order or a config/params
  mismatch is a hard load error, never a silently-garbage policy.

Pixel bundles (conv encoder) load too (ISSUE 13 — the fleet's pixel
cell): the DrQ-style encoder (4× conv3x3 SAME, stride 2 then 1, relu;
Dense(embed) → LayerNorm → tanh) is reimplemented in numpy with an
im2col matmul per layer, parity-tested against the jitted actor. A
48×48×2 forward is a few MXU-free milliseconds per batched act — actor
hosts run env-rate, not serving-rate, so numpy is plenty.

The forward is the exact acting-time data path the server runs —
normalize → [conv-encode] → MLP(relu) → tanh — in float32 numpy. Parity
with the jitted ``act_deterministic`` is tested to ~1e-5 (XLA may
reassociate float reductions; exploration noise dwarfs that).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

# serve/bundle.py's layout constants, restated: importing that module pulls
# D4PGConfig (and with it the JAX runtime) at top level, which this module
# must never do. tests/test_fleet.py pins the two copies equal.
BUNDLE_VERSION = 1
PARAMS_FILE = "actor_params.npz"
META_FILE = "bundle.json"


def _conv2d_same(x: np.ndarray, kernel: np.ndarray, bias: np.ndarray,
                 stride: int) -> np.ndarray:
    """NHWC conv with SAME padding via im2col matmul — flax ``nn.Conv``'s
    exact arithmetic (patch order (kh, kw, C) matches the kernel's
    row-major flatten)."""
    n, h, w, c = x.shape
    kh, kw, _, f = kernel.shape
    out_h, out_w = -(-h // stride), -(-w // stride)
    pad_h = max((out_h - 1) * stride + kh - h, 0)
    pad_w = max((out_w - 1) * stride + kw - w, 0)
    x = np.pad(
        x,
        ((0, 0), (pad_h // 2, pad_h - pad_h // 2),
         (pad_w // 2, pad_w - pad_w // 2), (0, 0)),
    )
    cols = np.empty((n, out_h, out_w, kh * kw * c), np.float32)
    for i in range(kh):
        for j in range(kw):
            cols[..., (i * kw + j) * c:(i * kw + j + 1) * c] = x[
                :, i:i + out_h * stride:stride, j:j + out_w * stride:stride, :
            ]
    return cols @ kernel.reshape(kh * kw * c, f) + bias


class _NumpyPixelEncoder:
    """models/encoders.py:PixelEncoder in numpy: conv3x3 SAME (stride 2
    then 1) + relu ×4, flatten, Dense(embed), LayerNorm(eps=1e-6), tanh."""

    def __init__(self, convs, dense, layer_norm, pixel_shape):
        self._convs = convs              # [(kernel [3,3,in,out], bias)]
        self._dense = dense              # (kernel, bias)
        self._ln = layer_norm            # (bias, scale)
        self.pixel_shape = tuple(pixel_shape)

    def __call__(self, flat: np.ndarray) -> np.ndarray:
        x = np.asarray(flat, np.float32).reshape(
            (-1,) + self.pixel_shape
        )
        for i, (kernel, bias) in enumerate(self._convs):
            x = _conv2d_same(x, kernel, bias, stride=2 if i == 0 else 1)
            np.maximum(x, 0.0, out=x)
        x = x.reshape(x.shape[0], -1)
        dk, db = self._dense
        x = x @ dk + db
        lb, ls = self._ln
        mean = x.mean(axis=-1, keepdims=True)
        var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
        x = (x - mean) / np.sqrt(var + 1e-6) * ls + lb
        return np.tanh(x)


class NumpyPolicy:
    """A loaded bundle evaluated in numpy. ``act`` maps ``[N, obs_dim]``
    observations to canonical (−1, 1) actions — the space host envs step
    in (``GymAdapter`` applies the affine to env bounds itself, so the
    bundle's bounds are carried for provenance, not applied here)."""

    def __init__(
        self,
        *,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        obs_dim: int,
        action_dim: int,
        n_step: int,
        gamma: float,
        env: Optional[str],
        generation: int,
        obs_norm: Optional[Tuple[np.ndarray, np.ndarray]],
        obs_clip: float = 5.0,
        mtime: Optional[float] = None,
        path: Optional[str] = None,
        encoder: Optional[_NumpyPixelEncoder] = None,
        stats_generation: int = 0,
    ):
        self._layers = layers            # [(kernel [in, out], bias [out])]
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.n_step = n_step
        self.gamma = gamma
        self.env = env
        self.generation = generation
        self._obs_norm = obs_norm        # (mean_f32, std_f32_floored) | None
        self._obs_clip = obs_clip
        self.mtime = mtime               # bundle.json mtime at load
        self.path = path
        self._encoder = encoder          # pixel bundles only
        # Which published statistics these acting-time obs-norm params
        # came from (bundle meta.stats_generation) — stamped onto every
        # emitted window so ingest can age out stale-stats experience.
        self.stats_generation = int(stats_generation)

    @property
    def pixel_shape(self) -> Optional[Tuple[int, ...]]:
        return None if self._encoder is None else self._encoder.pixel_shape

    @property
    def has_obs_norm(self) -> bool:
        return self._obs_norm is not None

    def retain_stats_from(self, old: "NumpyPolicy") -> None:
        """Chaos ``stale_stats`` support: adopt THIS bundle's params but
        keep acting on ``old``'s normalizer statistics AND their
        generation — the windows then honestly advertise the stale stats
        they were produced under, and ingest ages them out."""
        self._obs_norm = old._obs_norm
        self.stats_generation = old.stats_generation

    def act(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic forward: ``[N, obs_dim]`` → ``[N, action_dim]``
        in (−1, 1)."""
        x = np.asarray(obs, np.float32)
        if self._obs_norm is not None:
            mean, std = self._obs_norm
            x = np.clip((x - mean) / std, -self._obs_clip, self._obs_clip)
        if self._encoder is not None:
            x = self._encoder(x)
        last = len(self._layers) - 1
        for i, (kernel, bias) in enumerate(self._layers):
            x = x @ kernel + bias
            if i < last:
                np.maximum(x, 0.0, out=x)  # relu
        return np.tanh(x)


def _derive_obs_norm(
    stats: Optional[dict], obs_dim: int, eps: float = 1e-2
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(mean_f32, std_f32 floored at eps) from persisted Welford stats —
    the same derivation the serve batcher and RunningObsNorm apply."""
    if stats is None:
        return None
    count = float(stats["count"])
    mean = np.asarray(stats["mean"], np.float64)
    if mean.shape != (obs_dim,):
        raise ValueError(
            f"obs_norm stats are {mean.shape}-shaped, obs_dim is {obs_dim}"
        )
    m2 = np.asarray(stats["m2"], np.float64)
    std = (
        np.sqrt(np.maximum(m2 / count, 0.0)) if count > 0 else np.ones_like(mean)
    )
    return mean.astype(np.float32), np.maximum(std, eps).astype(np.float32)


def load_numpy_policy(bundle_dir: str) -> NumpyPolicy:
    """Load a serving bundle into a :class:`NumpyPolicy` without JAX.

    Raises ``ValueError`` on pixel bundles, unsupported layer counts,
    leaf-count/shape mismatches, or a bundle-version skew — the same
    fail-loudly contract as ``serve.bundle.load_bundle``.
    """
    meta_path = os.path.join(bundle_dir, META_FILE)
    mtime = os.stat(meta_path).st_mtime
    with open(meta_path) as f:
        doc = json.load(f)
    if doc.get("bundle_version") != BUNDLE_VERSION:
        raise ValueError(
            f"bundle_version {doc.get('bundle_version')!r} unsupported "
            f"(this code reads {BUNDLE_VERSION})"
        )
    agent = doc["agent"]
    obs_dim = int(agent["obs_dim"])
    action_dim = int(agent["action_dim"])
    pixel_shape = tuple(agent["pixel_shape"]) if agent.get("pixel_shape") \
        else None
    hidden = [int(h) for h in agent.get("hidden_sizes", (256, 256, 256))]
    if len(hidden) > 9:
        # tree_flatten sorts layer names as STRINGS; hidden_10 would sort
        # before hidden_2 and scramble the leaf order this loader assumes.
        raise ValueError(
            f"{len(hidden)} hidden layers: the numpy loader supports at "
            "most 9 (flax name-sort order becomes ambiguous past that)"
        )
    with np.load(os.path.join(bundle_dir, PARAMS_FILE)) as z:
        leaves = [z[k] for k in sorted(z.files)]
    encoder = None
    trunk_in = obs_dim
    if pixel_shape is not None:
        # The conv encoder's leaves sort FIRST ('PixelEncoder_0' <
        # 'hidden_0' < 'out'), within it 'Conv_*' < 'Dense_0' <
        # 'LayerNorm_0', (bias, kernel)/(bias, scale) per layer — fully
        # determined, every leaf shape validated against the declared
        # architecture (features are the encoder's fixed defaults).
        features = (32, 32, 32, 32)
        embed = int(agent.get("encoder_embed_dim", 50))
        n_enc = 2 * len(features) + 4  # convs + Dense + LayerNorm
        if len(leaves) < n_enc:
            raise ValueError(
                f"pixel bundle has {len(leaves)} param leaves, the conv "
                f"encoder alone needs {n_enc} — config/params mismatch"
            )
        enc_leaves, leaves = leaves[:n_enc], leaves[n_enc:]
        h, w, c = pixel_shape
        convs = []
        prev_c = c
        for i, feat in enumerate(features):
            bias, kernel = enc_leaves[2 * i], enc_leaves[2 * i + 1]
            if bias.shape != (feat,) or kernel.shape != (3, 3, prev_c, feat):
                raise ValueError(
                    f"encoder conv {i}: bundle leaves are bias{bias.shape}"
                    f" / kernel{kernel.shape}, config implies bias({feat},)"
                    f" / kernel(3, 3, {prev_c}, {feat})"
                )
            convs.append((np.asarray(kernel, np.float32),
                          np.asarray(bias, np.float32)))
            prev_c = feat
        flat = -(-h // 2) * -(-w // 2) * features[-1]
        d_bias, d_kernel = enc_leaves[8], enc_leaves[9]
        ln_bias, ln_scale = enc_leaves[10], enc_leaves[11]
        if d_bias.shape != (embed,) or d_kernel.shape != (flat, embed):
            raise ValueError(
                f"encoder dense: bundle leaves are bias{d_bias.shape} / "
                f"kernel{d_kernel.shape}, config implies bias({embed},) / "
                f"kernel({flat}, {embed})"
            )
        if ln_bias.shape != (embed,) or ln_scale.shape != (embed,):
            raise ValueError(
                f"encoder layernorm: bundle leaves are {ln_bias.shape} / "
                f"{ln_scale.shape}, config implies ({embed},) twice"
            )
        encoder = _NumpyPixelEncoder(
            convs,
            (np.asarray(d_kernel, np.float32),
             np.asarray(d_bias, np.float32)),
            (np.asarray(ln_bias, np.float32),
             np.asarray(ln_scale, np.float32)),
            pixel_shape,
        )
        trunk_in = embed
    widths = hidden + [action_dim]
    if len(leaves) != 2 * len(widths):
        raise ValueError(
            f"bundle has {len(leaves)} trunk param leaves, config implies "
            f"{2 * len(widths)} (MLP {hidden} → {action_dim})"
        )
    layers: List[Tuple[np.ndarray, np.ndarray]] = []
    prev = trunk_in
    for i, width in enumerate(widths):
        bias, kernel = leaves[2 * i], leaves[2 * i + 1]
        if bias.shape != (width,) or kernel.shape != (prev, width):
            raise ValueError(
                f"layer {i}: bundle leaves are bias{bias.shape} / "
                f"kernel{kernel.shape}, config implies bias({width},) / "
                f"kernel({prev}, {width}) — config/params mismatch"
            )
        layers.append(
            (np.asarray(kernel, np.float32), np.asarray(bias, np.float32))
        )
        prev = width
    meta = doc.get("meta") or {}
    obs_norm_doc = doc.get("obs_norm")
    return NumpyPolicy(
        layers=layers,
        obs_dim=obs_dim,
        action_dim=action_dim,
        n_step=int(agent.get("n_step", 1)),
        gamma=float(agent.get("gamma", 0.99)),
        env=meta.get("env"),
        generation=int(meta.get("generation", 0)),
        obs_norm=_derive_obs_norm(obs_norm_doc, obs_dim),
        mtime=mtime,
        path=os.path.abspath(bundle_dir),
        encoder=encoder,
        stats_generation=(
            int(meta.get("stats_generation", meta.get("generation", 0)))
            if obs_norm_doc is not None else 0
        ),
    )


def bundle_meta_mtime(bundle_dir: str) -> Optional[float]:
    """mtime of ``bundle.json`` (the hot-swap watch key — the exporter
    moves it into place LAST); None when absent."""
    try:
        return os.stat(os.path.join(bundle_dir, META_FILE)).st_mtime
    except FileNotFoundError:
        return None
