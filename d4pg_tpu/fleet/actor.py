"""Remote actor host: env + NumPy policy → windows over the wire.

``python -m d4pg_tpu.fleet.actor --connect HOST:PORT --bundle DIR``

One process per actor host, **provably JAX-free on the hot path** (the
d4pglint ``host-jax-import`` manifest covers this module, and a tier-1
subprocess test asserts no ``jax*`` module ever loads): the policy is a
:class:`~d4pg_tpu.fleet.policy.NumpyPolicy` evaluated from a serving
bundle directory, the env is the shared host adapter
(``envs/gym_adapter.make_host_env``), and the n-step collapse is the
repo's own :class:`~d4pg_tpu.replay.nstep_writer.NStepWriter` pointed at
a local spool — so the windows that cross the wire are column-for-column
what the in-process writer path would have inserted (parity-tested).

Weight distribution IS the bundle attestation: the trainer re-exports the
bundle (params first, json second, each atomic) at every publish
interval; this host polls ``bundle.json``'s mtime and hot-swaps the whole
policy — params, obs-norm stats, and the bundle **generation** — between
env steps, exactly like the serve reload watcher. Windows are tagged with
the generation of the policy that produced them, so the ingest server can
drop stale experience with an honest count.

Failure semantics (docs/fleet.md has the full table):

- **reconnect** under the shared bounded ``utils/retry.py:Backoff``;
  **resume-safe**: frames unacknowledged at disconnect are dropped, never
  resent (at-most-once — a duplicate window silently double-weights a
  transition, a dropped one just costs a little data), and the spool of
  not-yet-sent windows survives the reconnect;
- **flow control**: at most ``max_inflight`` unacked frames (server-
  advertised in HELLO_OK); when credits run out the env loop blocks —
  collection backpressure, not unbounded buffering. While DISCONNECTED
  the bounded spool drops its oldest windows instead (a dead learner
  must not grow this host's memory without limit);
- **explicit shed**: an ``OVERLOADED(queue_full)`` ack counts the frame's
  windows shed and moves on — mirroring the serve client contract.

SIGTERM/SIGINT drain: stop stepping, flush the spool's complete windows,
wait briefly for acks, print the final counter summary, exit 0.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from d4pg_tpu.fleet import wire
from d4pg_tpu.fleet.policy import NumpyPolicy, bundle_meta_mtime, load_numpy_policy
from d4pg_tpu.replay.her import HindsightWriter
from d4pg_tpu.replay.nstep_writer import NStepWriter
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.utils.retry import Backoff
from d4pg_tpu.analysis import flowledger, lockwitness

STAT_KEYS = (
    "env_steps",
    "episodes",
    "windows_emitted",
    "windows_sent",
    "windows_acked",
    "windows_shed",
    "windows_stale",
    "windows_dropped_reconnect",
    "windows_dropped_spool",
    "frames_sent",
    "reconnects",
    "bundle_reloads",
    "generation",
)


class _Spool:
    """Bounded FIFO of complete windows, each row tagged with the bundle
    generation / stats generation / relabeled flag in force when it was
    emitted. ``add`` is the duck-typed buffer target :class:`NStepWriter`
    emits into. Single-threaded (the env loop owns it); bounded so a long
    disconnection cannot grow host memory — the oldest windows go first
    (they are the stalest anyway)."""

    def __init__(self, limit: int):
        self.limit = int(limit)
        self.rows: deque = deque()
        self.dropped = 0
        self.generation = 0        # stamped by the actor at every policy swap
        self.stats_generation = 0  # stamped at every STATS swap (obs-norm)
        self.relabeled = False     # toggled by the HER writer factory

    def add(self, obs, action, reward, next_obs, discount) -> None:
        if len(self.rows) >= self.limit:
            self.rows.popleft()
            self.dropped += 1
        self.rows.append(
            (
                (self.generation, self.stats_generation, self.relabeled),
                np.asarray(obs, np.float32),
                np.asarray(action, np.float32),
                float(reward),
                np.asarray(next_obs, np.float32),
                float(discount),
            )
        )

    def __len__(self) -> int:
        return len(self.rows)

    def take_frame(self, max_rows: int):
        """Pop the longest same-tag prefix up to ``max_rows`` →
        ``(tag, columns)`` with ``tag = (generation, stats_generation,
        relabeled)``, or None when empty. Same-tag so a frame's single
        header stays honest across a mid-spool policy/stats swap or an
        original→relabeled phase flip."""
        if not self.rows:
            return None
        tag = self.rows[0][0]
        rows = []
        while self.rows and len(rows) < max_rows and self.rows[0][0] == tag:
            rows.append(self.rows.popleft())
        return tag, {
            "obs": np.stack([r[1] for r in rows]),
            "action": np.stack([r[2] for r in rows]),
            "reward": np.asarray([r[3] for r in rows], np.float32),
            "next_obs": np.stack([r[4] for r in rows]),
            "discount": np.asarray([r[5] for r in rows], np.float32),
        }


class _HerWriterFactory:
    """The ``writer_factory`` the repo's own :class:`HindsightWriter`
    calls once for the ORIGINAL trajectory pass and once per relabel
    pass: the first call per episode flush marks spooled windows
    original, every later one marks them relabeled — how the wire knows
    which windows may fold obs-norm statistics. Reset per episode by
    :meth:`FleetActor._her_flush`."""

    def __init__(self, spool: _Spool, n_step: int, gamma: float):
        self.spool = spool
        self.n_step = n_step
        self.gamma = gamma
        self.calls = 0

    def __call__(self) -> NStepWriter:
        self.calls += 1
        self.spool.relabeled = self.calls > 1
        return NStepWriter(self.spool, self.n_step, self.gamma)


class FleetLink:
    """One connection to the ingest server: synchronous HELLO handshake,
    then pipelined WINDOWS frames acked on a reader thread, bounded by the
    server-advertised in-flight window."""

    # d4pglint shared-mutable-state: single transition None→exception by
    # the reader thread; senders check-then-fail (PolicyClient pattern)
    _THREAD_SAFE = ("_dead",)

    def __init__(
        self,
        host: str,
        port: int,
        hello: dict,
        *,
        on_ack,
        connect_timeout_s: float = 10.0,
    ):
        import socket

        self._on_ack = on_ack  # (kind, n) kind ∈ accepted|stale|shed|dropped
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            protocol.write_frame(
                self._sock, protocol.HELLO, 0, wire.encode_hello(**hello)
            )
            frame = protocol.read_frame(self._sock)  # timeout still armed
            if frame is None:
                raise ProtocolError("server closed during handshake")
            msg_type, _req_id, payload = frame
            if msg_type == protocol.ERROR:
                # Structured refusals (capability/dims mismatch) surface
                # their machine-readable gap codes; plain-text errors
                # (old servers, non-handshake failures) pass through raw.
                refusal = wire.decode_refusal(payload)
                if refusal is not None:
                    codes = ",".join(
                        g.get("code", "?") for g in refusal.get("gaps", ())
                    )
                    raise RuntimeError(
                        f"ingest refused handshake"
                        f"{f' [{codes}]' if codes else ''}: "
                        f"{refusal.get('message', '')}"
                    )
                raise RuntimeError(
                    f"ingest refused handshake: {payload.decode('utf-8', 'replace')}"
                )
            if msg_type != protocol.HELLO_OK:
                raise ProtocolError(f"unexpected handshake reply {msg_type}")
            ok = wire.decode_hello_ok(payload)
        except BaseException:
            self._sock.close()
            raise
        self.server_generation = int(ok["generation"])
        self.max_windows = int(ok["max_windows_per_frame"])
        self.max_inflight = int(ok["max_inflight"])
        # Negotiated capability set (None against a pre-ISSUE-13 server,
        # which replies without caps): the frame kind every send uses.
        self.caps: Optional[dict] = ok.get("caps")
        self.server_stats_generation = int(ok.get("stats_generation", 0))
        self.obs_mode = (self.caps or {}).get("obs_mode", "f32")
        # WINDOWS2 only where its header matters (non-f32 rows or stats
        # tagging): plain f32 no-stats traffic stays on the v1 WINDOWS
        # frame, byte-identical to a pre-capability actor's.
        self._use_v2 = (
            self.obs_mode != "f32" or bool((self.caps or {}).get("obs_norm"))
        )
        # Reader blocks between acks indefinitely — the handshake timeout
        # must not kill an idle-but-healthy connection.
        self._sock.settimeout(None)
        self._rfile = self._sock.makefile("rb")
        self._credits = threading.Semaphore(self.max_inflight)
        self._pending: dict = {}  # req_id -> window count
        self._pending_lock = lockwitness.named_lock(
            "FleetLink._pending_lock"
        )
        self._next_id = 0
        self._dead: Optional[Exception] = None
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="fleet-link-reader", daemon=True
        )
        self._reader.start()

    @property
    def dead(self) -> Optional[Exception]:
        return self._dead

    def acquire_credit(self, timeout: float) -> bool:
        """Flow control: returns once an in-flight slot frees (True) or the
        timeout lapses / the link died (False)."""
        if self._dead is not None:
            return False
        return self._credits.acquire(timeout=timeout)

    def release_credit(self) -> None:
        """Hand back an acquired-but-unused credit (nothing was sent)."""
        self._credits.release()

    def send_windows(self, tag, cols: dict, truncate: bool = False) -> int:
        """Ship one frame (caller holds a credit). ``tag`` is the spool's
        ``(generation, stats_generation, relabeled)`` triple. Returns its
        window count; raises OSError on a dead/broken socket. Drop
        accounting for a failed send lives HERE, exactly once: either
        this thread pops the pending entry (and counts it), or the
        reader's death sweep already did — never both.

        ``truncate`` is the ``pixel_truncate`` chaos fault: the header
        declares the full payload, the body stops halfway, and the socket
        is abortively closed — the mid-``sendall`` death shape. The
        server must whole-drop the torn frame (ProtocolError inside
        read_frame), and this side accounts the windows dropped through
        the normal failed-send path."""
        generation, stats_gen, relabeled = tag
        n = len(cols["reward"])
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            req_id = self._next_id
            self._pending[req_id] = n
        if self._dead is not None:
            self._fail_send(req_id)
            raise OSError("link is dead")
        if self._use_v2:
            msg_type = protocol.WINDOWS2
            payload = wire.encode_windows2(
                generation,
                stats_gen,
                self.obs_mode,
                relabeled,
                cols["obs"],
                cols["action"],
                cols["reward"],
                cols["next_obs"],
                cols["discount"],
            )
        else:
            msg_type = protocol.WINDOWS
            payload = wire.encode_windows(
                generation,
                cols["obs"],
                cols["action"],
                cols["reward"],
                cols["next_obs"],
                cols["discount"],
            )
        try:
            if truncate:
                protocol.write_truncated_frame(
                    self._sock, msg_type, req_id, payload, len(payload) // 2
                )
                protocol.abortive_close(self._sock)
                raise OSError("chaos: frame truncated mid-stream")
            protocol.write_frame(self._sock, msg_type, req_id, payload)
        except OSError:
            self._fail_send(req_id)
            raise
        return n

    def _fail_send(self, req_id: int) -> None:
        """A registered frame never made it out: count its windows dropped
        — unless the reader's death sweep got there first (pop tells us)."""
        with self._pending_lock:
            n = self._pending.pop(req_id, None)
        if n is not None:
            self._on_ack("dropped", n)

    def _read_loop(self) -> None:
        err: Exception = ConnectionError("server closed the connection")
        try:
            while True:
                frame = protocol.read_frame(self._rfile)
                if frame is None:
                    break
                msg_type, req_id, payload = frame
                with self._pending_lock:
                    n = self._pending.pop(req_id, None)
                if n is None:
                    if msg_type == protocol.ERROR:
                        err = RuntimeError(
                            payload.decode("utf-8", "replace")
                        )
                        break
                    continue
                if msg_type == protocol.WINDOWS_OK:
                    accepted, stale = wire.decode_windows_ok(payload)
                    if accepted:
                        self._on_ack("accepted", accepted)
                    if stale:
                        self._on_ack("stale", stale)
                elif msg_type == protocol.OVERLOADED:
                    self._on_ack("shed", n)  # explicit queue_full shed
                elif msg_type == protocol.ERROR:
                    # the frame died server-side with the connection
                    self._on_ack("dropped", n)
                    err = RuntimeError(payload.decode("utf-8", "replace"))
                    break
                else:
                    # An unexpected reply type for a KNOWN req_id: its
                    # pending entry is already popped, so without this
                    # branch the frame's windows would vanish from the
                    # emitted==accounted identity (the zero-torn-windows
                    # contract). Count them dropped and kill the link —
                    # a peer speaking unexpected types is not one to
                    # trust with framing.
                    self._on_ack("dropped", n)
                    raise ProtocolError(f"unexpected reply type {msg_type}")
                self._credits.release()
        except (OSError, ProtocolError) as e:
            if not self._closed:
                err = ConnectionError(str(e))
        finally:
            # mark dead FIRST, then sweep: a racing send either lands in
            # the swept dict (counted dropped here) or sees _dead after
            # registering and fails itself
            self._dead = err
            with self._pending_lock:
                pending, self._pending = list(self._pending.values()), {}
            # in-flight at disconnect: dropped, never resent (at-most-once)
            for n in pending:
                self._on_ack("dropped", n)
                self._credits.release()

    def inflight(self) -> int:
        with self._pending_lock:
            return len(self._pending)

    def abort(self) -> None:
        """Abortive close (chaos ``reconnect_flap``): RST the server so
        both sides see the flap."""
        protocol.abortive_close(self._sock)
        self.close()

    def close(self) -> None:
        import socket

        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5)
        try:
            self._rfile.close()
        except OSError:
            pass


class FleetActor:
    """The env + policy + uplink loop. Construct, then :meth:`run`."""

    def __init__(
        self,
        *,
        connect: str,
        bundle_dir: str,
        env_id: Optional[str] = None,
        num_envs: int = 1,
        seed: int = 0,
        noise_sigma: float = 0.3,
        batch_windows: int = 16,
        spool_limit: int = 1024,
        poll_interval_s: float = 2.0,
        max_env_steps: int = 0,
        stats_interval_s: float = 10.0,
        reconnect_attempts: int = 60,
        connect_timeout_s: float = 10.0,
        stop_event: Optional[threading.Event] = None,
        chaos=None,
        actor_id: Optional[str] = None,
        her: bool = False,
        her_k: int = 4,
        variant: int = 0,
    ):
        host, _, port = connect.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"--connect must be HOST:PORT, got {connect!r}")
        self.host, self.port = host, int(port)
        self.bundle_dir = bundle_dir
        self.policy: NumpyPolicy = load_numpy_policy(bundle_dir)
        self.env_id = env_id or self.policy.env
        if not self.env_id:
            raise ValueError(
                "bundle carries no env id; pass --env explicitly"
            )
        self.num_envs = int(num_envs)
        if self.num_envs < 1:
            raise ValueError(
                f"--num-envs must be >= 1, got {num_envs} (a fleet actor "
                "host exists to run envs; 0 envs is the learner-side "
                "train.py --num-envs 0 flag, not this one)"
            )
        self.seed = int(seed)
        self.noise_sigma = float(noise_sigma)
        self.batch_windows = int(batch_windows)
        self.poll_interval_s = float(poll_interval_s)
        self.max_env_steps = int(max_env_steps)
        self.stats_interval_s = float(stats_interval_s)
        self.reconnect_attempts = int(reconnect_attempts)
        self.connect_timeout_s = float(connect_timeout_s)
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._chaos = chaos
        self.actor_id = actor_id or f"{self.env_id}-actor"
        self.her = bool(her)
        self.her_k = int(her_k)
        # League variant assignment (ISSUE 15): declared in the HELLO
        # caps; the ingest refuses a mismatch (variant_mismatch), and for
        # a non-default assignment the HELLO_OK echo is verified too — a
        # mis-wired port (a pre-variant learner behind it) must fail
        # loudly, not silently feed the wrong population member.
        self.variant = int(variant)
        self._rng = np.random.default_rng(seed)
        self.spool = _Spool(spool_limit)
        self.spool.generation = self.policy.generation
        self.spool.stats_generation = self.policy.stats_generation
        self._bundle_mtime = self.policy.mtime
        self._link: Optional[FleetLink] = None
        # Paced-reconnect state: while disconnected the env loop keeps
        # collecting (the bounded spool absorbs) and _ensure_link makes at
        # most one non-blocking attempt whenever _retry_at has passed.
        self._backoff: Optional[Backoff] = None
        self._retry_at = 0.0
        self._stats = dict.fromkeys(STAT_KEYS, 0)
        self._stats["generation"] = self.policy.generation
        # reader thread acks vs main
        self._stats_lock = lockwitness.named_lock("FleetActor._stats_lock")

        from d4pg_tpu.envs.gym_adapter import make_host_env

        self.envs = [make_host_env(self.env_id) for _ in range(self.num_envs)]
        if self.her:
            # Actor-side HER (ISSUE 13): the repo's OWN HindsightWriter
            # relabels on this host, pointed at the spool through a
            # factory that tags original vs relabeled passes — so the
            # windows that cross the wire are column-for-column what the
            # learner-side HER path would have inserted (the seeded
            # parity oracle pins it).
            for env in self.envs:
                if not getattr(env, "is_goal_env", False) or not hasattr(
                    env, "compute_reward"
                ):
                    raise ValueError(
                        f"--her needs a goal-dict env; {self.env_id!r} "
                        "is not one"
                    )
            self._her_factories = [
                _HerWriterFactory(
                    self.spool, self.policy.n_step, self.policy.gamma
                )
                for _ in range(self.num_envs)
            ]
            self.writers = [
                HindsightWriter(
                    writer_factory=self._her_factories[i],
                    compute_reward=self.envs[i].compute_reward,
                    k_future=self.her_k,
                    rng=np.random.default_rng(self.seed + 7000 + i),
                )
                for i in range(self.num_envs)
            ]
        else:
            self.writers = [
                NStepWriter(self.spool, self.policy.n_step, self.policy.gamma)
                for _ in range(self.num_envs)
            ]
        self._obs = np.stack(
            [
                env.reset(seed=self.seed + 1000 * i)
                for i, env in enumerate(self.envs)
            ]
        ).astype(np.float32)
        if self.her:
            # goal views for the relabeler: (observation, achieved,
            # desired) dict BEFORE each step, refreshed after
            self._goal_prev = [
                self._goal_view(env) for env in self.envs
            ]
            # Per-env (generation, stats_generation) captured at EPISODE
            # START: HER buffers a whole episode before anything reaches
            # the spool, so a mid-episode bundle hot-swap must not
            # re-stamp already-acted experience as fresh — the flush
            # tags the whole episode with the generation in force when
            # it BEGAN (the conservative direction: ingest may drop a
            # partially-fresh episode as stale, never accept stale
            # windows as fresh).
            self._her_episode_tag = [
                (self.policy.generation, self.policy.stats_generation)
                for _ in range(self.num_envs)
            ]
        if self._obs.shape[1] != self.policy.obs_dim:
            raise ValueError(
                f"env {self.env_id!r} observations are "
                f"{self._obs.shape[1]}-dim, bundle policy expects "
                f"{self.policy.obs_dim}"
            )

    # ---------------------------------------------------------------- stats
    def _inc(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += n

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["windows_dropped_spool"] = self.spool.dropped
        out["spool_depth"] = len(self.spool)
        return out

    def _on_ack(self, kind: str, n: int) -> None:
        self._inc(
            {
                "accepted": "windows_acked",
                "stale": "windows_stale",
                "shed": "windows_shed",
                "dropped": "windows_dropped_reconnect",
            }[kind],
            n,
        )

    def request_stop(self) -> None:
        """Signal-safe: just set the event (install_graceful_signals)."""
        self._stop.set()

    # ----------------------------------------------------------------- link
    def _hello(self) -> dict:
        """The HELLO handshake payload — single source for every connect
        path (_ensure_link and the drain reconnect) so the two can never
        drift on a field. The ``caps`` vector states what this host CAN
        produce; the server picks from it or refuses with a structured
        reason (replay/source.py:negotiate_fleet)."""
        obs_modes = ["f32", "u8"]
        try:
            # Advertise bf16 only when this host can actually encode it:
            # ml_dtypes is a lazy extra (f32/u8 hosts never need it), and
            # negotiating a mode we then crash on at the first send is
            # exactly the mis-deployment the handshake exists to refuse.
            import ml_dtypes  # noqa: F401

            obs_modes.append("bf16")
        except ImportError:
            pass
        return dict(
            actor_id=self.actor_id,
            env=self.env_id,
            obs_dim=self.policy.obs_dim,
            action_dim=self.policy.action_dim,
            n_step=self.policy.n_step,
            gamma=self.policy.gamma,
            generation=self.policy.generation,
            caps=dict(
                wire=2,
                obs_modes=obs_modes,
                her=self.her,
                obs_norm=self.policy.has_obs_norm,
                variant=self.variant,
            ),
        )

    def _check_negotiated(self, link: FleetLink) -> None:
        """A pre-ISSUE-13 server replies without caps: fine for plain f32
        traffic, fatal when this host's config NEEDS the capability wire
        (HER tagging, stats generations, non-f32 rows)."""
        if link.caps is None and (
            self.her
            or self.policy.has_obs_norm
            or self.policy.pixel_shape is not None
            or self.variant != 0
        ):
            raise RuntimeError(
                "ingest server does not speak capability negotiation "
                "(pre-ISSUE-13 learner) but this actor needs it "
                f"(her={self.her}, obs_norm={self.policy.has_obs_norm}, "
                f"pixel={self.policy.pixel_shape is not None}, "
                f"variant={self.variant})"
            )
        if self.variant != 0 and link.caps is not None:
            echoed = int(link.caps.get("variant", 0))
            if echoed != self.variant:
                # Config skew, fatal and unretried: the port answers but a
                # DIFFERENT league variant is behind it (a pre-variant
                # learner echoes 0). Streaming on would contaminate that
                # variant's replay with another policy's experience.
                raise RuntimeError(
                    f"ingest server is league variant {echoed}, this "
                    f"actor is assigned variant {self.variant} — wrong "
                    "port (the league controller re-points actors when "
                    "a slot's variant is replaced)"
                )

    def _ensure_link(self) -> bool:
        """Connected, or ONE non-blocking paced reconnect attempt under the
        bounded Backoff schedule. False while disconnected — the caller's
        env loop keeps collecting and the bounded spool absorbs (dropping
        its oldest past the limit: windows_dropped_spool) instead of this
        host blocking through the whole reconnect budget. Raises
        RuntimeError once the attempt budget is spent."""
        if self._link is not None and self._link.dead is None:
            return True
        if self._link is not None:
            self._link.close()  # sweeps unacked → windows_dropped_reconnect
            self._link = None
            self._inc("reconnects")
        if self._backoff is None:
            self._backoff = Backoff(
                base_s=0.2,
                max_s=5.0,
                max_attempts=self.reconnect_attempts,
                rng=random.Random(self.seed),  # deterministic jitter (chaos)
            )
            self._retry_at = time.monotonic()  # first attempt is free
        if self._stop.is_set() or time.monotonic() < self._retry_at:
            return False
        try:
            link = FleetLink(
                self.host,
                self.port,
                self._hello(),
                on_ack=self._on_ack,
                connect_timeout_s=self.connect_timeout_s,
            )
        except (OSError, ProtocolError) as e:
            return self._retry_later(e)
        self._check_negotiated(link)  # fatal, not retried: config skew
        if self._chaos is not None:
            e = self._chaos.tick("reconnect_flap")
            if e is not None:
                # Injected flap: abortive close right after a good
                # handshake — the next attempt runs under the same
                # (reset-on-success is NOT reached) backoff schedule.
                link.abort()
                self._inc("reconnects")
                return self._retry_later(RuntimeError("chaos reconnect_flap"))
        self._backoff = None
        self._link = link
        if link.server_generation > self.policy.generation:
            # HELLO_OK just told us our bundle is already stale
            # (reconnect into a long-running learner): reload NOW
            # instead of streaming up-to-a-poll-interval of windows
            # the ingest would drop wholesale as stale.
            self._maybe_reload_bundle()
        return True

    def _retry_later(self, err: Exception) -> bool:
        delay = self._backoff.next_delay()
        if delay is None:
            raise RuntimeError(
                f"could not reach ingest server {self.host}:{self.port} "
                f"after {self.reconnect_attempts} bounded retries: {err}"
            )
        self._retry_at = time.monotonic() + delay
        return False

    def _flush_once(self, deadline: Optional[float] = None) -> bool:
        """Ship one frame from the spool. False when nothing was sent
        (empty spool, stopping, or the link died — caller re-enters).
        ``deadline`` (a ``time.monotonic`` instant) marks the drain path:
        no reconnect Backoff (a mid-drain link death means the rest
        counts dropped, never a 60-attempt budget past the 5 s bound),
        and the credit wait gives up at the deadline instead of blocking
        on a stalled server."""
        if not self.spool.rows:
            return False
        if deadline is not None:
            if self._link is None or self._link.dead is not None:
                return False
        elif not self._ensure_link():
            return False
        link = self._link
        # Flow control: block until an in-flight slot frees — this IS the
        # collection backpressure (the env loop pauses with us).
        while not link.acquire_credit(timeout=0.5):
            if link.dead is not None:
                return False
            if deadline is not None:
                # Drain path: _stop is ALWAYS set here (SIGTERM is the
                # normal drain trigger), so only the deadline may end the
                # wait — a slow-acking but live server still gets the full
                # drain budget to free a credit.
                if time.monotonic() >= deadline:
                    return False
            elif self._stop.is_set():
                return False
        frame = self.spool.take_frame(link.max_windows)
        if frame is None:
            link.release_credit()
            return False
        tag, cols = frame
        truncate = False
        if self._chaos is not None:
            e = self._chaos.tick("slow_link")
            if e is not None:
                # slow_link@N:ms — stall this send; proves the server's
                # read deadline tolerates a slow-but-live peer and flow
                # control (not queue growth) absorbs the stall.
                stall = e.arg if e.arg is not None else 100.0
                self._stop.wait(stall / 1e3)
            e = self._chaos.tick("pixel_truncate")
            if e is not None:
                # pixel_truncate@N — die mid-sendall on this frame (the
                # header promises bytes the body never delivers) and RST.
                # The server must whole-drop the torn frame; this side's
                # windows count dropped, and the normal paced reconnect
                # takes over.
                truncate = True
        try:
            n = link.send_windows(tag, cols, truncate=truncate)
        except OSError:
            # in flight at the disconnect: dropped whole (send_windows /
            # the reader's death sweep counted it — exactly one of them)
            return False
        self._inc("windows_sent", n)
        self._inc("frames_sent")
        return True

    # --------------------------------------------------------------- bundle
    def _maybe_reload_bundle(self) -> None:
        m = bundle_meta_mtime(self.bundle_dir)
        if m is None or m == self._bundle_mtime:
            return
        if self._chaos is not None:
            e = self._chaos.tick("stale_bundle")
            if e is not None:
                # Injected stale bundle: skip this swap AND advance the
                # bookmark — this host keeps acting on the old generation
                # until the NEXT export, so its windows age out server-side
                # (windows_dropped_stale_gen proves the drop path).
                self._bundle_mtime = m
                print(
                    "[fleet-actor] chaos stale_bundle: skipping hot-swap, "
                    f"staying on generation {self.policy.generation}",
                    flush=True,
                )
                return
        try:
            fresh = load_numpy_policy(self.bundle_dir)
        except (OSError, ValueError, KeyError) as e:
            # torn/malformed export: keep acting on the old policy; the
            # bookmark advances so a bad export logs once, not every poll
            self._bundle_mtime = m
            print(
                f"[fleet-actor] bundle reload failed (keeping old): {e}",
                flush=True,
            )
            return
        if self._chaos is not None and fresh.has_obs_norm:
            e = self._chaos.tick("stale_stats")
            if e is not None:
                # Injected stale STATS: adopt the fresh params (the
                # policy generation advances honestly) but keep acting on
                # the OLD normalizer statistics — emitted windows carry
                # the old stats generation, and the ingest server must
                # count + drop them (windows_dropped_stale_stats) once
                # the lag exceeds fleet_max_gen_lag.
                fresh.retain_stats_from(self.policy)
                print(
                    "[fleet-actor] chaos stale_stats: keeping stats "
                    f"generation {fresh.stats_generation} under params "
                    f"generation {fresh.generation}",
                    flush=True,
                )
        self._bundle_mtime = fresh.mtime
        self.policy = fresh
        self.spool.generation = fresh.generation
        self.spool.stats_generation = fresh.stats_generation
        with self._stats_lock:
            self._stats["generation"] = fresh.generation
            self._stats["bundle_reloads"] += 1
        print(
            f"[fleet-actor] hot-swapped bundle generation={fresh.generation}",
            flush=True,
        )

    # ------------------------------------------------------------- env loop
    @staticmethod
    def _goal_view(env) -> tuple:
        """(observation, achieved_goal, desired_goal) copies from the
        adapter's ``last_goal_obs`` — copies because the relabeler holds
        them across the whole episode."""
        g = env.last_goal_obs
        return (
            np.asarray(g["observation"], np.float32).copy(),
            np.asarray(g["achieved_goal"], np.float32).copy(),
            np.asarray(g["desired_goal"], np.float32).copy(),
        )

    def _her_flush(self, i: int, truncated: bool) -> None:
        """Episode end: relabel + flush through the repo's own
        HindsightWriter. The factory's call counter restarts so the
        original pass tags windows original, relabel passes relabeled.
        The whole flush is stamped with the EPISODE-START generation tag
        (see ``_her_episode_tag``) — then the spool returns to the live
        policy's tags for the next episode."""
        cur = (self.spool.generation, self.spool.stats_generation)
        self.spool.generation, self.spool.stats_generation = (
            self._her_episode_tag[i]
        )
        try:
            self._her_factories[i].calls = 0
            self.writers[i].end_episode(truncated=truncated)
        finally:
            self.spool.generation, self.spool.stats_generation = cur
            self.spool.relabeled = False  # next episode starts original
        self._her_episode_tag[i] = (
            self.policy.generation, self.policy.stats_generation
        )

    def _maybe_her_actor_kill(self) -> None:
        """her_actor_kill@N — SIGKILL this host on its Nth ENV STEP
        (ticked once per env per loop, so the count means env steps at
        any ``--num-envs``), mid-episode: the relabeler's buffered
        episode dies with the process, so nothing torn can ever reach
        the wire (HER windows only exist after ``end_episode``), and
        in-flight frames die under the server's torn-frame whole-drop.
        A supervisor restarts the host; the learner sees a reconnect."""
        e = self._chaos.tick("her_actor_kill")
        if e is not None:
            import signal as _signal

            print("[chaos] her_actor_kill: SIGKILL self", flush=True)
            os.kill(os.getpid(), _signal.SIGKILL)

    def _step_envs(self) -> None:
        a = self.policy.act(self._obs)
        if self.noise_sigma > 0.0:
            a = a + self.noise_sigma * self._rng.standard_normal(
                a.shape
            ).astype(np.float32)
        np.clip(a, -1.0, 1.0, out=a)
        for i, env in enumerate(self.envs):
            if self._chaos is not None:
                self._maybe_her_actor_kill()
            obs2, r, term, trunc, _info = env.step(a[i])
            if self.her:
                g_next = self._goal_view(env)
                g_prev = self._goal_prev[i]
                self.writers[i].add(
                    observation=g_prev[0],
                    achieved_goal=g_prev[1],
                    desired_goal=g_prev[2],
                    action=a[i].copy(),
                    reward=float(r),
                    next_observation=g_next[0],
                    next_achieved_goal=g_next[1],
                    terminated=bool(term),
                )
                if term or trunc:
                    self._her_flush(i, truncated=not bool(term))
                    self._obs[i] = env.reset()
                    self._goal_prev[i] = self._goal_view(env)
                    self._inc("episodes")
                else:
                    self._obs[i] = obs2
                    self._goal_prev[i] = g_next
                continue
            # .copy(): NStepWriter stores obs WITHOUT copying, and the
            # `self._obs[i] = ...` below assigns INTO this row — without
            # the copy every emitted window's obs would silently read the
            # row's FUTURE value (regression-tested)
            self.writers[i].add(
                self._obs[i].copy(), a[i], r, obs2,
                terminated=term, truncated=trunc,
            )
            if term or trunc:
                self._obs[i] = env.reset()
                self._inc("episodes")
            else:
                self._obs[i] = obs2
        self._inc("env_steps", self.num_envs)

    # ------------------------------------------------------------------ run
    def run(self) -> dict:
        """The main loop; returns the final stats dict. Blocks until
        ``max_env_steps`` (0 = until stopped) or :meth:`request_stop`."""
        next_poll = time.monotonic() + self.poll_interval_s
        next_stats = time.monotonic() + self.stats_interval_s
        try:
            while not self._stop.is_set():
                if (
                    self.max_env_steps
                    and self._stats["env_steps"] >= self.max_env_steps
                ):
                    break
                now = time.monotonic()
                if now >= next_poll:
                    self._maybe_reload_bundle()
                    next_poll = now + self.poll_interval_s
                if now >= next_stats:
                    print(f"[fleet-actor] {self.stats()}", flush=True)
                    next_stats = now + self.stats_interval_s
                before = len(self.spool) + self.spool.dropped
                self._step_envs()
                self._inc(
                    "windows_emitted",
                    (len(self.spool) + self.spool.dropped) - before,
                )
                while (
                    len(self.spool) >= self.batch_windows
                    and not self._stop.is_set()
                ):
                    if not self._flush_once():
                        break
            self._drain()
        finally:
            if self._link is not None:
                self._link.close()
                self._link = None
            for env in self.envs:
                if hasattr(env, "close"):
                    env.close()
        out = self.stats()
        print(f"[fleet-actor] drained: {out}", flush=True)
        # --debug-guards: every emitted window must be booked under
        # exactly one terminal (acked/stale/shed/dropped) or still be
        # spooled — the vanished-windows bug class, checked at exit
        flowledger.check("fleet-actor", out, where="actor drain")
        return out

    def _drain(self) -> None:
        """Best-effort final flush: ship the spool's complete windows and
        wait briefly for acks. A dead/unreachable server just means those
        windows count dropped — the drain must never hang a SIGTERM."""
        deadline = time.monotonic() + 5.0
        if self._link is None or self._link.dead is not None:
            # ONE bounded connect attempt, even when stopping (SIGTERM is
            # the normal drain path) — never the full Backoff budget,
            # which could block the exit minutes past the deadline.
            if self._link is not None:
                self._link.close()
                self._link = None
                self._inc("reconnects")
            try:
                self._link = FleetLink(
                    self.host, self.port, self._hello(),
                    on_ack=self._on_ack,
                    connect_timeout_s=min(2.0, self.connect_timeout_s),
                )
            except (OSError, ProtocolError, RuntimeError):
                return  # unreachable: whatever is spooled counts dropped
        while self.spool.rows and time.monotonic() < deadline:
            if self._link.dead is not None:
                break
            if not self._flush_once(deadline=deadline):
                break
        link = self._link
        if link is not None:
            while link.inflight() > 0 and time.monotonic() < deadline:
                if link.dead is not None:
                    break
                time.sleep(0.02)
        # anything still spooled is dropped by exit (counted implicitly via
        # spool_depth in the final stats line)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.fleet.actor", description=__doc__
    )
    p.add_argument("--connect", required=True, metavar="HOST:PORT",
                   help="learner's ingest endpoint (train.py --fleet-listen)")
    p.add_argument("--bundle", required=True,
                   help="bundle directory the trainer publishes "
                        "(--fleet-bundle); polled for hot-swaps")
    p.add_argument("--env", default=None,
                   help="host env id (default: the bundle's provenance env)")
    p.add_argument("--num-envs", type=int, default=1,
                   help="envs in this host process (one batched numpy "
                        "forward per step)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--noise-sigma", type=float, default=0.3,
                   help="gaussian exploration noise scale in canonical "
                        "(-1,1) action space (0 = deterministic)")
    p.add_argument("--batch-windows", type=int, default=16,
                   help="windows accumulated before a frame ships")
    p.add_argument("--spool-limit", type=int, default=1024,
                   help="bounded local window spool; past it the oldest "
                        "windows drop (counted) while disconnected")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="bundle.json mtime poll seconds (hot-swap cadence)")
    p.add_argument("--max-steps", type=int, default=0,
                   help="stop after this many env steps (0 = until signal)")
    p.add_argument("--stats-interval", type=float, default=10.0)
    p.add_argument("--reconnect-attempts", type=int, default=60,
                   help="bounded Backoff budget per disconnection; "
                        "exhausting it exits 1 (supervisor restarts)")
    p.add_argument("--her", action="store_true",
                   help="actor-side hindsight relabeling (goal-dict envs): "
                        "the repo's own HindsightWriter runs on THIS host "
                        "and relabeled windows ship wire-identical to "
                        "learner-side ones; the learner must run --her too "
                        "(negotiated at HELLO)")
    p.add_argument("--her-k", type=int, default=4,
                   help="relabeled copies per episode (HER 'future' k)")
    p.add_argument("--variant", type=int, default=0,
                   help="league variant id this host is ASSIGNED to "
                        "(d4pg_tpu/league): declared in the HELLO caps "
                        "and exact-matched against the learner's — a "
                        "mismatch (or a pre-variant learner behind the "
                        "port, for a non-zero assignment) is refused. "
                        "0 = default/pre-league variant")
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="deterministic fault injection (d4pg_tpu/chaos.py): "
                        "actor-side sites reconnect_flap@N, stale_bundle@N, "
                        "slow_link@N:ms, stale_stats@N, pixel_truncate@N, "
                        "her_actor_kill@N")
    p.add_argument("--debug-guards", action="store_true",
                   help="arm the runtime witnesses (lock-order, window "
                        "conservation): drain fails loudly on a lock-order "
                        "contradiction or an accounting imbalance")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.debug_guards:
        # BEFORE FleetActor() so its named locks register witnessed
        lockwitness.enable()
        flowledger.enable()
    chaos = None
    if args.chaos:
        from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

        chaos = ChaosInjector(ChaosPlan.parse(args.chaos))
    actor = FleetActor(
        connect=args.connect,
        bundle_dir=args.bundle,
        env_id=args.env,
        num_envs=args.num_envs,
        seed=args.seed,
        noise_sigma=args.noise_sigma,
        batch_windows=args.batch_windows,
        spool_limit=args.spool_limit,
        poll_interval_s=args.poll_interval,
        max_env_steps=args.max_steps,
        stats_interval_s=args.stats_interval,
        reconnect_attempts=args.reconnect_attempts,
        chaos=chaos,
        her=args.her,
        her_k=args.her_k,
        variant=args.variant,
    )
    from d4pg_tpu.utils.signals import install_graceful_signals

    install_graceful_signals(
        actor.request_stop,
        "[signal] {sig}: draining spool and exiting "
        "(second signal hard-kills)",
    )
    print(
        f"[fleet-actor] {actor.actor_id}: env={actor.env_id} "
        f"x{actor.num_envs} -> {actor.host}:{actor.port} "
        f"(bundle generation {actor.policy.generation})",
        flush=True,
    )
    try:
        actor.run()
    except RuntimeError as e:
        print(f"[fleet-actor] fatal: {e}", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
