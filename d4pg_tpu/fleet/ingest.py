"""Experience-ingest server: remote actor windows → the replay writer path.

The learner-side half of the collection fleet. Remote actor hosts
(``python -m d4pg_tpu.fleet.actor``) connect over the serve framing,
handshake with ``HELLO`` (dims / n-step / gamma validated against the
replay config — a mismatch would silently corrupt training, so it is
refused before any window lands), then stream ``WINDOWS`` frames of
COMPLETE n-step transitions. Threading shape mirrors the policy server:

- one accept thread;
- one reader thread per connection, with **deadline-bounded reads**
  (``read_timeout_s`` on the socket): a half-open peer is detected and
  closed instead of pinning a thread forever — the actor reconnects
  under its Backoff;
- one **writer thread** draining a bounded frame queue into
  ``ReplayBuffer.add_batch`` — the exact call the in-process
  ``BatchedNStepWriter`` path lands on, which is what makes fleet and
  in-process replay content identical (parity-tested).

Admission control is the serve batcher's contract, applied per frame:

- **bounded queue, explicit shed** — a full queue answers ``OVERLOADED``
  (``queue_full``) immediately; the actor counts the shed windows and
  keeps its latency honest instead of diverging;
- **generation-tagged drops** — every frame carries the bundle
  generation its windows were produced under; frames older than
  ``current − max_gen_lag`` are counted (``windows_dropped_stale_gen``)
  and discarded, never written. The trainer bumps the generation at
  every bundle publish (``--fleet-bundle`` / ``--fleet-publish-interval``);
- **torn windows never reach replay** — the actor ships only complete
  windows, frames are atomic at the protocol layer (a disconnect
  mid-frame is a ``ProtocolError``, the partial frame is dropped whole),
  and unacknowledged frames are dropped client-side on reconnect —
  mirroring the pool's ``take_dropped`` contract end to end.

``--debug-guards``: the writer thread's two rotating staging slots are
generation-tagged in the trainer's :class:`StagingLedger` (write before
fill, hold across the ``add_batch`` copy), so any future async consumer
of the staging memory trips the same reuse guard as every other rotated
slot in the repo.

Deliberately JAX-free (numpy + stdlib): constructible before any backend
decision, importable by tests that never touch a device.
"""

from __future__ import annotations

import errno
import json
import socket
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from d4pg_tpu import netio
from d4pg_tpu.analysis.ledger import NULL_LEDGER
from d4pg_tpu.fleet import wire
from d4pg_tpu.replay import source
from d4pg_tpu.replay.uniform import Transition
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.analysis import flowledger, lockwitness

# counter keys, in the order they appear in metrics rows / healthz
COUNTER_KEYS = (
    "windows_ingested",
    # ISSUE 18: the per-source split of windows_ingested — "actor"
    # connections (collection fleet) vs "mirror" connections (flywheel
    # serving tap), chosen by the HELLO ``source`` cap. Identity:
    # windows_from_actors + windows_from_mirror == windows_ingested.
    "windows_from_actors",
    "windows_from_mirror",
    "windows_dropped_stale_gen",
    # ISSUE 13: windows produced under obs-norm statistics older than the
    # allowed lag — counted and discarded exactly like stale-generation
    # ones (a mis-normalized action distribution is the same staleness
    # class as a stale policy).
    "windows_dropped_stale_stats",
    "windows_shed",
    "frames_total",
    "bytes_total",
    "connections",
    "connections_total",
    "protocol_errors",
    "handshake_refusals",
    "generation",
    "stats_generation",
)


class IngestServer:
    """Bounded-queue experience ingest in front of a replay buffer.

    ``buffer`` needs only ``add_batch(Transition)`` (uniform and PER both
    qualify); the buffer's own lock makes the write thread-safe against
    the learner's sampling and any local collection running alongside.
    """

    # d4pglint shared-mutable-state:
    # _thread_error — single transition None→exception (writer stores,
    #   check_alive readers check-then-raise);
    # _staging_flip — writer thread is the ONLY writer (single-writer-
    #   thread design; readers never touch the rotation)
    _THREAD_SAFE = ("_thread_error", "_staging_flip")
    # d4pglint thread-lifecycle: per-connection reader threads are not
    # joined — close() shuts every socket in _conns (unblocking reads at
    # once), and the read deadline (read_timeout_s) bounds the half-open
    # zombie case even without a close.
    _DETACHED_THREADS = ("fleet-ingest-conn",)

    def __init__(
        self,
        buffer,
        *,
        obs_dim: int,
        action_dim: int,
        n_step: int,
        gamma: float,
        host: str = "127.0.0.1",
        port: int = 0,
        queue_limit: int = 64,
        read_timeout_s: float = 120.0,
        max_gen_lag: int = 1,
        max_inflight: int = 8,
        caps: Optional[dict] = None,
        obs_norm=None,
        ledger=None,
        chaos=None,
    ):
        assert queue_limit >= 1 and max_inflight >= 1
        self.buffer = buffer
        self.obs_dim = int(obs_dim)
        self.action_dim = int(action_dim)
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.queue_limit = int(queue_limit)
        self.read_timeout_s = float(read_timeout_s)
        self.max_gen_lag = int(max_gen_lag)
        self.max_inflight = int(max_inflight)
        # What the learner's replay REQUIRES of actors (ISSUE 13): obs
        # wire mode, actor-side HER, generation-tagged obs-norm stats —
        # and (ISSUE 15) the league variant id this learner IS.
        # None = the pre-capability default (f32, no HER, no stats,
        # variant 0) — byte-identical v1 behavior.
        self.caps = dict(caps) if caps is not None else {
            "obs_mode": "f32", "her": False, "obs_norm": False,
        }
        self.caps.setdefault("variant", 0)
        # The ingest writer is the single statistics writer in fleet-fed
        # obs-norm runs (the seam's obs_norm_fleet_single_writer gap
        # guarantees no local collector races this): stats fold once per
        # ORIGINAL ingested window — the same once-per-observed-step
        # cadence as Trainer._ingest_obs — never per relabeled copy.
        self._obs_norm = obs_norm
        self.max_windows = wire.max_windows_per_frame(
            obs_dim, action_dim, obs_mode=self.caps["obs_mode"]
        )
        self._chaos = chaos

        # Frame queue: reader threads append decoded column dicts, the
        # writer thread drains. Bounded — admission past queue_limit sheds
        # at the reader with an explicit OVERLOADED reply.
        self._queue: deque = deque()
        # Witnessed under --debug-guards (static node ids, see lockwitness)
        self._cond = lockwitness.named_condition("IngestServer._cond")
        self._stop = False  # guarded by _cond

        # Writer staging: two rotating sets of preallocated column arrays,
        # generation-tagged in the ledger (--debug-guards). add_batch
        # copies synchronously, so the hold spans exactly the copy — the
        # discipline matters the day a consumer goes async, and the tag
        # makes a leak visible at close.
        cap = self.max_windows * 2
        self._staging = [
            {
                "obs": np.zeros((cap, obs_dim), np.float32),
                "action": np.zeros((cap, action_dim), np.float32),
                "reward": np.zeros(cap, np.float32),
                "next_obs": np.zeros((cap, obs_dim), np.float32),
                "discount": np.zeros(cap, np.float32),
            }
            for _ in range(2)
        ]
        self._staging_cap = cap
        self._staging_flip = 0  # writer-thread-only
        self._ledger = ledger if ledger is not None else NULL_LEDGER
        self._staging_group = "fleet.ingest"

        self._counters = dict.fromkeys(COUNTER_KEYS, 0)
        self._counters_lock = lockwitness.named_lock(
            "IngestServer._counters_lock"
        )

        self._listen_sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._writer_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = lockwitness.named_lock("IngestServer._conns_lock")
        self._shutdown = threading.Event()
        self._thread_error: Optional[BaseException] = None
        self._started = False

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "IngestServer":
        if self._started:
            raise RuntimeError("ingest server already started")
        self._started = True
        self._listen_sock = socket.create_server(
            (self.host, self._requested_port)
        )
        self.port = self._listen_sock.getsockname()[1]
        self._writer_thread = threading.Thread(
            target=self._writer_loop, name="fleet-ingest-writer", daemon=True
        )
        self._writer_thread.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-ingest-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain and stop: no new connections, every frame already admitted
        to the queue is written to replay, then tear down."""
        self._shutdown.set()
        if self._listen_sock is not None:
            # shutdown() + self-connect: close() alone does not wake a
            # thread blocked in accept() (same dance as PolicyServer.drain)
            try:
                self._listen_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            wake = "127.0.0.1" if self.host in ("0.0.0.0", "") else self.host
            try:
                with socket.create_connection((wake, self.port), timeout=1):
                    pass
            except OSError:
                pass
            try:
                self._listen_sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        # Reader threads block in recv with a timeout; closing their
        # sockets unblocks them immediately.
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._writer_thread is not None:
            self._writer_thread.join(timeout=timeout)
            if self._writer_thread.is_alive():
                raise RuntimeError("ingest writer thread failed to drain")
            self._writer_thread = None
        # --debug-guards: the per-source ingest split must balance once
        # the writer thread has drained the queue
        flowledger.check("fleet-ingest", self.counters(),
                         where="ingest close")

    def check_alive(self) -> None:
        if self._thread_error is not None:
            raise RuntimeError(
                "fleet ingest thread died"
            ) from self._thread_error

    # --------------------------------------------------------------- counters
    def _inc(self, key: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[key] += n

    def set_generation(self, generation: int) -> None:
        """Called by the trainer at every bundle publish: windows produced
        against generations older than ``generation − max_gen_lag`` are
        dropped from here on. Obs-norm statistics ride the same bundle, so
        the stats generation advances in lockstep (a window acted under
        stale stats is dropped via the SAME lag rule, counted apart)."""
        with self._counters_lock:
            self._counters["generation"] = int(generation)
            self._counters["stats_generation"] = int(generation)

    @property
    def generation(self) -> int:
        with self._counters_lock:
            return self._counters["generation"]

    @property
    def stats_generation(self) -> int:
        with self._counters_lock:
            return self._counters["stats_generation"]

    def counters(self) -> dict:
        """Snapshot of the fleet counters (one lock hop); the trainer
        prefixes these ``fleet_`` into every metrics.jsonl row."""
        with self._counters_lock:
            return dict(self._counters)

    # ------------------------------------------------------------ connections
    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._listen_sock.accept()
            except OSError as e:
                if self._shutdown.is_set():
                    return  # listen socket closed: draining
                if e.errno in (errno.EBADF, errno.EINVAL):
                    # the listen socket died under us WITHOUT a drain:
                    # surface it (check_alive) instead of silently never
                    # accepting again while the learner paces forever
                    self._thread_error = e
                    return
                # transient (ECONNABORTED from a client RST between SYN
                # and accept — the chaos partition/flap traffic shape —
                # or a brief EMFILE): keep accepting
                time.sleep(0.05)
                continue
            if self._shutdown.is_set():
                try:
                    conn.close()  # the close()'s own wake-up connection
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Bounded SEND for acks (netio.configure_reply_timeout — the
            # ONE place the SO_SNDTIMEO close-on-timeout guard lives for
            # thread-path endpoints; the serve/router front-ends moved
            # onto the event loop's write-progress deadline instead): an
            # actor that stops reading must not wedge this reader
            # thread's ack writes forever.
            netio.configure_reply_timeout(conn)
            # Deadline-bounded reads: a peer that stops sending (half-open
            # TCP after an actor-host power loss) is detected here instead
            # of pinning this reader thread forever. Live actors stream
            # continuously or reconnect, so a generous timeout only bounds
            # the zombie case.
            conn.settimeout(self.read_timeout_s)
            with self._conns_lock:
                self._conns.add(conn)
            self._inc("connections_total")
            self._inc("connections")
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="fleet-ingest-conn",
                daemon=True,
            ).start()

    def _handshake(self, conn, rfile) -> Optional[dict]:
        """First non-HEALTHZ frame must be a valid HELLO; reply HELLO_OK
        or ERROR. Returns the negotiated capability set when the
        connection may stream windows, None otherwise. HEALTHZ is
        answered pre-handshake so monitoring probes work the same way
        they do against the serve port (docs/fleet.md)."""
        while True:
            frame = protocol.read_frame(rfile)
            if frame is None:
                return None
            msg_type, req_id, payload = frame
            if msg_type != protocol.HEALTHZ:
                break
            protocol.write_frame(
                conn,
                protocol.HEALTHZ_OK,
                req_id,
                json.dumps(self.counters()).encode(),
            )
        if msg_type != protocol.HELLO:
            raise ProtocolError(
                f"expected HELLO as the first frame, got type {msg_type}"
            )
        # decode_hello is the single coercion point: the numeric fields
        # arrive already int/float-typed (malformed ones raised there)
        hello = wire.decode_hello(payload)
        problems = []
        if hello["obs_dim"] != self.obs_dim:
            problems.append(f"obs_dim {hello['obs_dim']} != {self.obs_dim}")
        if hello["action_dim"] != self.action_dim:
            problems.append(
                f"action_dim {hello['action_dim']} != {self.action_dim}"
            )
        if hello["n_step"] != self.n_step:
            problems.append(f"n_step {hello['n_step']} != {self.n_step}")
        if abs(hello["gamma"] - self.gamma) > 1e-9:
            problems.append(f"gamma {hello['gamma']} != {self.gamma}")
        # Capability negotiation (ISSUE 13): what used to be a CLI-level
        # refusal matrix (--fleet-listen vs --her/--obs-norm/pixels) is
        # settled per connection HERE — a caps-less HELLO negotiates as a
        # pre-capability actor, and a mismatch refuses with a structured
        # machine-readable reason, never a wrong-distribution stream.
        actor_caps = hello.get("caps") or source.LEGACY_ACTOR_CAPS
        chosen, gaps = source.negotiate_fleet(self.caps, actor_caps)
        if problems or gaps:
            # A mis-configured actor must fail loudly at connect, not
            # stream windows that silently train the wrong MDP.
            self._inc("handshake_refusals")
            protocol.write_frame(
                conn,
                protocol.ERROR,
                req_id,
                wire.encode_refusal(
                    "; ".join(
                        problems + [g.message for g in gaps]
                    ),
                    gaps,
                ),
            )
            return None
        protocol.write_frame(
            conn,
            protocol.HELLO_OK,
            req_id,
            wire.encode_hello_ok(
                generation=self.generation,
                max_windows=self.max_windows,
                max_inflight=self.max_inflight,
                # reply caps ONLY to a caps-sending actor: the v1 reply
                # stays byte-identical for pre-capability actors
                caps=chosen if hello.get("caps") is not None else None,
                stats_generation=self.stats_generation,
            ),
        )
        return chosen

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        try:
            negotiated = self._handshake(conn, rfile)
            if negotiated is None:
                return
            src = str(negotiated.get("source", "actor"))
            while True:
                frame = protocol.read_frame(rfile)
                if frame is None:
                    return  # clean EOF: actor drained and closed
                if self._chaos is not None:
                    e = self._chaos.tick("partition")
                    if e is not None:
                        # Abortive close (RST on real stacks) mid-stream:
                        # the actor sees a reset with frames in flight —
                        # exactly the network-partition fault class. Its
                        # contract: drop unacked windows, reconnect under
                        # Backoff, never resend (at-most-once).
                        protocol.abortive_close(conn)
                        raise OSError("chaos: injected partition")
                msg_type, req_id, payload = frame
                if msg_type == protocol.HEALTHZ:
                    protocol.write_frame(
                        conn,
                        protocol.HEALTHZ_OK,
                        req_id,
                        json.dumps(self.counters()).encode(),
                    )
                    continue
                if msg_type == protocol.WINDOWS:
                    # The pre-capability frame: f32 flat rows, no stats
                    # tag. Only a connection negotiated down to the plain
                    # f32/no-stats wire may speak it — a WINDOWS frame on
                    # a u8/bf16/obs-norm ingest would silently bypass the
                    # negotiated encoding, so it dies as a protocol error.
                    if self.caps["obs_mode"] != "f32" or self.caps["obs_norm"]:
                        raise ProtocolError(
                            "WINDOWS (v1) frame on a connection that "
                            f"negotiated obs_mode={self.caps['obs_mode']!r}"
                            f"/obs_norm={self.caps['obs_norm']}; speak "
                            "WINDOWS2"
                        )
                    gen, cols = wire.decode_windows(
                        payload, self.obs_dim, self.action_dim
                    )
                    stats_gen, relabeled = None, False
                elif msg_type == protocol.WINDOWS2:
                    gen, stats_gen, obs_mode, relabeled, cols = (
                        wire.decode_windows2(
                            payload, self.obs_dim, self.action_dim
                        )
                    )
                    if obs_mode != self.caps["obs_mode"]:
                        raise ProtocolError(
                            f"WINDOWS2 frame carries obs_mode={obs_mode!r}, "
                            f"connection negotiated "
                            f"{self.caps['obs_mode']!r}"
                        )
                    # The flywheel mirror's behavior-log-prob column is a
                    # GATE input (read from the mirror spool), not replay
                    # content — the ring stores Transition columns only.
                    cols.pop("logprob", None)
                else:
                    raise ProtocolError(f"unexpected message type {msg_type}")
                self._inc("frames_total")
                self._inc("bytes_total", len(payload))
                n = len(cols["reward"])
                if gen < self.generation - self.max_gen_lag:
                    # Stale-bundle drop: these windows were produced by a
                    # policy the learner has long moved past (Ape-X keeps
                    # them; SEED-RL-style on-policy-ish ingest drops them —
                    # we drop, count, and TELL the actor so it can fix its
                    # bundle sync instead of wasting uplink).
                    self._inc("windows_dropped_stale_gen", n)
                    protocol.write_frame(
                        conn,
                        protocol.WINDOWS_OK,
                        req_id,
                        wire.encode_windows_ok(0, n),
                    )
                    continue
                if (
                    self.caps["obs_norm"]
                    and stats_gen is not None
                    and stats_gen < self.stats_generation - self.max_gen_lag
                ):
                    # Stale-STATS drop (ISSUE 13): the window's actions
                    # were chosen under normalizer statistics the learner
                    # has moved past — same staleness class as a stale
                    # policy, counted apart so the two failure modes stay
                    # distinguishable in metrics/healthz.
                    self._inc("windows_dropped_stale_stats", n)
                    protocol.write_frame(
                        conn,
                        protocol.WINDOWS_OK,
                        req_id,
                        wire.encode_windows_ok(0, n),
                    )
                    continue
                # Fold obs-norm statistics once per ORIGINAL window (the
                # once-per-observed-step cadence); relabeled HER copies
                # re-observe the same step under substituted goals and
                # must not multi-count it.
                fold = bool(self.caps["obs_norm"]) and not relabeled
                with self._cond:
                    full = len(self._queue) >= self.queue_limit
                    if not full:
                        self._queue.append((cols, fold, src))
                        self._cond.notify()
                if full:
                    # Explicit shed at the bounded queue (the batcher's
                    # queue_full semantics): the learner's writer is behind;
                    # the actor sees an honest no and applies backpressure.
                    self._inc("windows_shed", n)
                    protocol.write_frame(
                        conn, protocol.OVERLOADED, req_id, b"queue_full"
                    )
                    continue
                protocol.write_frame(
                    conn,
                    protocol.WINDOWS_OK,
                    req_id,
                    wire.encode_windows_ok(n, 0),
                )
        except ProtocolError as e:
            # Malformed frame: framing is unrecoverable — ERROR once, close.
            # Any partially-received WINDOWS frame died inside read_frame,
            # so its windows never reached the queue (torn frames whole-drop).
            self._inc("protocol_errors")
            try:
                protocol.write_frame(conn, protocol.ERROR, 0, str(e).encode())
            except OSError:
                pass
        except OSError:
            pass  # peer reset / read deadline / socket closed by close()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            self._inc("connections", -1)
            try:
                rfile.close()
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ---------------------------------------------------------------- writer
    def _writer_loop(self) -> None:
        try:
            while True:
                frames = []
                with self._cond:
                    while not self._queue and not self._stop:
                        self._cond.wait(0.2)
                    if not self._queue and self._stop:
                        return
                    # Drain multiple frames per wake, up to the staging
                    # capacity — one add_batch per wake however many
                    # frames accumulated (the PR-2 drain-and-batch shape).
                    rows = 0
                    while self._queue:
                        n = len(self._queue[0][0]["reward"])
                        if frames and rows + n > self._staging_cap:
                            break
                        frames.append(self._queue.popleft())
                        rows += n
                self._write_frames(frames)
        except BaseException as e:
            self._thread_error = e
            raise

    def _write_frames(self, frames: list) -> None:
        """``frames`` is a list of ``(cols, fold, src)`` triples popped
        from the admission queue."""
        total = sum(len(f["reward"]) for f, _fold, _src in frames)
        if total == 0:
            return
        if self._obs_norm is not None:
            # Single-writer statistics fold (this thread is the only
            # updater — the seam refuses configs with a second one),
            # BEFORE add_batch so a sampled batch never sees rows its
            # stats have not absorbed. Original windows only.
            for f, fold, _src in frames:
                if fold:
                    self._obs_norm.update(f["obs"])
        flip = self._staging_flip
        self._staging_flip = 1 - flip
        self._ledger.write(
            self._staging_group, flip, writer="fleet-ingest-writer"
        )
        staging = self._staging[flip]
        # an oversize single frame (> staging cap) falls back to a direct
        # unstaged write below rather than overrunning the slot
        if total <= self._staging_cap:
            pos = 0
            for f, _fold, _src in frames:
                n = len(f["reward"])
                for k in ("obs", "action", "reward", "next_obs", "discount"):
                    staging[k][pos : pos + n] = f[k]
                pos += n
            cols = {k: staging[k][:total] for k in staging}
        else:
            cols = {
                k: np.concatenate([f[k] for f, _fold, _src in frames])
                for k in ("obs", "action", "reward", "next_obs", "discount")
            }
        hold = self._ledger.hold(
            self._staging_group, flip, holder="fleet-ingest-add_batch"
        )
        try:
            self.buffer.add_batch(
                Transition(
                    cols["obs"],
                    cols["action"],
                    cols["reward"],
                    cols["next_obs"],
                    cols["discount"],
                )
            )
        finally:
            # add_batch copies synchronously under the buffer lock; the
            # staging slot is free the moment it returns.
            hold.release()
        mirror = sum(
            len(f["reward"]) for f, _fold, s in frames if s == "mirror"
        )
        self._inc("windows_ingested", total)
        if mirror:
            self._inc("windows_from_mirror", mirror)
        if total - mirror:
            self._inc("windows_from_actors", total - mirror)
