"""Networked collection fleet: remote actor hosts → experience-ingest.

The Ape-X/SEED-RL input-side decomposition (Horgan et al. 2018; Espeholt
et al. 2020) applied to this trainer: many actor hosts run env + a NumPy
policy locally against a periodically-synced serving bundle and stream
complete n-step windows to the learner's replay writer over the same
length-prefixed framed protocol the policy server speaks
(``d4pg_tpu/serve/protocol.py``).

Pieces (docs/fleet.md has the full contract):

- :mod:`d4pg_tpu.fleet.wire`   — payload codecs for the fleet frames;
- :mod:`d4pg_tpu.fleet.policy` — NumPy-only bundle loader + MLP forward
  (the actor host's hot path never imports JAX);
- :mod:`d4pg_tpu.fleet.ingest` — learner-side ingest server: bounded-queue
  admission with explicit shed, generation-tagged stale drops, writer
  thread feeding ``ReplayBuffer.add_batch``;
- :mod:`d4pg_tpu.fleet.actor`  — the remote actor host CLI
  (``python -m d4pg_tpu.fleet.actor``).

This package is deliberately import-light: every module here is JAX-free
(d4pglint ``host-jax-import`` manifest) so an actor host never pulls the
JAX runtime, and the learner can construct the ingest server before any
backend decision.
"""
