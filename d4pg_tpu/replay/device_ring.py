"""Device-resident replay shard: an HBM ring mirroring the host buffer.

ROADMAP item 1 (the Podracer/Anakin move, Hessel et al. 2021): keep the
whole sample→train→write-back lifecycle on-device so the only steady-state
host traffic is fresh experience trickling in.  The host buffer stays the
source of truth for *writes* (n-step writers, HER relabeling, PER trees,
generation stamps, snapshots all keep working unchanged); this module
mirrors its ring rows into device HBM so the learner's megastep
(``d4pg_tpu.runtime.megastep``) can gather batches without a host→device
batch upload per grad step.

Three pieces:

- :class:`DeviceRing` — the transition fields as a ``[capacity, ...]``
  pytree of device arrays plus a device-resident ``size`` scalar;
- :func:`ingest_body` / :func:`make_ingest` — the jit-compiled,
  donated-buffer chunk writer: a fixed-shape ``[chunk_cap, ...]`` chunk
  scatters into the ring at explicit slot indices (pad rows carry slot
  ``capacity``, dropped by the out-of-bounds scatter mode), so ONE
  compiled program covers every flush regardless of fill level or ring
  wrap;
- :class:`DeviceRingSync` — the host-side flusher: tracks the host
  buffer's monotone write counter and ships only the rows written since
  the last flush, in large infrequent chunks (the ``ingest_chunk`` stage),
  never per step and never per grad step.

:meth:`DeviceRingSync.stage` adds the ISSUE-16 double buffer on top: the
trainer calls it right after dispatching a megastep, so the NEXT flush's
first chunk gathers and ships H2D while the device is busy computing —
the transfer overlaps compute instead of serializing before the next
dispatch. ``flush`` consumes the staged chunk first (iff its base write
counter is still current), then ships the remainder in write order, so
last-write-wins is preserved even when the collector overwrote staged
rows in between.

Deliberate non-goals: the chunk gather allocates fresh host arrays per
flush (ingest is the infrequent cold path — reusing staging here would
buy nothing and re-open the ledger-hold question the hot paths needed;
``stage`` preallocates only its index buffers, since it runs once per
dispatch on the hot path);
pixel (uint8-quantized) buffers are not mirrored (a 100k-row pixel ring
is ~0.9 GB of HBM better spent on batch size — the trainer rejects the
combination loudly).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class _StagedChunk(NamedTuple):
    """One pre-staged ingest chunk (``DeviceRingSync.stage``): the gather
    + H2D of the next flush's FIRST chunk, done while the device runs the
    megastep so the transfer overlaps compute instead of serializing
    before the next dispatch (ISSUE 16's double-buffer leg)."""

    synced_at: int        # self._synced when staged (consume iff unchanged)
    covers: int           # global write index this chunk syncs through
    dev_chunk: dict       # device-resident row fields
    slots_dev: jax.Array  # device-resident [chunk_cap] slot indices
    new_size_dev: jax.Array  # ring fill count consistent at `covers`
    nbytes: int


class DeviceRing(NamedTuple):
    """Transition fields as device-resident ``[capacity, ...]`` arrays.

    Field names match the batch-dict keys every train path consumes, so
    :func:`d4pg_tpu.agent.d4pg.gather_batches` works on it directly.
    ``size`` is the filled-row count (int32 scalar, device-resident so the
    megastep's in-kernel uniform draw needs no host operand)."""

    obs: jax.Array        # [C, O] f32
    action: jax.Array     # [C, A] f32
    reward: jax.Array     # [C]    f32
    next_obs: jax.Array   # [C, O] f32
    discount: jax.Array   # [C]    f32
    size: jax.Array       # scalar int32


def device_ring_init(
    capacity: int, obs_dim: int, action_dim: int, mesh=None
) -> DeviceRing:
    # device_put COMMITS the fresh arrays: an uncommitted jnp.zeros ring
    # and the committed output of the first ingest would be distinct jit
    # cache keys — two compiles of the same program, tripping the
    # recompile sentinel's budget of 1.
    #
    # With ``mesh``, fields are placed SHARDED over "dp" on the capacity
    # axis per the partition registry (parallel/partition.py:RING_RULES):
    # each dp shard owns capacity/dp rows, in the STRIPED host↔device row
    # mapping (see ShardedDeviceRingSync) so every shard fills evenly from
    # the first rows of experience.
    ring = DeviceRing(
        obs=jnp.zeros((capacity, obs_dim), jnp.float32),
        action=jnp.zeros((capacity, action_dim), jnp.float32),
        reward=jnp.zeros((capacity,), jnp.float32),
        next_obs=jnp.zeros((capacity, obs_dim), jnp.float32),
        discount=jnp.zeros((capacity,), jnp.float32),
        size=jnp.zeros((), jnp.int32),
    )
    if mesh is None:
        return jax.device_put(ring)
    from jax.sharding import NamedSharding

    from d4pg_tpu.parallel.partition import ring_partition_specs

    n_shards = int(mesh.shape["dp"])
    if capacity % n_shards:
        raise ValueError(
            f"sharded ring: capacity {capacity} not divisible by dp="
            f"{n_shards}"
        )
    specs = ring_partition_specs(ring)
    if jax.process_count() > 1:
        # Collective-free placement (parallel/distributed.stage_global):
        # device_put onto non-addressable shardings fires a per-leaf
        # agreement broadcast that deadlocks against in-flight transfer
        # programs under the gloo CPU backend.
        from d4pg_tpu.parallel.distributed import stage_global

        return DeviceRing(
            *(stage_global(mesh, spec, leaf) for leaf, spec in zip(ring, specs))
        )
    return DeviceRing(
        *(
            jax.device_put(leaf, NamedSharding(mesh, spec))
            for leaf, spec in zip(ring, specs)
        )
    )


def ingest_body(ring: DeviceRing, chunk: dict, slots: jax.Array,
                new_size: jax.Array) -> DeviceRing:
    """Scatter one fixed-shape chunk of rows into the ring (donated).

    ``slots`` is ``[chunk_cap]`` int32: real rows carry their host ring
    slot index, pad rows carry ``capacity`` — out of bounds, dropped by
    ``mode="drop"`` — so partial chunks and ring wrap need no second
    program. In the d4pglint ``MEGASTEP_FUNCTIONS`` manifest: this body is
    jit-traced, so host numpy / ``.item()`` coercions here would smuggle a
    per-flush host sync into the device loop."""
    return DeviceRing(
        obs=ring.obs.at[slots].set(chunk["obs"], mode="drop"),
        action=ring.action.at[slots].set(chunk["action"], mode="drop"),
        reward=ring.reward.at[slots].set(chunk["reward"], mode="drop"),
        next_obs=ring.next_obs.at[slots].set(chunk["next_obs"], mode="drop"),
        discount=ring.discount.at[slots].set(chunk["discount"], mode="drop"),
        size=new_size,
    )


def make_ingest():
    """The jitted donated-buffer ingest: ONE compiled program per chunk
    shape (DeviceRingSync uses a single fixed ``chunk_cap``, so exactly
    one compile for the run — the recompile sentinel budgets it).

    Each call returns a jit of a FRESH wrapper function, not of
    ``ingest_body`` itself: ``jax.jit`` wrappers of the same underlying
    function object share one specialization cache, so a second ring
    (another trainer or bench in the same process, at another chunk
    shape) would inflate this ring's ``_cache_size()`` and false-trip the
    sentinel's budget of 1."""

    def _ingest(ring, chunk, slots, new_size):
        return ingest_body(ring, chunk, slots, new_size)

    return jax.jit(_ingest, donate_argnums=(0,))


class DeviceRingSync:
    """Host-side flusher keeping a :class:`DeviceRing` mirroring a host
    :class:`~d4pg_tpu.replay.uniform.ReplayBuffer`'s ring slots.

    ``flush(ring)`` ships every row written to the host buffer since the
    last flush (by its monotone ``total_added`` counter) as ≤ ``chunk_cap``
    -row chunks: slot indices are reconstructed from the host write head,
    rows are gathered with the buffer's own locked :meth:`gather` (so a
    concurrent collector thread can never hand us a torn row), and the
    explicit ``device_put`` + donated ingest dispatch are the ONLY
    steady-state host→device traffic of the device-resident data plane.
    More than ``capacity`` pending writes collapse to one full-ring resync
    (the overwritten intermediates no longer exist to ship).
    """

    def __init__(self, buffer, chunk_cap: int = 4096):
        self._buffer = buffer
        self.capacity = int(buffer.capacity)
        self.chunk_cap = int(min(chunk_cap, self.capacity))
        self._synced = 0  # host buffer total_added already mirrored
        self._ingest = make_ingest()
        # H2D bytes shipped, for telemetry/bench accounting (host-side
        # counter of exactly the bytes the explicit device_puts staged).
        self.bytes_ingested = 0
        self.chunks_ingested = 0
        # Device-PER seam (replay/device_per.py:DevicePerSync.on_chunk):
        # called with each chunk's ALREADY-STAGED device slot array so the
        # priority tree seeds the same rows the ring just mirrored — zero
        # extra H2D, and ring row vs priority leaf can never desync.
        self.tree_hook = None
        # Double-buffer staging (stage()): the next flush's first chunk,
        # pre-gathered + device_put while the device runs the megastep.
        # Slot/gather index buffers are preallocated so the hot-path
        # stage() call allocates no fresh host staging per dispatch
        # (device_put copies out of them before returning).
        self._staged: Optional[_StagedChunk] = None
        self._stage_slots = np.full(self.chunk_cap, self.capacity, np.int32)
        self._stage_gidx = np.zeros(self.chunk_cap, np.int64)

    def stage(self) -> bool:
        """Pre-stage the next flush's FIRST chunk: gather ≤ ``chunk_cap``
        pending rows and ``device_put`` them NOW, so the H2D transfer
        overlaps the in-flight megastep's compute instead of serializing
        in front of the next dispatch (the ``ingest_stage`` timer stage).

        Safe to call at any time: a no-op if a chunk is already staged or
        nothing is pending, and :meth:`flush` consumes the staged chunk
        only while its base write counter still matches — rows the
        collector overwrites AFTER staging are re-shipped by the flush's
        remainder loop, which runs after the staged scatter, so host write
        order (last-write-wins) is preserved end to end.

        Returns True iff a chunk is staged on exit."""
        if self._staged is not None:
            return True
        buf = self._buffer
        total = buf.total_added
        n_pending = min(total - self._synced, self.capacity)
        if n_pending <= 0:
            return False
        first = total - n_pending
        n = min(n_pending, self.chunk_cap)
        slots = self._stage_slots
        slots.fill(self.capacity)
        slots[:n] = (first + np.arange(n)) % self.capacity
        gidx = self._stage_gidx
        gidx.fill(0)
        gidx[:n] = slots[:n]
        chunk = dict(buf.gather(gidx))  # locked: never a torn row
        covers = first + n
        # Fill count consistent at `covers` writes — the remainder loop
        # (or a later flush) advances it to the final value.
        new_size = np.int32(min(covers, self.capacity))
        dev_chunk = jax.device_put(chunk)  # explicit staging (exempt)
        slots_dev = jax.device_put(slots)
        nbytes = (
            sum(v.nbytes for v in chunk.values())
            + slots.nbytes + new_size.nbytes
        )
        self._staged = _StagedChunk(
            synced_at=self._synced,
            covers=covers,
            dev_chunk=dev_chunk,
            slots_dev=slots_dev,
            new_size_dev=jax.device_put(new_size),
            nbytes=nbytes,
        )
        return True

    @property
    def ingest_fn(self):
        """The jitted ingest entry point (for recompile-sentinel tracking)."""
        return self._ingest

    def pending(self) -> int:
        return min(self._buffer.total_added - self._synced, self.capacity)

    def flush(self, ring: DeviceRing) -> DeviceRing:
        """Mirror all pending host writes into ``ring``; returns the
        updated ring (the argument is consumed — donated)."""
        buf = self._buffer
        staged, self._staged = self._staged, None
        if staged is not None and staged.synced_at == self._synced:
            # Consume the pre-staged chunk: its transfer already happened
            # under the previous dispatch. Rows written (or overwritten)
            # since staging fall into [covers, total) and ship below, in
            # write order, so the staged scatter can never shadow a newer
            # row.
            ring = self._ingest(
                ring, staged.dev_chunk, staged.slots_dev,
                staged.new_size_dev,
            )
            if self.tree_hook is not None:
                self.tree_hook(staged.slots_dev)
            self.bytes_ingested += staged.nbytes
            self.chunks_ingested += 1
            self._synced = staged.covers
        total = buf.total_added
        n_pending = min(total - self._synced, self.capacity)
        if n_pending <= 0:
            return ring
        # Slots of the last n_pending writes, oldest first: the host write
        # head has advanced `total` writes from slot 0, so write j (0-based,
        # global) landed at slot j % capacity.
        first = total - n_pending
        new_size = np.int32(min(total, self.capacity))
        for lo in range(0, n_pending, self.chunk_cap):
            hi = min(lo + self.chunk_cap, n_pending)
            n = hi - lo
            slots = np.full(self.chunk_cap, self.capacity, np.int32)
            slots[:n] = (first + lo + np.arange(n)) % self.capacity
            # Pad index rows re-read slot 0 so gather() returns the full
            # fixed shape; their scatter targets are out of bounds and
            # dropped, so the garbage never lands.
            gidx = np.zeros(self.chunk_cap, np.int64)
            gidx[:n] = slots[:n]
            chunk = dict(buf.gather(gidx))  # locked: never a torn row
            dev_chunk = jax.device_put(chunk)  # explicit staging (exempt)
            slots_dev = jax.device_put(slots)
            ring = self._ingest(
                ring, dev_chunk, slots_dev, jax.device_put(new_size),
            )
            if self.tree_hook is not None:
                self.tree_hook(slots_dev)
            self.bytes_ingested += sum(v.nbytes for v in chunk.values())
            self.bytes_ingested += slots.nbytes + new_size.nbytes
            self.chunks_ingested += 1
        self._synced = total
        return ring


# --------------------------------------------------------- sharded variant
def striped_perm(capacity: int, n_shards: int) -> np.ndarray:
    """``[n_shards, capacity // n_shards]`` host-slot indices per shard
    lane: lane ``d`` local row ``i`` holds host slot ``i * n_shards + d``.

    This is the sharded ring's row layout contract, shared by the flusher
    (mirror mapping), the megastep's parity oracle (lane construction from
    the host buffer), and the tests. STRIPED rather than block-contiguous
    on purpose: host writes land round-robin across shards, so every
    shard's slice fills evenly from the first rows of experience — with
    contiguous blocks, shard ``d`` would stay EMPTY until a fraction d/D
    of capacity had ever been written, and the shard-local uniform draw
    would have nothing to sample."""
    cl = capacity // n_shards
    return (np.arange(cl)[None, :] * n_shards + np.arange(n_shards)[:, None])


def striped_lanes(buffer, n_shards: int) -> DeviceRing:
    """Build the parity oracle's lane view of a HOST buffer: a DeviceRing
    whose row fields carry a leading ``[n_shards]`` lane axis laid out by
    :func:`striped_perm` — lane ``d`` holds exactly the rows shard ``d``
    of a sharded ring mirrors, in the same local order. ``size`` is the
    global fill count (replicated in the oracle's vmap)."""
    perm = striped_perm(int(buffer.capacity), n_shards)
    return DeviceRing(
        obs=jnp.asarray(buffer.obs[perm]),
        action=jnp.asarray(buffer.action[perm]),
        reward=jnp.asarray(buffer.reward[perm]),
        next_obs=jnp.asarray(buffer.next_obs[perm]),
        discount=jnp.asarray(buffer.discount[perm]),
        size=jnp.int32(min(buffer.total_added, int(buffer.capacity))),
    )


def sharded_ingest_body(ring: DeviceRing, chunk: dict, slots: jax.Array,
                        new_size: jax.Array) -> DeviceRing:
    """Per-shard chunk scatter (the shard_map body of the sharded ingest).

    ``ring`` is the shard's LOCAL slice (``[capacity/dp, ...]`` rows);
    ``chunk``/``slots`` arrive ``[1, chunk_local, ...]`` (the leading
    shard axis shard_map split to 1): real rows carry their LOCAL slot
    index, pad rows carry ``capacity/dp`` — out of the local bounds,
    dropped by ``mode="drop"``. One fixed compiled shape per shard covers
    every flush, exactly like the unsharded ingest. In the d4pglint
    ``MEGASTEP_FUNCTIONS`` manifest: jit-traced, so host numpy or
    ``.item()`` here would smuggle a per-flush host sync into the device
    loop."""
    sl = slots[0]
    return DeviceRing(
        obs=ring.obs.at[sl].set(chunk["obs"][0], mode="drop"),
        action=ring.action.at[sl].set(chunk["action"][0], mode="drop"),
        reward=ring.reward.at[sl].set(chunk["reward"][0], mode="drop"),
        next_obs=ring.next_obs.at[sl].set(chunk["next_obs"][0], mode="drop"),
        discount=ring.discount.at[sl].set(chunk["discount"][0], mode="drop"),
        size=new_size,
    )


def sharded_chunk_specs():
    """PartitionSpecs for a flush chunk's fields (leading axis = the shard
    axis, placed ``P("dp", ...)`` so each dp shard receives exactly its
    sub-chunk). ONE definition on purpose: the jitted ingest's in_shardings
    and the flusher's explicit ``device_put`` staging must agree, or every
    flush silently reshards — the phantom-transfer class the sentinel
    budgets exist to catch."""
    from jax.sharding import PartitionSpec as P

    return {
        "obs": P("dp", None, None),
        "action": P("dp", None, None),
        "reward": P("dp", None),
        "next_obs": P("dp", None, None),
        "discount": P("dp", None),
    }


def make_sharded_ingest(mesh, chunk_local: int, obs_dim: int, action_dim: int):
    """The jitted donated-buffer SHARDED ingest: one compiled program per
    (mesh, chunk shape) — the flusher uses a single fixed ``chunk_local``,
    so exactly one compile for the run (sentinel budget 1, same contract
    as :func:`make_ingest`). In/out shardings come from the partition-rule
    registry (``RING_RULES`` via ``ring_partition_specs``); the chunk's
    leading axis is the shard axis, placed ``P("dp", ...)`` so each dp
    shard receives exactly its sub-chunk — ingest stays shard-local, no
    collectives in the lowered program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from d4pg_tpu.parallel.compat import shard_map
    from d4pg_tpu.parallel.partition import ring_partition_specs

    template = DeviceRing(
        obs=np.zeros((2, obs_dim), np.float32),
        action=np.zeros((2, action_dim), np.float32),
        reward=np.zeros((2,), np.float32),
        next_obs=np.zeros((2, obs_dim), np.float32),
        discount=np.zeros((2,), np.float32),
        size=np.zeros((), np.int32),
    )
    ring_specs = ring_partition_specs(template)
    chunk_specs = sharded_chunk_specs()
    mapped = shard_map(
        sharded_ingest_body,
        mesh=mesh,
        in_specs=(ring_specs, chunk_specs, P("dp", None), P()),
        out_specs=ring_specs,
        check_vma=False,
    )
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        (ring_specs, chunk_specs, P("dp", None), P()),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.jit(
        mapped,
        in_shardings=shardings,
        out_shardings=shardings[0],
        donate_argnums=(0,),
    )


class ShardedDeviceRingSync:
    """The dp-sharded flusher: mirrors a host buffer's ring slots into a
    :class:`DeviceRing` whose rows are sharded over "dp" (ROADMAP item 2 —
    the scale-out of :class:`DeviceRingSync`).

    Layout is STRIPED (:func:`striped_perm`): host slot ``j`` lives on
    shard ``j % dp`` at local row ``j // dp``, so collection fills every
    shard evenly and the megastep's shard-local uniform draw over
    ``[0, size // dp)`` rows is always backed by mirrored data. Each flush
    ships ONE fixed-shape ``[dp, chunk_local, ...]`` chunk per round —
    every shard's sub-chunk padded to the same ``chunk_local`` (pad slot
    = local capacity, dropped by the scatter) — placed per-shard with an
    explicit ``NamedSharding`` ``device_put``; the donated shard_map
    ingest then scatters locally. Same contract as the unsharded sync:
    one compiled ingest program ever, explicit staging is the only
    steady-state H2D, more than ``capacity`` pending writes collapse to a
    full resync.
    """

    def __init__(self, buffer, mesh, chunk_cap: int = 4096):
        self._buffer = buffer
        self._mesh = mesh
        self.n_shards = int(mesh.shape["dp"])
        self.capacity = int(buffer.capacity)
        if self.capacity % self.n_shards:
            raise ValueError(
                f"sharded ring: capacity {self.capacity} not divisible "
                f"by dp={self.n_shards}"
            )
        self.local_capacity = self.capacity // self.n_shards
        self.chunk_local = int(
            min(max(1, chunk_cap // self.n_shards), self.local_capacity)
        )
        self._synced = 0
        obs_dim = buffer.obs.shape[1]
        act_dim = buffer.action.shape[1]
        self._ingest = make_sharded_ingest(
            mesh, self.chunk_local, obs_dim, act_dim
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Built from the SAME spec dict the jitted ingest's in_shardings
        # use (sharded_chunk_specs) — staging and program can never drift.
        self._chunk_sharding = {
            k: NamedSharding(mesh, s) for k, s in sharded_chunk_specs().items()
        }
        self._slots_sharding = NamedSharding(mesh, P("dp", None))
        self._scalar_sharding = NamedSharding(mesh, P())
        self.bytes_ingested = 0
        self.chunks_ingested = 0
        # Device-PER seam: same contract as DeviceRingSync.tree_hook, with
        # the [dp, chunk_local] LOCAL-slot layout this sync stages (pad =
        # local capacity) — exactly what the sharded tree ingest expects.
        self.tree_hook = None

    @property
    def ingest_fn(self):
        """The jitted ingest entry point (recompile-sentinel tracking)."""
        return self._ingest

    def pending(self) -> int:
        return min(self._buffer.total_added - self._synced, self.capacity)

    def flush(self, ring: DeviceRing) -> DeviceRing:
        """Mirror all pending host writes into the sharded ``ring``;
        returns the updated ring (the argument is consumed — donated)."""
        buf = self._buffer
        total = buf.total_added
        n_pending = min(total - self._synced, self.capacity)
        if n_pending <= 0:
            return ring
        first = total - n_pending
        new_size = np.int32(min(total, self.capacity))
        D, cl = self.n_shards, self.chunk_local
        # Pending host slots in write order, dealt to their owner shards.
        pend = (first + np.arange(n_pending)) % self.capacity
        by_shard = [pend[pend % D == d] // D for d in range(D)]
        rounds = max(1, -(-max(len(b) for b in by_shard) // cl))
        for r in range(rounds):
            slots = np.full((D, cl), self.local_capacity, np.int32)
            gidx = np.zeros((D, cl), np.int64)
            for d in range(D):
                part = by_shard[d][r * cl:(r + 1) * cl]
                slots[d, : len(part)] = part
                # Pad index rows re-read the shard's slot 0 so gather()
                # returns the fixed shape; their scatter targets are out
                # of local bounds and dropped.
                gidx[d, : len(part)] = part * D + d
            chunk = {
                k: np.asarray(v).reshape((D, cl) + v.shape[1:])
                for k, v in dict(buf.gather(gidx.ravel())).items()
            }
            # Explicit per-shard staging (exempt from the transfer guard):
            # the NamedSharding device_put hands each dp shard exactly its
            # sub-chunk.
            dev_chunk = {
                k: jax.device_put(v, self._chunk_sharding[k])
                for k, v in chunk.items()
            }
            slots_dev = jax.device_put(slots, self._slots_sharding)
            ring = self._ingest(
                ring,
                dev_chunk,
                slots_dev,
                jax.device_put(new_size, self._scalar_sharding),
            )
            if self.tree_hook is not None:
                self.tree_hook(slots_dev)
            self.bytes_ingested += sum(v.nbytes for v in chunk.values())
            self.bytes_ingested += slots.nbytes + new_size.nbytes
            self.chunks_ingested += 1
        self._synced = total
        return ring


# ------------------------------------------------------ multi-host variant
class MultihostRingSync:
    """Per-host flusher for a PROCESS-SPANNING sharded ring (ISSUE 17).

    Same striped layout and the same compiled ingest program as
    :class:`ShardedDeviceRingSync`, but the mesh's ``dp`` shards live on
    ``P = jax.process_count()`` processes and each process owns a
    process-LOCAL host :class:`~d4pg_tpu.replay.uniform.ReplayBuffer` of
    capacity ``C/P`` — its own ingest servers/collectors feed it, nothing
    crosses hosts on the write path. The layout algebra that makes this
    exact: with process-major device order, process ``p`` owns global
    shards ``[p*L, (p+1)*L)`` (``L`` local devices, ``D = P*L`` total), so
    a LOCAL buffer striped over ``L`` lanes is precisely the global striped
    ring restricted to ``p``'s shards — local slot ``m`` IS global slot
    ``(m//L)*D + p*L + (m%L)``, and host ``p``'s ``k``-th local write is
    global write ``(k//L)*D + p*L + (k%L)`` of the interleaved stream.

    Every flush is a COLLECTIVE: the ingest program scatters into all
    ``D`` shards, so all processes must dispatch it the same number of
    times with the same fill count. Cross-host cursor agreement does that
    with one small host all-gather per flush — each process contributes
    ``(local total_added, local rounds needed)``; everyone runs
    ``max(rounds)`` rounds (processes with nothing pending ship all-pad
    chunks, dropped by the scatter) and commits the agreed global fill
    count, the largest gapless prefix of the interleaved global write
    stream derivable from the gathered cursors. Chunk staging uses
    ``jax.make_array_from_callback``: the callback runs only for this
    process's ADDRESSABLE shards, so each host stages exactly its local
    sub-chunks — per-host ingest H2D, no cross-host replay bytes ever.
    """

    def __init__(self, buffer, mesh, chunk_cap: int = 4096):
        from d4pg_tpu.parallel.distributed import local_shard_span

        self._buffer = buffer
        self._mesh = mesh
        self.n_shards = int(mesh.shape["dp"])            # D (global)
        self.n_processes = int(jax.process_count())      # P
        lo, hi = local_shard_span(mesh, "dp")
        self.shard_lo = lo
        self.local_shards = hi - lo                      # L
        self.host_capacity = int(buffer.capacity)        # C/P (local buffer)
        self.capacity = self.host_capacity * self.n_processes  # C (global)
        if self.capacity % self.n_shards:
            raise ValueError(
                f"multihost ring: global capacity {self.capacity} not "
                f"divisible by dp={self.n_shards}"
            )
        if self.host_capacity % max(self.local_shards, 1):
            raise ValueError(
                f"multihost ring: local capacity {self.host_capacity} not "
                f"divisible by local shard count {self.local_shards}"
            )
        self.local_capacity = self.capacity // self.n_shards  # rows/shard
        self.chunk_local = int(
            min(max(1, chunk_cap // self.n_shards), self.local_capacity)
        )
        self._synced = 0
        obs_dim = buffer.obs.shape[1]
        act_dim = buffer.action.shape[1]
        self._ingest = make_sharded_ingest(
            mesh, self.chunk_local, obs_dim, act_dim
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._chunk_sharding = {
            k: NamedSharding(mesh, s) for k, s in sharded_chunk_specs().items()
        }
        self._slots_sharding = NamedSharding(mesh, P("dp", None))
        self._scalar_sharding = NamedSharding(mesh, P())
        # Per-HOST H2D accounting: only the bytes this process staged for
        # its local shards (the bench sums hosts for the aggregate).
        self.bytes_ingested = 0
        self.chunks_ingested = 0
        self.tree_hook = None

    @property
    def ingest_fn(self):
        """The jitted ingest entry point (recompile-sentinel tracking)."""
        return self._ingest

    def pending(self) -> int:
        return min(self._buffer.total_added - self._synced, self.host_capacity)

    def _stage(self, local_rows: np.ndarray, sharding):
        """Stage ``[L, chunk_local, ...]`` local lane rows as the global
        ``[D, chunk_local, ...]`` chunk array: the callback materializes
        only this process's addressable shard slices."""
        base = self.shard_lo
        shape = (self.n_shards,) + local_rows.shape[1:]

        def cb(idx):
            d = idx[0].start if idx[0].start is not None else 0
            return local_rows[d - base:d - base + 1]

        return jax.make_array_from_callback(shape, sharding, cb)

    def _stage_scalar(self, value):
        """Replicated scalar via the same collective-free callback path —
        ``device_put`` onto a process-spanning replicated sharding fires
        an agreement broadcast per call (see distributed.stage_global)."""
        arr = np.asarray(value)
        return jax.make_array_from_callback(
            arr.shape, self._scalar_sharding, lambda idx: arr[idx]
        )

    def _gapless_total(self, totals: np.ndarray) -> int:
        """The largest global write count ``T`` such that every one of the
        first ``T`` interleaved global writes has landed, given each host's
        local ``total_added``: host ``p``'s cursor ``t_p`` means its shard
        ``p*L + s`` has received ``ceil((t_p - s)/L)`` writes, and the
        gapless prefix ends at the first shard still short —
        ``min_d(writes_d * D + d)``. Exact under the lock-step deal (equals
        the true total); conservative under skewed per-host feeds (never
        counts a row some host has not written)."""
        D, L = self.n_shards, self.local_shards
        best = None
        for p in range(self.n_processes):
            t = int(totals[p])
            for s in range(L):
                d = p * L + s
                writes = max(-(-(t - s) // L), 0)
                cand = writes * D + d
                if best is None or cand < best:
                    best = cand
        return int(best)

    def flush(self, ring: DeviceRing) -> DeviceRing:
        """Mirror pending LOCAL host writes into this process's shards of
        the global ``ring`` (consumed — donated). Collective: every
        process of the mesh must call this at the same point; the embedded
        cursor all-gather agrees on rounds and fill count."""
        from d4pg_tpu.parallel.distributed import host_allgather_i64

        buf = self._buffer
        L, cl = self.local_shards, self.chunk_local
        total = buf.total_added
        n_pending = min(total - self._synced, self.host_capacity)
        first = total - n_pending
        pend = (first + np.arange(n_pending)) % self.host_capacity
        by_lane = [pend[pend % L == s] // L for s in range(L)]
        my_rounds = (
            -(-max(len(b) for b in by_lane) // cl) if n_pending > 0 else 0
        )
        agreed = host_allgather_i64([total, my_rounds])   # [P, 2]
        rounds = int(agreed[:, 1].max())
        if rounds == 0:
            return ring
        new_size = np.int32(
            min(self._gapless_total(agreed[:, 0]), self.capacity)
        )
        for r in range(rounds):
            slots = np.full((L, cl), self.local_capacity, np.int32)
            gidx = np.zeros((L, cl), np.int64)
            for s in range(L):
                part = by_lane[s][r * cl:(r + 1) * cl]
                # LOCAL lane rows are GLOBAL shard rows: lane s row i is
                # local slot i*L + s = global slot i*D + (base + s), i.e.
                # shard (base+s) local row i — identical row index, so the
                # local deal needs no re-mapping.
                slots[s, : len(part)] = part
                gidx[s, : len(part)] = part * L + s
            chunk = {
                k: np.asarray(v).reshape((L, cl) + v.shape[1:])
                for k, v in dict(buf.gather(gidx.ravel())).items()
            }
            dev_chunk = {
                k: self._stage(v, self._chunk_sharding[k])
                for k, v in chunk.items()
            }
            slots_dev = self._stage(slots, self._slots_sharding)
            ring = self._ingest(
                ring,
                dev_chunk,
                slots_dev,
                self._stage_scalar(new_size),
            )
            if self.tree_hook is not None:
                self.tree_hook(slots_dev)
            self.bytes_ingested += sum(v.nbytes for v in chunk.values())
            self.bytes_ingested += slots.nbytes + new_size.nbytes
            self.chunks_ingested += 1
        self._synced = total
        return ring

    # ---------------------------------------------------------- snapshots
    def gather_snapshot(self, ring: DeviceRing) -> dict:
        """Assemble the GLOBAL ring into the exact
        :meth:`~d4pg_tpu.replay.uniform.ReplayBuffer.snapshot` npz layout
        (rows ``[0, size)`` in global slot order + ``pos``/``size``), so a
        multi-host checkpoint restores onto ANY topology — single-process
        ``ReplayBuffer.restore`` included. Collective (the per-field
        gathers all-gather across processes): every process must call it;
        process 0 writes the file. Call after :meth:`flush` so unmirrored
        local rows are not silently dropped from the snapshot."""
        from d4pg_tpu.parallel.distributed import (
            gather_global,
            host_allgather_i64,
        )

        totals = host_allgather_i64([self._buffer.total_added])[:, 0]
        T = self._gapless_total(totals)
        size = int(min(T, self.capacity))
        pos = int(T % self.capacity)
        D = self.n_shards
        perm = striped_perm(self.capacity, D).reshape(-1)
        out = {"pos": np.asarray(pos), "size": np.asarray(size)}
        for name in ("obs", "action", "reward", "next_obs", "discount"):
            lanes = gather_global(getattr(ring, name))
            host = np.empty_like(lanes)
            host[perm] = lanes
            out[name] = host[:size]
        return out

    def deal_snapshot(self, data) -> int:
        """Restore this process's share of a GLOBAL replay snapshot (the
        :meth:`gather_snapshot` / single-process ``ReplayBuffer.snapshot``
        layout) into the LOCAL host buffer; returns the local row count.
        Inverse of the striped deal: global total ``T`` puts
        ``t_p = (T//D)*L + clip(T%D - p*L, 0, L)`` writes on host ``p``,
        and local slot ``m`` reads global slot ``(m//L)*D + p*L + (m%L)``.
        Host-local (no collective); resets ``_synced`` so the next flush
        re-mirrors the restored rows."""
        size = int(np.asarray(data["size"]).item())
        pos = int(np.asarray(data["pos"]).item())
        # Same lifetime-counter reconstruction rule as ReplayBuffer.restore.
        T = pos + self.capacity if size == self.capacity else size
        D, L, base = self.n_shards, self.local_shards, self.shard_lo
        t_p = (T // D) * L + int(np.clip(T % D - base, 0, L))
        n_local = min(t_p, self.host_capacity)
        m = np.arange(n_local)
        j = (m // L) * D + base + (m % L)
        local = {
            name: np.asarray(data[name])[j]
            for name in ("obs", "action", "reward", "next_obs", "discount")
        }
        local["pos"] = np.asarray(t_p % self.host_capacity)
        local["size"] = np.asarray(n_local)
        buf = self._buffer
        with buf._lock:
            buf._restore_arrays(local)
            # _restore_arrays reconstructs the lifetime counter as
            # pos+capacity on a full local ring — pin the exact cursor we
            # derived instead, so the next cursor agreement sees the same
            # T on every host.
            buf._total_added = t_p
        self._synced = 0
        return n_local
