"""Device-resident replay shard: an HBM ring mirroring the host buffer.

ROADMAP item 1 (the Podracer/Anakin move, Hessel et al. 2021): keep the
whole sample→train→write-back lifecycle on-device so the only steady-state
host traffic is fresh experience trickling in.  The host buffer stays the
source of truth for *writes* (n-step writers, HER relabeling, PER trees,
generation stamps, snapshots all keep working unchanged); this module
mirrors its ring rows into device HBM so the learner's megastep
(``d4pg_tpu.runtime.megastep``) can gather batches without a host→device
batch upload per grad step.

Three pieces:

- :class:`DeviceRing` — the transition fields as a ``[capacity, ...]``
  pytree of device arrays plus a device-resident ``size`` scalar;
- :func:`ingest_body` / :func:`make_ingest` — the jit-compiled,
  donated-buffer chunk writer: a fixed-shape ``[chunk_cap, ...]`` chunk
  scatters into the ring at explicit slot indices (pad rows carry slot
  ``capacity``, dropped by the out-of-bounds scatter mode), so ONE
  compiled program covers every flush regardless of fill level or ring
  wrap;
- :class:`DeviceRingSync` — the host-side flusher: tracks the host
  buffer's monotone write counter and ships only the rows written since
  the last flush, in large infrequent chunks (the ``ingest_chunk`` stage),
  never per step and never per grad step.

Deliberate non-goals: the chunk gather allocates fresh host arrays per
flush (ingest is the infrequent cold path — reusing staging here would
buy nothing and re-open the ledger-hold question the hot paths needed);
pixel (uint8-quantized) buffers are not mirrored (a 100k-row pixel ring
is ~0.9 GB of HBM better spent on batch size — the trainer rejects the
combination loudly).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceRing(NamedTuple):
    """Transition fields as device-resident ``[capacity, ...]`` arrays.

    Field names match the batch-dict keys every train path consumes, so
    :func:`d4pg_tpu.agent.d4pg.gather_batches` works on it directly.
    ``size`` is the filled-row count (int32 scalar, device-resident so the
    megastep's in-kernel uniform draw needs no host operand)."""

    obs: jax.Array        # [C, O] f32
    action: jax.Array     # [C, A] f32
    reward: jax.Array     # [C]    f32
    next_obs: jax.Array   # [C, O] f32
    discount: jax.Array   # [C]    f32
    size: jax.Array       # scalar int32


def device_ring_init(capacity: int, obs_dim: int, action_dim: int) -> DeviceRing:
    # device_put COMMITS the fresh arrays: an uncommitted jnp.zeros ring
    # and the committed output of the first ingest would be distinct jit
    # cache keys — two compiles of the same program, tripping the
    # recompile sentinel's budget of 1.
    return jax.device_put(
        DeviceRing(
            obs=jnp.zeros((capacity, obs_dim), jnp.float32),
            action=jnp.zeros((capacity, action_dim), jnp.float32),
            reward=jnp.zeros((capacity,), jnp.float32),
            next_obs=jnp.zeros((capacity, obs_dim), jnp.float32),
            discount=jnp.zeros((capacity,), jnp.float32),
            size=jnp.zeros((), jnp.int32),
        )
    )


def ingest_body(ring: DeviceRing, chunk: dict, slots: jax.Array,
                new_size: jax.Array) -> DeviceRing:
    """Scatter one fixed-shape chunk of rows into the ring (donated).

    ``slots`` is ``[chunk_cap]`` int32: real rows carry their host ring
    slot index, pad rows carry ``capacity`` — out of bounds, dropped by
    ``mode="drop"`` — so partial chunks and ring wrap need no second
    program. In the d4pglint ``MEGASTEP_FUNCTIONS`` manifest: this body is
    jit-traced, so host numpy / ``.item()`` coercions here would smuggle a
    per-flush host sync into the device loop."""
    return DeviceRing(
        obs=ring.obs.at[slots].set(chunk["obs"], mode="drop"),
        action=ring.action.at[slots].set(chunk["action"], mode="drop"),
        reward=ring.reward.at[slots].set(chunk["reward"], mode="drop"),
        next_obs=ring.next_obs.at[slots].set(chunk["next_obs"], mode="drop"),
        discount=ring.discount.at[slots].set(chunk["discount"], mode="drop"),
        size=new_size,
    )


def make_ingest():
    """The jitted donated-buffer ingest: ONE compiled program per chunk
    shape (DeviceRingSync uses a single fixed ``chunk_cap``, so exactly
    one compile for the run — the recompile sentinel budgets it).

    Each call returns a jit of a FRESH wrapper function, not of
    ``ingest_body`` itself: ``jax.jit`` wrappers of the same underlying
    function object share one specialization cache, so a second ring
    (another trainer or bench in the same process, at another chunk
    shape) would inflate this ring's ``_cache_size()`` and false-trip the
    sentinel's budget of 1."""

    def _ingest(ring, chunk, slots, new_size):
        return ingest_body(ring, chunk, slots, new_size)

    return jax.jit(_ingest, donate_argnums=(0,))


class DeviceRingSync:
    """Host-side flusher keeping a :class:`DeviceRing` mirroring a host
    :class:`~d4pg_tpu.replay.uniform.ReplayBuffer`'s ring slots.

    ``flush(ring)`` ships every row written to the host buffer since the
    last flush (by its monotone ``total_added`` counter) as ≤ ``chunk_cap``
    -row chunks: slot indices are reconstructed from the host write head,
    rows are gathered with the buffer's own locked :meth:`gather` (so a
    concurrent collector thread can never hand us a torn row), and the
    explicit ``device_put`` + donated ingest dispatch are the ONLY
    steady-state host→device traffic of the device-resident data plane.
    More than ``capacity`` pending writes collapse to one full-ring resync
    (the overwritten intermediates no longer exist to ship).
    """

    def __init__(self, buffer, chunk_cap: int = 4096):
        self._buffer = buffer
        self.capacity = int(buffer.capacity)
        self.chunk_cap = int(min(chunk_cap, self.capacity))
        self._synced = 0  # host buffer total_added already mirrored
        self._ingest = make_ingest()
        # H2D bytes shipped, for telemetry/bench accounting (host-side
        # counter of exactly the bytes the explicit device_puts staged).
        self.bytes_ingested = 0
        self.chunks_ingested = 0

    @property
    def ingest_fn(self):
        """The jitted ingest entry point (for recompile-sentinel tracking)."""
        return self._ingest

    def pending(self) -> int:
        return min(self._buffer.total_added - self._synced, self.capacity)

    def flush(self, ring: DeviceRing) -> DeviceRing:
        """Mirror all pending host writes into ``ring``; returns the
        updated ring (the argument is consumed — donated)."""
        buf = self._buffer
        total = buf.total_added
        n_pending = min(total - self._synced, self.capacity)
        if n_pending <= 0:
            return ring
        # Slots of the last n_pending writes, oldest first: the host write
        # head has advanced `total` writes from slot 0, so write j (0-based,
        # global) landed at slot j % capacity.
        first = total - n_pending
        new_size = np.int32(min(total, self.capacity))
        for lo in range(0, n_pending, self.chunk_cap):
            hi = min(lo + self.chunk_cap, n_pending)
            n = hi - lo
            slots = np.full(self.chunk_cap, self.capacity, np.int32)
            slots[:n] = (first + lo + np.arange(n)) % self.capacity
            # Pad index rows re-read slot 0 so gather() returns the full
            # fixed shape; their scatter targets are out of bounds and
            # dropped, so the garbage never lands.
            gidx = np.zeros(self.chunk_cap, np.int64)
            gidx[:n] = slots[:n]
            chunk = dict(buf.gather(gidx))  # locked: never a torn row
            dev_chunk = jax.device_put(chunk)  # explicit staging (exempt)
            ring = self._ingest(
                ring, dev_chunk, jax.device_put(slots),
                jax.device_put(new_size),
            )
            self.bytes_ingested += sum(v.nbytes for v in chunk.values())
            self.bytes_ingested += slots.nbytes + new_size.nbytes
            self.chunks_ingested += 1
        self._synced = total
        return ring
