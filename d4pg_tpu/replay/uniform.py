"""Uniform ring-buffer replay on preallocated NumPy arrays.

Capability parity with reference ``replay_memory.py:4-80`` (``Replay.add`` /
``sample``) — but columnar storage with O(1) vectorized batched writes and
gather-based sampling: a sampled batch is a set of contiguous-dtype arrays
ready for a single ``jax.device_put`` (the host→TPU boundary), not a Python
list of tuples re-stacked per sample (``replay_memory.py:61-80``).

Transitions carry an explicit per-sample ``discount`` = γ^m·(1−terminal) so
the device-side projection needs no gamma/n plumbing — this is how the build
fixes the reference's dead/inconsistent n-step path (SURVEY.md quirk #3/#5).
"""

from __future__ import annotations

import os
import threading
from typing import Mapping, NamedTuple

import numpy as np
from d4pg_tpu.analysis import lockwitness


class Transition(NamedTuple):
    """One (possibly n-step-collapsed) transition."""

    obs: np.ndarray        # s_t
    action: np.ndarray     # a_t
    reward: np.ndarray     # R_t = sum_{k<m} gamma^k r_{t+k}
    next_obs: np.ndarray   # s_{t+m}
    discount: np.ndarray   # gamma^m * (1 - terminal)


class ReplayBuffer:
    """Thread-safe columnar ring buffer.

    Writes (actor threads) and reads (learner thread) take a lock; the
    critical sections are pure NumPy slice ops so contention stays tiny.
    The reference's equivalent races (per-process buffers, SURVEY.md §5
    'race detection') are structurally removed.
    """

    def __init__(self, capacity: int, obs_dim: int, action_dim: int,
                 obs_dtype=np.float32, obs_scale: float | None = None,
                 decode_on_sample: bool = True):
        """``obs_dtype=np.uint8`` quantizes observations to bytes in storage
        — 4× less host RAM for pixel envs, the standard pixel-replay layout.
        ``obs_scale`` is the fixed store-time multiplier, declared once at
        construction (guessing the convention per frame mis-encodes dark
        frames). Only 255.0 ([0,1]-float envs, the default) is accepted:
        decoded batches are always [0,1] floats, so an env emitting raw
        [0,255] bytes would act on a different input range than it trains
        on — byte envs must normalize at the env boundary instead. Flat
        envs keep f32 and ignore ``obs_scale``."""
        self.capacity = int(capacity)
        self.obs_dtype = np.dtype(obs_dtype)
        self._quantized = self.obs_dtype == np.uint8
        # decode_on_sample=False (quantized buffers only) keeps sampled obs
        # rows in their stored uint8 form so the TRAINER can ship them over
        # the host→device link at 1 byte/element and dequantize in-jit —
        # the pixel-batch link wall is 4× the f32 one (302 MB per K=32
        # batch-256 48×48×2 dispatch; measured ~3 grad-steps/s through the
        # tunnel). Consumers must divide by 255 before use.
        self._decode_on_sample = bool(decode_on_sample)
        self._obs_scale = float(obs_scale) if obs_scale is not None else 255.0
        if self._quantized and self._obs_scale != 255.0:
            # With scale≠255 the stored rows decode to [0,1] while acting/eval
            # feed the RAW env range to the same actor — a train/act input
            # mismatch. Byte envs must normalize at the env boundary (emit
            # [0,1] floats) instead of relying on store-time scale.
            raise ValueError(
                "obs_scale must be 255.0 (env emits [0,1] floats); byte-image "
                "envs should normalize observations at the env boundary"
            )
        self.obs = np.zeros((capacity, obs_dim), self.obs_dtype)
        self.action = np.zeros((capacity, action_dim), np.float32)
        self.reward = np.zeros((capacity,), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), self.obs_dtype)
        self.discount = np.zeros((capacity,), np.float32)
        # Per-slot write generation: bumped on every overwrite so async
        # priority write-backs can detect that a sampled slot was recycled
        # (new transition) before the flush landed, and drop the update
        # instead of stamping a fresh max-priority insert with another
        # transition's TD priority.
        self._gen = np.zeros((capacity,), np.int64)
        self._pos = 0
        self._size = 0
        # Monotone lifetime write counter (never wraps): the device-ring
        # mirror (replay/device_ring.py) diffs it to find which slots
        # changed since its last flush. Plain-int reads are safe off-lock
        # (readers tolerate one-batch staleness: unmirrored rows simply
        # ship on the next flush).
        self._total_added = 0
        # Witnessed under --debug-guards (static node id, see lockwitness)
        self._lock = lockwitness.named_lock("ReplayBuffer._lock")

    def _encode_obs(self, obs: np.ndarray) -> np.ndarray:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        if self._quantized:
            return np.clip(np.rint(obs * self._obs_scale), 0.0, 255.0).astype(
                np.uint8
            )
        return obs

    def _decode_obs(self, stored: np.ndarray) -> np.ndarray:
        if self._quantized:
            return stored.astype(np.float32) / 255.0
        return stored

    def __len__(self) -> int:
        return self._size

    @property
    def total_added(self) -> int:
        """Monotone count of rows ever written (including overwrites)."""
        return self._total_added

    def add_batch(self, t: Transition) -> np.ndarray:
        """Insert a batch of transitions; returns the slot indices written."""
        obs = self._encode_obs(t.obs)
        n = obs.shape[0]
        with self._lock:
            idx = (self._pos + np.arange(n)) % self.capacity
            self.obs[idx] = obs
            self.action[idx] = np.atleast_2d(np.asarray(t.action, np.float32))
            self.reward[idx] = np.asarray(t.reward, np.float32).reshape(n)
            self.next_obs[idx] = self._encode_obs(t.next_obs)
            self.discount[idx] = np.asarray(t.discount, np.float32).reshape(n)
            self._gen[idx] += 1
            self._pos = int((self._pos + n) % self.capacity)
            self._size = int(min(self._size + n, self.capacity))
            self._total_added += n
        return idx

    def add(self, obs, action, reward, next_obs, discount) -> np.ndarray:
        return self.add_batch(
            Transition(
                np.asarray(obs)[None],
                np.asarray(action)[None],
                np.asarray([reward]),
                np.asarray(next_obs)[None],
                np.asarray([discount]),
            )
        )

    def gather(self, idx: np.ndarray) -> Mapping[str, np.ndarray]:
        decode = (
            self._decode_obs if self._decode_on_sample else (lambda x: x)
        )
        with self._lock:
            return {
                "obs": decode(self.obs[idx]),
                "action": self.action[idx],
                "reward": self.reward[idx],
                "next_obs": decode(self.next_obs[idx]),
                "discount": self.discount[idx],
            }

    def sample(self, batch_size: int, rng: np.random.Generator):
        """Uniform sample of stacked arrays (reference ``replay_memory.py:61-80``)."""
        idx = rng.integers(0, self._size, size=batch_size)
        return self.gather(idx)

    # ------------------------------------------------------------- snapshot
    def _snapshot_arrays(self) -> dict:
        """Stored rows in ring order [0, size) as LIVE VIEWS. The caller
        MUST hold self._lock and copy every value before releasing it."""
        n = self._size
        return {
            "obs": self.obs[:n],
            "action": self.action[:n],
            "reward": self.reward[:n],
            "next_obs": self.next_obs[:n],
            "discount": self.discount[:n],
            "pos": np.asarray(self._pos),
            "size": np.asarray(n),
        }

    def snapshot(self, path: str) -> None:
        """Write the buffer contents to ``path`` (.npz, atomic via rename).

        The reference checkpoints nothing but network weights (SURVEY.md §5
        'checkpoint/resume'); without this, --resume restarts with an empty
        replay and repays the whole warmup in fresh interaction.
        """
        with self._lock:
            # Real copies: collector threads keep mutating the live arrays
            # while the (seconds-long) compression below runs unlocked.
            data = {k: np.array(v, copy=True) for k, v in self._snapshot_arrays().items()}
        tmp = f"{path}.tmp.npz"  # savez appends .npz unless present
        # Uncompressed: replay rows are high-entropy floats (deflate gains
        # ~10%) and compression stalls the learner for minutes at 1M rows.
        np.savez(tmp, **data)
        os.replace(tmp, path)

    def _restore_arrays(self, data) -> int:
        n = int(np.asarray(data["size"]).item())
        if n > self.capacity:
            raise ValueError(
                f"snapshot holds {n} rows > capacity {self.capacity}; "
                "raise --rmsize to restore it"
            )
        if data["obs"].shape[1] != self.obs.shape[1]:
            raise ValueError("snapshot obs_dim does not match this buffer")
        self.obs[:n] = data["obs"]
        self.action[:n] = data["action"]
        self.reward[:n] = data["reward"]
        self.next_obs[:n] = data["next_obs"]
        self.discount[:n] = data["discount"]
        # Every row changed identity: invalidate any generation stamps
        # captured by samples taken before the restore.
        self._gen += 1
        self._size = n
        # Same capacity → resume the saved write head so FIFO eviction order
        # survives a wrapped ring; different capacity → data sits at [0, n).
        saved_pos = int(np.asarray(data["pos"]).item())
        self._pos = saved_pos if n == self.capacity else n % self.capacity
        # Re-derive the lifetime counter so (total_added % capacity) ==
        # _pos and min(total_added, capacity) == _size keep holding — the
        # two invariants the device-ring mirror's slot math rests on. A
        # fresh mirror (synced=0) then resyncs the whole restored buffer.
        self._total_added = (
            self._pos + self.capacity if n == self.capacity else n
        )
        return n

    def restore(self, path: str) -> int:
        """Load a :meth:`snapshot`; returns the number of rows restored."""
        with np.load(path, allow_pickle=False) as data:
            with self._lock:
                return self._restore_arrays(data)
