"""Hindsight experience replay ("future" strategy).

Capability parity with the reference's inline HER at ``main.py:154-184``:
after an episode, each transition is additionally stored with its desired
goal replaced by an achieved goal sampled from a *future* timestep of the
same episode, reward recomputed under the substituted goal. Two deliberate
fixes over the reference:

- the relabeled transition stores its own action, not the loop-final action
  (reference bug at ``main.py:184``, SURVEY.md quirk #6);
- original transitions are always stored (the reference gates ALL stores on
  ``args.her and not done`` — quirk #14), HER only adds relabeled copies.

Observations are goal-env dicts flattened as ``concat(observation, goal)``
exactly as the reference does (``main.py:73-79,144``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List

import numpy as np

from d4pg_tpu.replay.nstep_writer import NStepWriter


@dataclass
class _Step:
    observation: np.ndarray
    achieved_goal: np.ndarray
    desired_goal: np.ndarray
    action: np.ndarray
    reward: float
    next_observation: np.ndarray
    next_achieved_goal: np.ndarray
    terminated: bool


class HindsightWriter:
    """Buffers one episode, then writes original + k relabeled copies.

    ``compute_reward(achieved_goal, desired_goal) -> reward`` mirrors gym's
    ``env.compute_reward`` used by the reference at ``main.py:178``.
    ``done_on_success`` reproduces the reference's relabeled done flag
    (``main.py:183``): a relabeled transition is terminal iff its reward
    signals success.
    """

    def __init__(
        self,
        writer_factory: Callable[[], NStepWriter],
        compute_reward: Callable[[np.ndarray, np.ndarray], float],
        k_future: int = 4,
        rng: np.random.Generator | None = None,
        done_on_success: bool = True,
        success_reward: float = 0.0,
    ):
        self.writer_factory = writer_factory
        self.compute_reward = compute_reward
        self.k_future = k_future
        self.rng = rng or np.random.default_rng()
        self.done_on_success = done_on_success
        self.success_reward = success_reward
        self._episode: List[_Step] = []

    @staticmethod
    def flatten(observation: np.ndarray, goal: np.ndarray) -> np.ndarray:
        return np.concatenate([np.asarray(observation), np.asarray(goal)], axis=-1)

    def add(
        self,
        observation,
        achieved_goal,
        desired_goal,
        action,
        reward,
        next_observation,
        next_achieved_goal,
        terminated: bool,
    ) -> None:
        self._episode.append(
            _Step(
                np.asarray(observation),
                np.asarray(achieved_goal),
                np.asarray(desired_goal),
                np.asarray(action),
                float(reward),
                np.asarray(next_observation),
                np.asarray(next_achieved_goal),
                bool(terminated),
            )
        )

    def end_episode(self, truncated: bool = True) -> int:
        """Flush the episode: original + relabeled transitions. Returns the
        number of (raw) transitions written (before n-step collapse)."""
        ep = self._episode
        self._episode = []
        if not ep:
            return 0
        count = 0
        # Original trajectory through a fresh n-step window.
        w = self.writer_factory()
        for t, s in enumerate(ep):
            last = t == len(ep) - 1
            w.add(
                self.flatten(s.observation, s.desired_goal),
                s.action,
                s.reward,
                self.flatten(s.next_observation, s.desired_goal),
                terminated=s.terminated,
                truncated=last and truncated and not s.terminated,
            )
            count += 1
        # "future" relabels: each pass substitutes goals drawn from future
        # steps of the same episode (reference main.py:170-171).
        for _ in range(self.k_future):
            w = self.writer_factory()
            # Per-timestep future index f >= t (reference draws uniformly
            # from [t, T)).
            future = np.array(
                [self.rng.integers(t, len(ep)) for t in range(len(ep))]
            )
            for t, s in enumerate(ep):
                goal = ep[future[t]].next_achieved_goal
                r = float(self.compute_reward(s.next_achieved_goal, goal))
                done = self.done_on_success and (r >= self.success_reward)
                last = t == len(ep) - 1
                w.add(
                    self.flatten(s.observation, goal),
                    s.action,  # this step's action (fixes reference main.py:184)
                    r,
                    self.flatten(s.next_observation, goal),
                    terminated=done,
                    truncated=last and not done,
                )
                count += 1
                if done:
                    # Relabeled episode ends at success; later steps belong to
                    # a "different" hindsight episode — start a new window.
                    w = self.writer_factory()
        return count
