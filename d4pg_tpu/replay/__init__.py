"""Host-side experience storage: ring buffers, PER segment trees, n-step, HER.

Replay stays on the TPU-VM host CPU (BASELINE.json north star): actors write
transitions here, the learner streams batches to device and TD priorities
back. Everything is vectorized NumPy (no Python tree walks — contrast the
reference's pointer-chasing ``prioritized_replay_memory.py:61-112``), with an
optional native C++ tree backend (``d4pg_tpu.replay.native``).
"""

from d4pg_tpu.replay.schedules import linear_schedule, noise_scale_schedule
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
from d4pg_tpu.replay.per import PrioritizedReplayBuffer
from d4pg_tpu.replay.nstep_writer import BatchedNStepWriter, NStepWriter
from d4pg_tpu.replay.her import HindsightWriter

__all__ = [
    "linear_schedule",
    "noise_scale_schedule",
    "MinTree",
    "SumTree",
    "ReplayBuffer",
    "Transition",
    "PrioritizedReplayBuffer",
    "BatchedNStepWriter",
    "NStepWriter",
    "HindsightWriter",
]
