"""ctypes bindings for the native C++ segment trees (``native/sumtree.cpp``).

Compiled on first use with g++ into a repo-local build dir (pybind11 is not
available in the image; the C ABI + ctypes keeps the binding dependency-free).
API-compatible with :class:`d4pg_tpu.replay.SumTree` / ``MinTree`` so
:class:`~d4pg_tpu.replay.PrioritizedReplayBuffer` swaps backends via its
``tree_backend`` argument ("auto" prefers native, falls back to NumPy).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "native", "sumtree.cpp")


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native_build")
    os.makedirs(d, exist_ok=True)
    return d


def load_library() -> ctypes.CDLL:
    """Compile (if stale) and load the shared library. Raises on any failure;
    callers with ``tree_backend='auto'`` catch and fall back to NumPy."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = _source_path()
        so = os.path.join(_build_dir(), "libsumtree.so")
        # <= so a fresh checkout (equal mtimes) rebuilds rather than loading
        # a foreign binary; no -march=native for the same reason (the build
        # dir is gitignored, but belt and braces).
        if not os.path.exists(so) or os.path.getmtime(so) <= os.path.getmtime(src):
            # one-time compile; serializing concurrent first-users on the
            # lock is the point (two racing g++ -o same.so corrupt it)
            subprocess.run(  # d4pglint: disable=lock-blocking-call
                ["g++", "-O3", "-shared", "-fPIC", "-o", so, src],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.st_create.restype = ctypes.c_void_p
        lib.st_create.argtypes = [ctypes.c_int64, ctypes.c_int]
        lib.st_destroy.argtypes = [ctypes.c_void_p]
        lib.st_capacity.restype = ctypes.c_int64
        lib.st_capacity.argtypes = [ctypes.c_void_p]
        lib.st_set.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.st_get.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.st_root.restype = ctypes.c_double
        lib.st_root.argtypes = [ctypes.c_void_p]
        lib.st_find_prefix.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
        ]
        lib.st_sample_gather.argtypes = [
            ctypes.c_void_p,                   # sum tree
            ctypes.c_void_p,                   # min tree
            ctypes.POINTER(ctypes.c_double),   # prefixes [n]
            ctypes.c_int64,                    # n = K*B
            ctypes.c_int64,                    # deal_k
            ctypes.c_int64,                    # size (live rows)
            ctypes.c_double,                   # beta
            ctypes.c_void_p,                   # obs ring (f32 or u8)
            ctypes.POINTER(ctypes.c_float),    # action ring
            ctypes.POINTER(ctypes.c_float),    # reward ring
            ctypes.c_void_p,                   # next_obs ring
            ctypes.POINTER(ctypes.c_float),    # discount ring
            ctypes.POINTER(ctypes.c_int64),    # generation ring
            ctypes.c_int64,                    # obs_dim
            ctypes.c_int64,                    # act_dim
            ctypes.c_int,                      # obs_mode
            ctypes.POINTER(ctypes.c_int64),    # idx out
            ctypes.POINTER(ctypes.c_int64),    # gen out
            ctypes.POINTER(ctypes.c_float),    # weights out
            ctypes.c_void_p,                   # obs out
            ctypes.POINTER(ctypes.c_float),    # action out
            ctypes.POINTER(ctypes.c_float),    # reward out
            ctypes.c_void_p,                   # next_obs out
            ctypes.POINTER(ctypes.c_float),    # discount out
        ]
        lib.st_update_priorities.restype = ctypes.c_double
        lib.st_update_priorities.argtypes = [
            ctypes.c_void_p,                   # sum tree
            ctypes.c_void_p,                   # min tree
            ctypes.POINTER(ctypes.c_int64),    # idx [n]
            ctypes.POINTER(ctypes.c_double),   # priorities [n] (|td|+eps)
            ctypes.c_int64,                    # n
            ctypes.POINTER(ctypes.c_int64),    # sample_gen [n] or None
            ctypes.POINTER(ctypes.c_int64),    # current generation ring
            ctypes.c_double,                   # alpha
        ]
        _LIB = lib
        return _LIB


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _f32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _vp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


# obs_mode values for st_sample_gather (must match native/sumtree.cpp)
OBS_F32 = 0      # float32 rows copied as-is
OBS_U8_DECODE = 1  # uint8 rows decoded to float32/255 at gather time
OBS_U8_RAW = 2   # uint8 rows copied raw (uint8 wire format)


class SampleGatherCall:
    """Precomputed ``st_sample_gather`` argument block for one (ring,
    staging-slot) pair.

    Pointer marshaling (``ndarray.ctypes.data_as``) costs ~1-2 µs per
    argument and the call takes 24 of them — at batch 256 that rivals the
    gather itself. The ring arrays and staging buffers are stable
    allocations (that stability is the point of the preallocated staging),
    so every pointer except the per-call ``prefixes`` is computed ONCE here
    and the hot path marshals exactly one array.
    """

    def __init__(
        self,
        sum_tree: "NativeSumTree",
        min_tree: "NativeMinTree",
        obs: np.ndarray,
        action: np.ndarray,
        reward: np.ndarray,
        next_obs: np.ndarray,
        discount: np.ndarray,
        gen: np.ndarray,
        obs_mode: int,
        out: dict,
    ):
        assert out["obs"].dtype == (
            np.float32 if obs_mode != OBS_U8_RAW else np.uint8
        )
        for a in (obs, action, reward, next_obs, discount, gen):
            assert a.flags.c_contiguous
        self._fn = load_library().st_sample_gather
        self._trees = (sum_tree._h, min_tree._h)
        self._ring = (
            _vp(obs), _f32(action), _f32(reward), _vp(next_obs),
            _f32(discount), _i64(gen), obs.shape[1], action.shape[1],
            int(obs_mode),
        )
        self._out = (
            _i64(out["idx"]), _i64(out["gen"]), _f32(out["weights"]),
            _vp(out["obs"]), _f32(out["action"]), _f32(out["reward"]),
            _vp(out["next_obs"]), _f32(out["discount"]),
        )

    def __call__(
        self, prefixes: np.ndarray, deal_k: int, size: int, beta: float
    ) -> None:
        """Run the fused descent+weights+gen-capture+gather. ``prefixes``
        [n] are caller-generated from the NumPy Generator so the seeded
        draw stream matches the NumPy oracle byte-for-byte."""
        self._fn(
            *self._trees, _f64(prefixes), prefixes.size, deal_k, size,
            float(beta), *self._ring, *self._out,
        )


def update_priorities(
    sum_tree: "NativeSumTree",
    min_tree: "NativeMinTree",
    idx: np.ndarray,
    priorities: np.ndarray,
    sample_gen: np.ndarray | None,
    cur_gen: np.ndarray,
    alpha: float,
) -> float:
    """Batched gen-filtered priority write-back; returns the max applied
    pre-α priority (0.0 when every entry was dropped as recycled)."""
    lib = load_library()
    assert idx.flags.c_contiguous and priorities.flags.c_contiguous
    assert idx.size == priorities.size
    sg = _i64(sample_gen) if sample_gen is not None else None
    return lib.st_update_priorities(
        sum_tree._h, min_tree._h, _i64(idx), _f64(priorities), idx.size,
        sg, _i64(cur_gen), float(alpha),
    )


class _NativeTreeBase:
    def __init__(self, capacity: int, is_min: bool):
        self._lib = load_library()
        self._h = self._lib.st_create(capacity, 1 if is_min else 0)
        self.capacity = self._lib.st_capacity(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.st_destroy(self._h)
            self._h = None

    def set(self, indices, values) -> None:
        idx = np.ascontiguousarray(np.atleast_1d(indices), np.int64)
        vals = np.ascontiguousarray(np.atleast_1d(values), np.float64)
        self._lib.st_set(self._h, _i64(idx), _f64(vals), idx.size)

    def get(self, indices) -> np.ndarray:
        idx = np.ascontiguousarray(np.atleast_1d(indices), np.int64)
        out = np.empty(idx.size, np.float64)
        self._lib.st_get(self._h, _i64(idx), _f64(out), idx.size)
        return out

    @property
    def root(self) -> float:
        return self._lib.st_root(self._h)


class NativeSumTree(_NativeTreeBase):
    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=False)

    def sum(self) -> float:
        return self.root

    def find_prefixsum_idx(self, prefixes) -> np.ndarray:
        p = np.ascontiguousarray(np.atleast_1d(prefixes), np.float64)
        out = np.empty(p.size, np.int64)
        self._lib.st_find_prefix(self._h, _f64(p), _i64(out), p.size)
        return out


class NativeMinTree(_NativeTreeBase):
    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=True)

    def min(self) -> float:
        return self.root
