"""ctypes bindings for the native C++ segment trees (``native/sumtree.cpp``).

Compiled on first use with g++ into a repo-local build dir (pybind11 is not
available in the image; the C ABI + ctypes keeps the binding dependency-free).
API-compatible with :class:`d4pg_tpu.replay.SumTree` / ``MinTree`` so
:class:`~d4pg_tpu.replay.PrioritizedReplayBuffer` swaps backends via its
``tree_backend`` argument ("auto" prefers native, falls back to NumPy).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()


def _source_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "native", "sumtree.cpp")


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native_build")
    os.makedirs(d, exist_ok=True)
    return d


def load_library() -> ctypes.CDLL:
    """Compile (if stale) and load the shared library. Raises on any failure;
    callers with ``tree_backend='auto'`` catch and fall back to NumPy."""
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = _source_path()
        so = os.path.join(_build_dir(), "libsumtree.so")
        # <= so a fresh checkout (equal mtimes) rebuilds rather than loading
        # a foreign binary; no -march=native for the same reason (the build
        # dir is gitignored, but belt and braces).
        if not os.path.exists(so) or os.path.getmtime(so) <= os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", so, src],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.st_create.restype = ctypes.c_void_p
        lib.st_create.argtypes = [ctypes.c_int64, ctypes.c_int]
        lib.st_destroy.argtypes = [ctypes.c_void_p]
        lib.st_capacity.restype = ctypes.c_int64
        lib.st_capacity.argtypes = [ctypes.c_void_p]
        lib.st_set.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.st_get.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.st_root.restype = ctypes.c_double
        lib.st_root.argtypes = [ctypes.c_void_p]
        lib.st_find_prefix.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
        ]
        _LIB = lib
        return _LIB


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class _NativeTreeBase:
    def __init__(self, capacity: int, is_min: bool):
        self._lib = load_library()
        self._h = self._lib.st_create(capacity, 1 if is_min else 0)
        self.capacity = self._lib.st_capacity(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.st_destroy(self._h)
            self._h = None

    def set(self, indices, values) -> None:
        idx = np.ascontiguousarray(np.atleast_1d(indices), np.int64)
        vals = np.ascontiguousarray(np.atleast_1d(values), np.float64)
        self._lib.st_set(self._h, _i64(idx), _f64(vals), idx.size)

    def get(self, indices) -> np.ndarray:
        idx = np.ascontiguousarray(np.atleast_1d(indices), np.int64)
        out = np.empty(idx.size, np.float64)
        self._lib.st_get(self._h, _i64(idx), _f64(out), idx.size)
        return out

    @property
    def root(self) -> float:
        return self._lib.st_root(self._h)


class NativeSumTree(_NativeTreeBase):
    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=False)

    def sum(self) -> float:
        return self.root

    def find_prefixsum_idx(self, prefixes) -> np.ndarray:
        p = np.ascontiguousarray(np.atleast_1d(prefixes), np.float64)
        out = np.empty(p.size, np.int64)
        self._lib.st_find_prefix(self._h, _f64(p), _i64(out), p.size)
        return out


class NativeMinTree(_NativeTreeBase):
    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=True)

    def min(self) -> float:
        return self.root
