"""Pure annealing schedules.

Reference ``LinearSchedule`` (``prioritized_replay_memory.py:5-29``) mutates
an internal counter on every ``value()`` call (SURVEY.md quirk #8); here the
schedule is a pure function of the learner step, so it is reproducible,
checkpoint-friendly, and usable inside jit.
"""

from __future__ import annotations


def linear_schedule(step: int, total_steps: int, start: float, end: float) -> float:
    """Linear interpolation start→end over total_steps, clamped after."""
    frac = min(max(float(step) / max(total_steps, 1), 0.0), 1.0)
    return start + frac * (end - start)
