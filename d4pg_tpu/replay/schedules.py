"""Pure annealing schedules.

Reference ``LinearSchedule`` (``prioritized_replay_memory.py:5-29``) mutates
an internal counter on every ``value()`` call (SURVEY.md quirk #8); here the
schedule is a pure function of the learner step, so it is reproducible,
checkpoint-friendly, and usable inside jit.
"""

from __future__ import annotations


def linear_schedule(step: int, total_steps: int, start: float, end: float) -> float:
    """Linear interpolation start→end over total_steps, clamped after."""
    frac = min(max(float(step) / max(total_steps, 1), 0.0), 1.0)
    return start + frac * (end - start)


def noise_scale_schedule(env_steps: int, decay_steps: int, final: float) -> float:
    """Exploration-noise scale at env_steps: 1→final over decay_steps;
    constant 1.0 when decay_steps <= 0 (the reference's effective behavior,
    SURVEY.md quirk #10). Shared by the host trainer and the on-device
    driver so their ε-decay can never diverge."""
    if decay_steps <= 0:
        return 1.0
    return linear_schedule(env_steps, decay_steps, 1.0, final)
