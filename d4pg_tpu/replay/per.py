"""Prioritized experience replay with vectorized proportional sampling.

Capability parity with reference ``PrioritizedReplayBuffer``
(``prioritized_replay_memory.py:224-335``): new samples enter at
``max_priority**alpha``, sampling is proportional to priority mass,
importance weights are ``(p·N)^{−β}`` normalized by the max weight (via the
min tree), priorities update as ``(|td| + ε)^α``. Differences by design:

- batched, stratified sampling in O(log n) vector passes (one tree descent
  per level for the whole batch) instead of per-sample Python recursion;
- β annealing is a pure function of the learner step
  (:func:`d4pg_tpu.replay.linear_schedule`), fixing the reference's stateful
  ``LinearSchedule.value()`` increment side-effect (SURVEY.md quirk #8);
- priorities come from the per-sample distributional CE loss — a true TD
  signal — rather than the reference's distribution-overlap surrogate
  (``ddpg.py:220-222``, quirk #7).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from d4pg_tpu.analysis.ledger import NULL_LEDGER
from d4pg_tpu.replay.schedules import linear_schedule
from d4pg_tpu.replay.segment_tree import MinTree, SumTree
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition


class SampledIndices(NamedTuple):
    """Slot indices plus the write generations they were sampled at.

    The async priority flusher applies updates hundreds of grad steps after
    sampling; with a fast collector the slot may have been recycled by then.
    ``update_priorities`` compares generations and drops write-backs for
    recycled slots — a Hogwild-class staleness is acceptable, stamping a
    *different transition* with this batch's TD priority is not.
    """

    idx: np.ndarray  # [B] int
    gen: np.ndarray  # [B] int64 — ReplayBuffer._gen[idx] at sample time


class PrioritizedReplayBuffer(ReplayBuffer):
    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        action_dim: int,
        alpha: float = 0.6,
        beta0: float = 0.4,
        beta_steps: int = 100_000,
        eps: float = 1e-6,
        tree_backend: str = "auto",
        obs_dtype=np.float32,
        obs_scale=None,
        decode_on_sample: bool = True,
    ):
        super().__init__(
            capacity, obs_dim, action_dim, obs_dtype=obs_dtype,
            obs_scale=obs_scale, decode_on_sample=decode_on_sample,
        )
        assert alpha >= 0
        self.alpha = alpha
        self.beta0 = beta0
        self.beta_steps = beta_steps
        self.eps = eps
        self._use_native = False
        if tree_backend == "native":
            from d4pg_tpu.replay.native import NativeSumTree, NativeMinTree

            self._sum = NativeSumTree(self.capacity)
            self._min = NativeMinTree(self.capacity)
            self._use_native = True
        elif tree_backend == "auto":
            try:
                from d4pg_tpu.replay.native import NativeSumTree, NativeMinTree

                self._sum = NativeSumTree(self.capacity)
                self._min = NativeMinTree(self.capacity)
                self._use_native = True
            except Exception as e:
                # "auto" means degrade, not die — but a 5-10x slower tree
                # backend should never be a silent surprise: log which
                # failure forced the fallback (no g++, bad build dir, ...).
                print(
                    f"[replay] native tree backend unavailable ({e!r}); "
                    "falling back to NumPy trees"
                )
                self._sum = SumTree(self.capacity)
                self._min = MinTree(self.capacity)
        else:
            self._sum = SumTree(self.capacity)
            self._min = MinTree(self.capacity)
        self._max_priority = 1.0
        # sample_block staging: preallocated per-draw-size buffer sets,
        # rotated round-robin so the arrays a dispatch's device_put reads
        # stay stable while the next sample_block of the same size fills a
        # different slot (see _staging_slot).
        self._staging: dict = {}
        # Staging ledger (--debug-guards): generation-tags the rotation so
        # a write into a slot an in-flight dispatch still holds raises
        # instead of corrupting the staged batch. NULL_LEDGER = no-op.
        self._ledger = NULL_LEDGER

    def add_batch(self, t: Transition) -> np.ndarray:
        idx = super().add_batch(t)
        p = self._max_priority**self.alpha
        with self._lock:
            self._sum.set(idx, np.full(idx.shape, p))
            self._min.set(idx, np.full(idx.shape, p))
        return idx

    def beta(self, step: int) -> float:
        return linear_schedule(step, self.beta_steps, self.beta0, 1.0)

    def _draw(self, batch_size: int, rng: np.random.Generator, step: int):
        """One locked stratified draw: (idx, IS weights, generation stamps).

        Caller must NOT hold the lock. One tree descent per level for the
        whole index vector (NumPy or C++ backend) — ``batch_size`` here may
        be K·B for a multi-batch draw; the descent is the same O(log n)
        vector passes either way.
        """
        with self._lock:
            total = self._sum.sum()
            # Stratified: one uniform draw per equal-mass segment
            # (reference samples one uniform per draw, prioritized_replay_memory.py:263).
            bounds = np.linspace(0.0, total, batch_size + 1)
            prefixes = rng.uniform(bounds[:-1], bounds[1:])
            # Guard the float edge where prefix == total would fall off the
            # last nonzero leaf.
            prefixes = np.minimum(prefixes, np.nextafter(total, 0.0))
            idx = self._sum.find_prefixsum_idx(prefixes)
            idx = np.minimum(idx, self._size - 1)
            p = self._sum.get(idx) / total
            beta = self.beta(step)
            weights = (p * self._size) ** (-beta)
            min_p = self._min.min() / total
            max_w = (min_p * self._size) ** (-beta)
            weights = weights / max_w
            # Capture generations BEFORE gather: if a writer recycles a slot
            # in between, the stale stamp makes update_priorities drop that
            # entry (conservative) rather than mis-stamp the new transition.
            # The copy IS the capture (a view would track the writer) and
            # must survive past the lock — small [B] int64 by design.
            gen = self._gen[idx].copy()  # d4pglint: disable=hot-path-alloc
        return idx, weights.astype(np.float32), gen

    def sample(self, batch_size: int, rng: np.random.Generator, step: int = 0):
        """Stratified proportional sample.

        Returns a batch dict with extra keys ``indices`` (for priority
        write-back) and ``weights`` (IS weights, max-normalized).
        """
        idx, weights, gen = self._draw(batch_size, rng, step)
        batch = dict(self.gather(idx))
        batch["indices"] = SampledIndices(idx, gen)
        batch["weights"] = weights
        return batch

    def sample_many(
        self, batch_size: int, k: int, rng: np.random.Generator, step: int = 0
    ) -> list[dict]:
        """K stratified batches from ONE locked K·B-wide tree descent + ONE
        ring gather — the host half of the fused-dispatch / prefetch path
        (k separate :meth:`sample` calls pay k lock round-trips and k
        gathers; this is one of each, using the same batched descent the
        C++ sum tree vectorizes). The K·B equal-mass segments are dealt
        round-robin (batch i takes draws i, i+k, i+2k, …), so every batch
        holds B draws evenly spread across the WHOLE priority mass — a
        strictly finer stratification than B segments, never a contiguous
        1/K slice of it. All K batches share one ``step`` (one β) and one
        generation capture — the semantics of sampling K batches
        back-to-back.
        """
        idx, weights, gen = self._draw(batch_size * k, rng, step)
        flat = self.gather(idx)
        out = []
        for i in range(k):
            sl = slice(i, None, k)
            b = {key: v[sl] for key, v in flat.items()}
            b["indices"] = SampledIndices(idx[sl], gen[sl])
            b["weights"] = weights[sl]
            out.append(b)
        return out

    # How many preallocated staging buffer sets sample_block rotates
    # through per draw size. 2 covers the prefetch double buffer (batch N+1
    # staged while N's in flight); the third is slack for an H2D transfer
    # that outlives a full dispatch on a slow link.
    STAGING_SLOTS = 3

    def _native_obs_mode(self) -> int:
        from d4pg_tpu.replay import native as _native

        if not self._quantized:
            return _native.OBS_F32
        return (
            _native.OBS_U8_DECODE
            if self._decode_on_sample
            else _native.OBS_U8_RAW
        )

    def _staging_slot(self, n: int) -> tuple[dict, int]:
        """Next staging buffer set for an n-row draw plus its rotation
        position (allocated once per size, then reused round-robin — the
        zero-alloc half of the native data plane: ``jax.device_put``
        always reads stable, caller-owned memory that no GC or resize can
        move)."""
        entry = self._staging.get(n)
        if entry is None:
            obs_dtype = (
                self.obs_dtype
                if self._quantized and not self._decode_on_sample
                else np.float32
            )
            obs_dim = self.obs.shape[1]
            act_dim = self.action.shape[1]

            def mk():
                slot = {
                    "idx": np.empty(n, np.int64),
                    "gen": np.empty(n, np.int64),
                    "weights": np.empty(n, np.float32),
                    "obs": np.empty((n, obs_dim), obs_dtype),
                    "action": np.empty((n, act_dim), np.float32),
                    "reward": np.empty(n, np.float32),
                    "next_obs": np.empty((n, obs_dim), obs_dtype),
                    "discount": np.empty(n, np.float32),
                }
                if self._use_native:
                    from d4pg_tpu.replay import native as _native

                    # ctypes pointers marshaled once per slot, not per call
                    slot["_call"] = _native.SampleGatherCall(
                        self._sum, self._min, self.obs, self.action,
                        self.reward, self.next_obs, self.discount,
                        self._gen, self._native_obs_mode(), slot,
                    )
                return slot

            entry = {
                "slots": [mk() for _ in range(self.STAGING_SLOTS)],
                "next": 0,
                # ledger group precomputed: no per-call f-string on the
                # hot path (NULL_LEDGER would only discard it)
                "group": f"per.sample_block[n={n}]",
            }
            self._staging[n] = entry
        pos = entry["next"]
        # Declares the overwrite to the ledger BEFORE the fill: trips here,
        # at the reuse site, if a dispatch staged from this slot is still
        # in flight (hold not yet released by the consumer).
        self._ledger.write(entry["group"], pos)
        slot = entry["slots"][pos]
        entry["next"] = (pos + 1) % len(entry["slots"])
        return slot, pos

    def set_ledger(self, ledger) -> None:
        """Attach a :class:`~d4pg_tpu.analysis.ledger.StagingLedger`
        (--debug-guards); the trainer releases the returned holds at each
        dispatch's priority-fetch synchronization point."""
        self._ledger = ledger if ledger is not None else NULL_LEDGER

    def sample_block(
        self, batch_size: int, k: int, rng: np.random.Generator, step: int = 0
    ) -> dict:
        """K stratified batches as contiguous [K, B, ...] blocks from ONE
        backend call — the host half of a fused dispatch, with zero
        steady-state allocation.

        Native backend: a single C call does the K·B prefix-sum descents,
        IS weights, generation capture, AND the row gather of every field
        straight into the preallocated staging slot — no per-field fancy
        indexing, no ``np.stack``, lock held only for that call. NumPy
        backend: the seeded oracle — same draws (identical RNG consumption:
        one ``uniform`` of size K·B over the stratified bounds), same dealt
        layout, built through :meth:`_draw` + :meth:`gather`.

        Batch i of the block equals ``sample_many``'s batch i exactly
        (round-robin dealing: draw j lands at block[j % k, j // k]). The
        field arrays are views of a reused staging slot — valid until
        ``STAGING_SLOTS - 1`` further same-size calls; ``indices`` holds
        fresh copies safe to retain for async priority write-back.

        Unlike :meth:`sample`, concurrent ``sample_block`` calls must be
        externally serialized (the trainer holds its buffer lock): the
        staging-slot rotation is what makes the hot path zero-alloc, and
        it hands out one slot per call, not per thread.
        """
        n = batch_size * k
        st, slot_pos = self._staging_slot(n)
        if self._use_native:
            with self._lock:
                total = self._sum.sum()
                # Same stratified-draw recipe as _draw, byte-for-byte: the
                # RNG stream is a determinism contract (tests pin it).
                bounds = np.linspace(0.0, total, n + 1)
                prefixes = rng.uniform(bounds[:-1], bounds[1:])
                prefixes = np.minimum(prefixes, np.nextafter(total, 0.0))
                st["_call"](prefixes, k, self._size, self.beta(step))
        else:
            idx, weights, gen = self._draw(n, rng, step)
            # Deal draw j to block row (j % k)*B + j//k: order[r] enumerates
            # the draw that lands at flattened block position r.
            order = np.arange(n).reshape(batch_size, k).T.reshape(-1)
            idx = idx[order]
            st["idx"][:] = idx
            st["gen"][:] = gen[order]
            st["weights"][:] = weights[order]
            flat = self.gather(idx)
            for key, v in flat.items():
                st[key][...] = v
        block = lambda a: a.reshape((k, batch_size) + a.shape[1:])
        out = {
            key: block(st[key])
            for key in ("obs", "action", "reward", "next_obs", "discount")
        }
        out["weights"] = block(st["weights"])
        out["indices"] = SampledIndices(
            # fresh small copies ARE the contract: idx/gen outlive the
            # staging rotation in the async priority flusher (docstring)
            block(st["idx"]).copy(), block(st["gen"]).copy()  # d4pglint: disable=hot-path-alloc
        )
        if self._ledger is not NULL_LEDGER:
            # Hand the consumer a hold on this slot; it must release at the
            # point that synchronizes the dispatch's read of the staged
            # arrays (the trainer: its priority D2H fetch). Key is absent
            # with guards off so default behavior is byte-identical.
            out["_staging_hold"] = self._ledger.hold(
                self._staging[n]["group"], slot_pos
            )
        return out

    def sample_block_indices(
        self, batch_size: int, k: int, rng: np.random.Generator, step: int = 0
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The index half of :meth:`sample_block`, with NO row gather:
        ``(idx [K, B] int64, weights [K, B] f32, gen [K, B] int64)``.

        This is the hybrid ``replay_placement`` data plane (ROADMAP item
        1): the host sum-tree still owns the PER descent, but only the
        tiny index/weight blocks cross the link — rows are gathered
        on-device from the HBM ring mirror by the megastep.

        Determinism contract (frozen-literal-tested): consumes the
        identical RNG stream as :meth:`sample_block` — one
        ``Generator.uniform`` of size K·B over the equal-mass stratified
        bounds — and deals draws to the identical round-robin block
        layout, so flipping ``replay_placement`` between ``host`` and
        ``hybrid`` moves no seeded run's index sequence. Returns fresh
        arrays (no staging rotation: [K, B] index blocks are link-trivial
        and must outlive the async priority flusher anyway).
        """
        n = batch_size * k
        idx, weights, gen = self._draw(n, rng, step)
        # Same dealing as sample_block: draw j lands at block[j % k, j // k].
        order = np.arange(n).reshape(batch_size, k).T.reshape(-1)
        block = lambda a: a[order].reshape(k, batch_size)
        return block(idx), block(weights), block(gen)

    def _snapshot_arrays(self) -> dict:
        data = super()._snapshot_arrays()
        n = self._size
        data["tree_priorities"] = self._sum.get(np.arange(n))  # α-exponentiated
        data["max_priority"] = np.asarray(self._max_priority)
        return data

    def _restore_arrays(self, data) -> int:
        n = super()._restore_arrays(data)
        if "tree_priorities" in data:
            idx = np.arange(n)
            pa = np.asarray(data["tree_priorities"], np.float64)
            # A snapshot can catch a row between the ring write and the tree
            # write (two lock acquisitions in add_batch): its leaf reads as
            # the sum tree's neutral 0. Restored as-is, 0 would poison the
            # min tree (min()==0 → all IS weights collapse) with no repair
            # path since a never-sampled row never gets a priority update.
            # Give such rows the max-priority seed add_batch would have —
            # flooring at the minimum would instead starve them forever.
            self._max_priority = float(np.asarray(data["max_priority"]).item())
            pa = np.where(pa <= 0.0, self._max_priority**self.alpha, pa)
            self._sum.set(idx, pa)
            self._min.set(idx, pa)
        else:  # snapshot from a uniform buffer: seed with max priority
            idx = np.arange(n)
            p = np.full(n, self._max_priority**self.alpha)
            self._sum.set(idx, p)
            self._min.set(idx, p)
        # Clear any stale mass beyond the snapshot (restoring into a
        # previously used buffer): leftover leaves would draw prefix-sum
        # samples that the idx clamp folds onto row n-1, oversampling it.
        if n < self.capacity:
            tail = np.arange(n, self.capacity)
            self._sum.set(tail, np.zeros(tail.shape))
            self._min.set(tail, np.full(tail.shape, np.inf))
        return n

    def update_priorities(self, indices, priorities: np.ndarray) -> None:
        """(|priority| + ε)^α into both trees (reference ``:315-335``).

        ``indices`` may be a raw index array or the :class:`SampledIndices`
        that :meth:`sample` returned; with the latter, entries whose slot was
        recycled since sampling (write generation changed) are dropped.
        Arrays of any shape are accepted (a fused dispatch writes back
        [K, B] blocks); they are flattened elementwise.

        Native backend: the |td|+ε pass runs OUTSIDE the lock and the
        generation filter + ^α + both tree updates + max-priority reduce
        are ONE C call — the lock scope is microseconds regardless of batch
        width, so write-back coalescing never stalls concurrent samplers.
        """
        priorities = np.abs(np.asarray(priorities, np.float64)) + self.eps
        assert np.all(priorities > 0)
        if isinstance(indices, SampledIndices):
            idx, sample_gen = indices.idx, indices.gen
        else:
            idx, sample_gen = indices, None
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        pri = np.ascontiguousarray(priorities.ravel())
        assert idx.size == pri.size
        if sample_gen is not None:
            sample_gen = np.ascontiguousarray(
                np.asarray(sample_gen, np.int64).ravel()
            )
        if self._use_native:
            from d4pg_tpu.replay import native as _native

            with self._lock:
                mx = _native.update_priorities(
                    self._sum, self._min, idx, pri, sample_gen, self._gen,
                    self.alpha,
                )
                if mx > 0.0:  # 0.0 == every entry dropped as recycled
                    self._max_priority = max(self._max_priority, mx)
            return
        with self._lock:
            if sample_gen is not None:
                live = self._gen[idx] == sample_gen
                if not live.all():
                    idx = idx[live]
                    pri = pri[live]
                    if idx.size == 0:
                        return
            pa = pri**self.alpha
            self._sum.set(idx, pa)
            self._min.set(idx, pa)
            self._max_priority = max(self._max_priority, float(pri.max()))
