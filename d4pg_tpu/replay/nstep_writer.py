"""n-step transition accumulation at insert time.

The reference *intended* this (dead code at ``replay_memory.py:21-58`` and
``main.py:209-242``, SURVEY.md quirk #3) and its active projection then used
the wrong discount (quirk #5). Here n-step is a real feature: the writer
maintains a sliding window per actor, emits ``(s_t, a_t, R_t^{(m)},
s_{t+m}, γ^m·(1−terminal))`` transitions, and handles episode ends exactly:

- termination: every partial window flushes with bootstrap discount 0;
- truncation (timeout): partial windows flush with discount γ^m — the value
  bootstrap is still valid at a timeout cut.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from d4pg_tpu.replay.uniform import ReplayBuffer, Transition


class NStepWriter:
    """Per-actor n-step window over a target buffer (uniform or PER)."""

    def __init__(self, buffer: ReplayBuffer, n: int, gamma: float):
        assert n >= 1
        self.buffer = buffer
        self.n = n
        self.gamma = gamma
        self._window: deque = deque()

    def _emit_front(self, next_obs: np.ndarray, terminal: bool, m: int) -> None:
        obs, action, _ = self._window[0]
        ret = 0.0
        for k, (_, _, r) in enumerate(self._window):
            ret += (self.gamma**k) * r
        discount = 0.0 if terminal else self.gamma**m
        self.buffer.add(obs, action, ret, next_obs, discount)
        self._window.popleft()

    def add(self, obs, action, reward, next_obs, terminated: bool, truncated: bool = False) -> None:
        """Feed one raw env step; emits ready n-step transitions to the buffer."""
        self._window.append((np.asarray(obs), np.asarray(action), float(reward)))
        if len(self._window) == self.n:
            self._emit_front(np.asarray(next_obs), terminated, self.n)
        if terminated or truncated:
            # Flush remaining partial windows against the episode's last state.
            while self._window:
                m = len(self._window)
                self._emit_front(np.asarray(next_obs), terminated, m)

    def reset(self) -> None:
        """Drop any un-flushed window (e.g. on actor restart)."""
        self._window.clear()


class BatchedNStepWriter:
    """N-wide n-step writer for the host actor pool: one vectorized window
    append and ONE ``buffer.add_batch`` per pool step.

    The per-actor :class:`NStepWriter` loop costs N Python ``add`` calls —
    each a deque walk plus a single-row ``buffer.add`` with its own lock
    round-trip — per pool step; at 64 actors that loop IS the ingest wall.
    Here the N sliding windows live in preallocated circular arrays
    ``[N, n, ...]``, the steady-state emit (every window full, no episode
    end) is a handful of vectorized ops writing into reused emit buffers,
    and all ready transitions enter the buffer as one N-row block.

    Emission semantics per actor match :class:`NStepWriter` exactly
    (full-window emit with m=n, termination flush with discount 0,
    truncation flush with discount γ^m); episode-end steps fall back to an
    ordered per-actor path, so only the ring INSERTION ORDER across actors
    differs from the sequential loop (contents are identical — tested).
    """

    def __init__(self, buffer: ReplayBuffer, num_actors: int, n: int, gamma: float):
        assert n >= 1 and num_actors >= 1
        self.buffer = buffer
        self.num_actors = num_actors
        self.n = n
        self.gamma = gamma
        self._gamma_pows = gamma ** np.arange(n)  # float64
        self._start = np.zeros(num_actors, np.int64)
        self._len = np.zeros(num_actors, np.int64)
        self._obs_w = None  # allocated lazily: dims come from the first step

    def _alloc(self, obs: np.ndarray, action: np.ndarray) -> None:
        N, n = self.num_actors, self.n
        self._obs_w = np.zeros((N, n) + obs.shape[1:], np.float32)
        self._act_w = np.zeros((N, n) + action.shape[1:], np.float32)
        # float64 rewards so the n-step return accumulates at the precision
        # of the scalar writer's Python-float loop (bit-identical emits).
        self._rew_w = np.zeros((N, n), np.float64)
        # reusable steady-state emit buffers (zero-alloc fast path)
        self._e_obs = np.empty((N,) + obs.shape[1:], np.float32)
        self._e_act = np.empty((N,) + action.shape[1:], np.float32)
        self._e_ret = np.empty(N, np.float64)
        self._e_disc = np.empty(N, np.float64)

    def _front_return(self, rows: np.ndarray, m: int) -> np.ndarray:
        """Σ_{k<m} γ^k·r_k over each listed actor's window front, with the
        scalar writer's k-ascending accumulation order."""
        ret = np.zeros(len(rows), np.float64)
        start = self._start[rows]
        for k in range(m):
            ret += self._gamma_pows[k] * self._rew_w[rows, (start + k) % self.n]
        return ret

    def add_batch(
        self,
        obs: np.ndarray,
        actions: np.ndarray,
        rewards: np.ndarray,
        next_obs: np.ndarray,
        terminated: np.ndarray,
        truncated: np.ndarray,
        active: np.ndarray = None,
    ) -> int:
        """Feed one synchronized pool step for all N actors; emits every
        ready/flushed n-step transition as ONE ``add_batch``. Returns the
        number of transitions emitted.

        ``active`` (bool [N], optional) masks rows that did NOT step this
        call (supervised-pool worker down/rejoining/quarantined): masked
        actors' windows are untouched — their in-flight episode was
        either already dropped whole (:meth:`drop_actor`) or resumes on a
        later step. ``None`` means all rows stepped (the steady state)."""
        obs = np.asarray(obs)
        actions = np.asarray(actions)
        rewards = np.asarray(rewards, np.float64)
        next_obs = np.asarray(next_obs)
        terminated = np.asarray(terminated, bool)
        truncated = np.asarray(truncated, bool)
        N, n = self.num_actors, self.n
        if self._obs_w is None:
            self._alloc(obs, actions)
        if active is not None and not active.all():
            # Degraded step (rare): ordered per-actor path over the live
            # rows only — identical per-actor emission semantics, one
            # add_batch for the whole step.
            cols: list[tuple] = []
            pos = (self._start + self._len) % n
            for i in range(N):
                if not active[i]:
                    continue
                self._obs_w[i, pos[i]] = obs[i]
                self._act_w[i, pos[i]] = actions[i]
                self._rew_w[i, pos[i]] = rewards[i]
                self._len[i] += 1
                if self._len[i] == n:
                    cols.append(self._pop_front(i, next_obs[i], terminated[i]))
                if terminated[i] or truncated[i]:
                    while self._len[i] > 0:
                        cols.append(
                            self._pop_front(i, next_obs[i], terminated[i])
                        )
            if not cols:
                return 0
            self.buffer.add_batch(
                Transition(
                    np.stack([c[0] for c in cols]),
                    np.stack([c[1] for c in cols]),
                    np.asarray([c[2] for c in cols]),
                    np.stack([c[3] for c in cols]),
                    np.asarray([c[4] for c in cols]),
                )
            )
            return len(cols)
        rows = np.arange(N)
        pos = (self._start + self._len) % n
        self._obs_w[rows, pos] = obs
        self._act_w[rows, pos] = actions
        self._rew_w[rows, pos] = rewards
        self._len += 1
        done = terminated | truncated
        if not done.any():
            ready = self._len == n
            if not ready.any():
                return 0  # warmup: no window full yet
            all_ready = ready.all()
            r = rows if all_ready else rows[ready]
            k = len(r)
            start = self._start[r]
            self._e_obs[:k] = self._obs_w[r, start]
            self._e_act[:k] = self._act_w[r, start]
            self._e_ret[:k] = self._front_return(r, n)
            # no episode ended on this branch → bootstrap always survives
            self._e_disc[:k] = self.gamma**n
            self._start[r] = (start + 1) % n
            self._len[r] -= 1
            self.buffer.add_batch(
                Transition(
                    self._e_obs[:k], self._e_act[:k], self._e_ret[:k],
                    next_obs if all_ready else next_obs[r], self._e_disc[:k],
                )
            )
            return k
        # Episode boundary somewhere: ordered per-actor emit + flush
        # (identical per-actor sequence to NStepWriter.add), still one
        # add_batch for the whole step.
        cols: list[tuple] = []
        for i in range(N):
            if self._len[i] == n:
                cols.append(self._pop_front(i, next_obs[i], terminated[i]))
            if done[i]:
                while self._len[i] > 0:
                    cols.append(self._pop_front(i, next_obs[i], terminated[i]))
        if not cols:
            return 0
        self.buffer.add_batch(
            Transition(
                np.stack([c[0] for c in cols]),
                np.stack([c[1] for c in cols]),
                np.asarray([c[2] for c in cols]),
                np.stack([c[3] for c in cols]),
                np.asarray([c[4] for c in cols]),
            )
        )
        return len(cols)

    def _pop_front(self, i: int, next_obs_i: np.ndarray, terminal: bool):
        m = int(self._len[i])
        ret = float(self._front_return(np.array([i]), m)[0])
        s = self._start[i]
        row = (
            self._obs_w[i, s].copy(),
            self._act_w[i, s].copy(),
            ret,
            next_obs_i,
            0.0 if terminal else self.gamma**m,
        )
        self._start[i] = (s + 1) % self.n
        self._len[i] -= 1
        return row

    def drop_actor(self, i: int) -> None:
        """Drop actor ``i``'s in-flight window WHOLE (supervised-pool
        worker failure): the episode tore mid-window, so emitting any of
        it would store transitions whose tail the env never produced."""
        self._start[i] = 0
        self._len[i] = 0

    def reset(self) -> None:
        """Drop all unfinished windows (e.g. on pool restart)."""
        self._start[:] = 0
        self._len[:] = 0
