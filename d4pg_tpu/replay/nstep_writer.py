"""n-step transition accumulation at insert time.

The reference *intended* this (dead code at ``replay_memory.py:21-58`` and
``main.py:209-242``, SURVEY.md quirk #3) and its active projection then used
the wrong discount (quirk #5). Here n-step is a real feature: the writer
maintains a sliding window per actor, emits ``(s_t, a_t, R_t^{(m)},
s_{t+m}, γ^m·(1−terminal))`` transitions, and handles episode ends exactly:

- termination: every partial window flushes with bootstrap discount 0;
- truncation (timeout): partial windows flush with discount γ^m — the value
  bootstrap is still valid at a timeout cut.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from d4pg_tpu.replay.uniform import ReplayBuffer


class NStepWriter:
    """Per-actor n-step window over a target buffer (uniform or PER)."""

    def __init__(self, buffer: ReplayBuffer, n: int, gamma: float):
        assert n >= 1
        self.buffer = buffer
        self.n = n
        self.gamma = gamma
        self._window: deque = deque()

    def _emit_front(self, next_obs: np.ndarray, terminal: bool, m: int) -> None:
        obs, action, _ = self._window[0]
        ret = 0.0
        for k, (_, _, r) in enumerate(self._window):
            ret += (self.gamma**k) * r
        discount = 0.0 if terminal else self.gamma**m
        self.buffer.add(obs, action, ret, next_obs, discount)
        self._window.popleft()

    def add(self, obs, action, reward, next_obs, terminated: bool, truncated: bool = False) -> None:
        """Feed one raw env step; emits ready n-step transitions to the buffer."""
        self._window.append((np.asarray(obs), np.asarray(action), float(reward)))
        if len(self._window) == self.n:
            self._emit_front(np.asarray(next_obs), terminated, self.n)
        if terminated or truncated:
            # Flush remaining partial windows against the episode's last state.
            while self._window:
                m = len(self._window)
                self._emit_front(np.asarray(next_obs), terminated, m)

    def reset(self) -> None:
        """Drop any un-flushed window (e.g. on actor restart)."""
        self._window.clear()
