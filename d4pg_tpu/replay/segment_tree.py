"""Array-based segment trees with batched, vectorized operations.

Capability parity with the reference's ``SegmentTree`` /
``SumSegmentTree.find_prefixsum_idx`` / ``MinSegmentTree``
(``prioritized_replay_memory.py:33-162``) — but flat NumPy arrays and
level-synchronous vector ops instead of per-element recursive Python, so a
256-sample PER batch costs ~log2(capacity) vectorized passes total. This is
what lets host-side PER keep up with a TPU-speed learner (SURVEY.md §7 hard
part (b)).

Layout: ``tree[1]`` is the root; leaves live at ``[capacity, 2*capacity)``.
"""

from __future__ import annotations

import numpy as np


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _SegmentTreeBase:
    def __init__(self, capacity: int, neutral: float, dtype=np.float64):
        self.capacity = _next_pow2(capacity)
        self.neutral = neutral
        self.tree = np.full(2 * self.capacity, neutral, dtype=dtype)
        self.depth = int(np.log2(self.capacity))

    def _combine(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def set(self, indices: np.ndarray, values: np.ndarray) -> None:
        """Batched leaf assignment + ancestor repair, O(log n) vector passes.

        Duplicate indices are allowed (last write wins, NumPy assignment
        semantics); ancestor recomputation from children is idempotent so
        shared ancestors are handled for free.
        """
        indices = np.atleast_1d(np.asarray(indices, np.int64))
        values = np.atleast_1d(values)
        pos = indices + self.capacity
        self.tree[pos] = values
        for _ in range(self.depth):
            pos = np.unique(pos // 2)
            self.tree[pos] = self._combine(self.tree[2 * pos], self.tree[2 * pos + 1])

    def get(self, indices) -> np.ndarray:
        return self.tree[np.asarray(indices, np.int64) + self.capacity]

    @property
    def root(self) -> float:
        return float(self.tree[1])


class SumTree(_SegmentTreeBase):
    """Sum-reduction tree supporting batched proportional sampling."""

    def __init__(self, capacity: int, dtype=np.float64):
        super().__init__(capacity, neutral=0.0, dtype=dtype)

    def _combine(self, a, b):
        return a + b

    def sum(self) -> float:
        return self.root

    def find_prefixsum_idx(self, prefixes: np.ndarray) -> np.ndarray:
        """Vectorized batch descent: for each prefix mass, the leaf index i
        with cumsum[0..i-1] <= prefix < cumsum[0..i] (reference
        ``prioritized_replay_memory.py:126-149``, one tree walk per sample —
        here one vector op per level for the whole batch)."""
        prefixes = np.asarray(prefixes, self.tree.dtype).copy()
        idx = np.ones(prefixes.shape[0], np.int64)
        for _ in range(self.depth):
            left = self.tree[2 * idx]
            # >= so a prefix landing exactly on a cumsum boundary selects the
            # next leaf, and zero-mass leaves are skipped.
            go_right = prefixes >= left
            prefixes -= np.where(go_right, left, 0.0)
            idx = 2 * idx + go_right
        return idx - self.capacity


class MinTree(_SegmentTreeBase):
    """Min-reduction tree for max-IS-weight normalization."""

    def __init__(self, capacity: int, dtype=np.float64):
        super().__init__(capacity, neutral=np.inf, dtype=dtype)

    def _combine(self, a, b):
        return np.minimum(a, b)

    def min(self) -> float:
        return self.root
