"""Device-resident PER: the priority structure lives in HBM (ROADMAP item 2).

Until this module, prioritized replay was the one scenario that still
tethered the learner to the host: ``--replay-placement device`` downgraded
PER to uniform, and ``hybrid`` shipped [K, B] index/IS-weight blocks from
the host sum-tree every dispatch — dragging the host lock and staging
machinery along. Here the sum tree itself moves on-chip: a log-depth
segment tree over the ring's ``[capacity]`` α-exponentiated priorities,
stored as the same flat ``[2L]`` array layout the host trees use
(``replay/segment_tree.py``: root at index 1, leaves at ``[L, 2L)`` with
``L = next_pow2(capacity)``), so stratified descent, IS-weight
computation, and post-step priority write-back all happen INSIDE the
fused megastep (``runtime/megastep.py:megastep_device_per_body``) with
zero host operands in steady state.

Layout and semantics mirror the host ``PrioritizedReplayBuffer`` exactly
— same stratified equal-mass segments, same round-robin block dealing,
same ``(|td| + ε)^α`` write-back, same max-priority seed for new rows —
but in f32 (device arithmetic) instead of the host trees' f64. The host
sum-tree stays the SEEDED PARITY ORACLE (the PR-6 discipline): the
device draw's prefixes are reproducible on host from the same key
(threefry is backend-deterministic), so tests descend the host tree with
the identical prefixes and pin identical index draws, f32-close IS
weights, and f32-close post-writeback priorities — frozen-literal-pinned
on both host tree backends (``tests/test_device_per.py``).

Sharding (dp): each shard owns a SHARD-LOCAL subtree over its
``capacity/dp`` striped ring rows (``device_ring.striped_perm`` — the
same layout the sharded ring uses, so tree row ``i`` of shard ``d`` IS
ring row ``i`` of shard ``d``), and the only cross-shard arithmetic is a
tiny replicated root combine — fixed-order reductions over the
``all_gather``-ed per-shard roots/minima, the PR-9 ``det_pmean``
discipline — which is what makes the 8-way mesh bit-exact against the
single-device vmap oracle. Each shard contributes ``batch/dp`` draws
proportional to its LOCAL mass (the fixed per-shard batch shape the
megastep needs); the true sampling probability of row ``i`` on shard
``d`` is therefore ``p_i / (D · T_d)`` and the IS weights correct for
exactly that two-level distribution, normalized by the GLOBAL max
weight. Striped ingest keeps shard masses statistically identical, so
the scheme converges to global-mass PER as priorities mix; at ``dp=1``
it reduces to the host formula term for term.

Backend ladder (the ``ops/pallas_projection.py`` convention): the jnp
log-depth gather descent here is the reference program; a Pallas
blocked-prefix-scan kernel (``ops/pallas_tree.py``) is selectable via
``TrainConfig.device_tree_backend="pallas"`` with the XLA path kept as
its equivalence oracle.

The traced functions here are listed in d4pglint's ``MEGASTEP_FUNCTIONS``
manifest: host numpy / ``.item()`` inside them would smuggle a per-step
host sync into the zero-transfer loop.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DevicePerTree(NamedTuple):
    """The device priority structure: ``sums`` is ``[S, 2L]`` f32 — one
    flat segment tree per dp shard lane (S = dp, or 1 unsharded), root at
    ``[lane, 1]``, leaves at ``[lane, L:2L)`` over the shard's LOCAL ring
    rows; ``max_priority`` is the replicated pre-α running maximum (the
    host buffer's ``_max_priority`` twin) that seeds newly ingested rows
    at ``max_priority**α``."""

    sums: jax.Array          # [S, 2L] f32
    max_priority: jax.Array  # scalar f32, replicated


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def tree_width(local_capacity: int) -> int:
    """Flat-array width of one lane's tree: ``2 * next_pow2(local_cap)``."""
    return 2 * next_pow2(local_capacity)


def device_per_init(
    capacity: int, *, n_shards: int = 1, mesh=None, max_priority: float = 1.0
) -> DevicePerTree:
    """A zero-mass tree for a ``capacity``-row ring. With ``mesh``, the
    lane axis is placed over "dp" (``parallel/partition.py:PER_TREE_RULES``)
    — shard-local subtrees, replicated max-priority scalar. device_put
    COMMITS the arrays for the same jit-cache-key reason as
    ``device_ring_init``."""
    if capacity % n_shards:
        raise ValueError(
            f"device PER tree: capacity {capacity} not divisible by "
            f"dp={n_shards}"
        )
    width = tree_width(capacity // n_shards)
    tree = DevicePerTree(
        sums=jnp.zeros((n_shards, width), jnp.float32),
        max_priority=jnp.float32(max_priority),
    )
    return _place_tree(tree, mesh)


def _place_tree(tree: DevicePerTree, mesh) -> DevicePerTree:
    """Commit a host-built tree to device: plain device_put unsharded, or
    per-leaf NamedSharding placement from ``PER_TREE_RULES`` on a mesh —
    THE one placement path (init and snapshot-restore share it, so the
    two can never place differently)."""
    if mesh is None:
        return jax.device_put(tree)
    from jax.sharding import NamedSharding

    from d4pg_tpu.parallel.partition import tree_partition_specs

    specs = tree_partition_specs(tree)
    if jax.process_count() > 1:
        # Collective-free multi-host placement (the host-built tree is
        # SPMD-identical on every process — same sidecar bytes / same
        # seeds): device_put onto non-addressable shardings fires a
        # per-leaf agreement broadcast that deadlocks against in-flight
        # transfer programs under gloo (distributed.stage_global).
        from d4pg_tpu.parallel.distributed import stage_global

        return DevicePerTree(
            *(stage_global(mesh, spec, leaf) for leaf, spec in zip(tree, specs))
        )
    return DevicePerTree(
        *(
            jax.device_put(leaf, NamedSharding(mesh, spec))
            for leaf, spec in zip(tree, specs)
        )
    )


# ----------------------------------------------------- per-lane traced ops
def repair_ancestors(sums_lane: jax.Array, pos: jax.Array) -> jax.Array:
    """Recompute every ancestor of the leaf positions ``pos`` (``[n]``
    int32; out-of-bounds entries ``>= 2L`` stay out of bounds and are
    dropped), one gather+scatter per level — the log-depth half of every
    tree write. Duplicate parents all write the identical
    children-derived value, so the scatter is deterministic."""
    width = sums_lane.shape[0]
    depth = (width // 2).bit_length() - 1
    for _ in range(depth):
        # Pads keep pointing past the end instead of dividing back into
        # range (capacity//2 would alias a real node).
        pos = jnp.where(pos < width, pos // 2, width)
        vals = sums_lane[2 * pos] + sums_lane[2 * pos + 1]
        sums_lane = sums_lane.at[pos].set(vals, mode="drop")
    return sums_lane


def set_leaves(
    sums_lane: jax.Array, slots: jax.Array, values: jax.Array,
    local_capacity: int,
) -> jax.Array:
    """Assign leaf values at ring slots (``slots`` int32; pad entries
    ``>= local_capacity`` are dropped — the ring ingest's pad-slot
    convention) and repair ancestors. ``values`` may be a scalar (the
    max-priority ingest seed) or ``[n]``."""
    width = sums_lane.shape[0]
    half = width // 2
    pos = jnp.where(slots < local_capacity, slots + half, width).astype(
        jnp.int32
    )
    vals = jnp.broadcast_to(values, pos.shape).astype(jnp.float32)
    sums_lane = sums_lane.at[pos].set(vals, mode="drop")
    return repair_ancestors(sums_lane, pos)


def update_leaves_last_wins(
    sums_lane: jax.Array, idx: jax.Array, values: jax.Array,
    local_capacity: int,
) -> jax.Array:
    """Leaf update with the HOST trees' duplicate semantics: when the same
    slot appears more than once in ``idx`` (one transition drawn into
    several rows of a [K, B] block), the LAST occurrence wins — numpy
    assignment order, which a bare XLA scatter does not guarantee. A
    deterministic scatter-max over flat positions picks each slot's last
    occurrence; losers are routed out of bounds and dropped."""
    idx = idx.reshape(-1).astype(jnp.int32)
    vals = values.reshape(-1).astype(jnp.float32)
    order = jnp.arange(idx.shape[0], dtype=jnp.int32)
    latest = (
        jnp.full((local_capacity,), -1, jnp.int32)
        .at[idx]
        .max(order, mode="drop")
    )
    win = latest[idx] == order
    slots = jnp.where(win, idx, local_capacity)
    return set_leaves(sums_lane, slots, vals, local_capacity)


def stratified_prefixes(
    key: jax.Array, k: int, batch: int, total: jax.Array
) -> jax.Array:
    """``[k, batch]`` prefix masses: one uniform per equal-mass segment of
    ``[0, total)``, segment ``j`` dealt to block ``[j % k, j // k]`` — the
    exact dealing `sample_block` uses, so batch ``i`` of a fused dispatch
    holds draws evenly spread across the WHOLE priority mass. The
    ``nextafter`` clamp guards the float edge where a prefix equal to
    ``total`` would fall off the last nonzero leaf (the host `_draw`
    guard, in f32)."""
    n = k * batch
    u = jax.random.uniform(key, (k, batch), jnp.float32)
    seg = (
        jnp.arange(n, dtype=jnp.float32)
        .reshape(batch, k)
        .T
    )
    pre = (seg + u) * (total / jnp.float32(n))
    return jnp.minimum(pre, jnp.nextafter(total, jnp.float32(0.0)))


def descend_prefix(sums_lane: jax.Array, prefixes: jax.Array) -> jax.Array:
    """The XLA reference descent: for each prefix mass, the leaf index
    ``i`` with ``cumsum[0..i-1] <= prefix < cumsum[0..i]`` — one vector
    gather per tree level for the whole batch (the jnp twin of the host
    ``SumTree.find_prefixsum_idx``, >= semantics so zero-mass leaves are
    skipped and boundary prefixes select the next leaf)."""
    width = sums_lane.shape[0]
    half = width // 2
    depth = half.bit_length() - 1
    flat = prefixes.reshape(-1)
    idx = jnp.ones(flat.shape, jnp.int32)
    for _ in range(depth):
        left = sums_lane[2 * idx]
        go_right = flat >= left
        flat = flat - jnp.where(go_right, left, jnp.float32(0.0))
        idx = 2 * idx + go_right.astype(jnp.int32)
    return (idx - half).reshape(prefixes.shape)


def lane_draw(
    sums_lane: jax.Array, key: jax.Array, k: int, batch: int,
    local_filled: jax.Array, *, tree_backend: str = "xla",
    interpret: bool = False,
):
    """One lane's stratified ``[k, batch]`` draw over its local mass.

    Returns ``(idx, p_leaf, total_local)`` — slot indices, their
    α-exponentiated leaf priorities, and this lane's root mass. The
    ``local_filled`` clamp mirrors the host ``_draw``'s ``size - 1``
    guard (at dp=1 ``local_filled`` IS the global fill count).
    ``tree_backend`` selects the descent implementation: "xla" is the
    reference log-depth gather descent, "pallas" the blocked prefix-scan
    kernel (``ops/pallas_tree.py``) validated against it."""
    width = sums_lane.shape[0]
    half = width // 2
    total = sums_lane[1]
    pre = stratified_prefixes(key, k, batch, total)
    if tree_backend == "pallas":
        from d4pg_tpu.ops.pallas_tree import find_prefix_pallas

        idx = find_prefix_pallas(sums_lane[half:], pre, interpret=interpret)
    else:
        idx = descend_prefix(sums_lane, pre)
    idx = jnp.clip(idx, 0, jnp.maximum(local_filled - 1, 0))
    return idx, sums_lane[half + idx], total


def lane_min_leaf(sums_lane: jax.Array) -> jax.Array:
    """Minimum nonzero leaf priority of one lane — the host MinTree's
    root, computed on the fly (zero-mass leaves are never-ingested rows /
    pow2 padding; real priorities are always ``>= eps**α > 0``)."""
    half = sums_lane.shape[0] // 2
    leaves = sums_lane[half:]
    return jnp.min(jnp.where(leaves > 0, leaves, jnp.inf))


def beta_at(step: jax.Array, beta0: float, beta_steps: int) -> jax.Array:
    """``linear_schedule(step, beta_steps, beta0, 1.0)`` in-kernel: the β
    anneal as a pure function of the learner step (device scalar)."""
    frac = jnp.clip(
        step.astype(jnp.float32) / jnp.float32(max(beta_steps, 1)), 0.0, 1.0
    )
    return jnp.float32(beta0) + frac * jnp.float32(1.0 - beta0)


def importance_weights(
    p_leaf: jax.Array, total_local: jax.Array, min_ratio_global: jax.Array,
    n_global: jax.Array, n_shards: int, beta: jax.Array,
) -> jax.Array:
    """Max-normalized IS weights for the shard-stratified scheme: row
    ``i`` on shard ``d`` is drawn with probability ``p_i / (D · T_d)``
    (each shard contributes batch/D draws from its local mass), so
    ``w = (N · p)^{-β}`` normalized by the GLOBAL max weight
    ``(N · min_ratio_global)^{-β}``. At D=1 this is the host formula
    term for term."""
    p = p_leaf / (jnp.float32(n_shards) * total_local)
    w = (p * n_global.astype(jnp.float32)) ** (-beta)
    max_w = (min_ratio_global * n_global.astype(jnp.float32)) ** (-beta)
    return (w / max_w).astype(jnp.float32)


def write_back_lane(
    sums_lane: jax.Array, idx: jax.Array, priorities: jax.Array,
    alpha: float, eps: float, local_capacity: int,
):
    """Post-step priority write-back for one lane: ``(|td| + ε)^α`` into
    the leaves (duplicate draws resolve last-wins, the host semantics)
    plus this lane's contribution to the max-priority update. Returns
    ``(sums_lane', local_max_abs_priority)`` — the caller combines the
    local maxima across shards (an exact, order-independent reduce)."""
    mag = jnp.abs(priorities) + jnp.float32(eps)
    pa = mag ** jnp.float32(alpha)
    sums_lane = update_leaves_last_wins(sums_lane, idx, pa, local_capacity)
    return sums_lane, jnp.max(mag)


# -------------------------------------------------------------- tree ingest
def tree_ingest_lane_body(
    alpha: float, local_capacity: int, sums_lane: jax.Array,
    max_priority: jax.Array, slots: jax.Array,
) -> jax.Array:
    """Seed newly mirrored ring rows at ``max_priority**α`` — the
    ``add_batch`` contract, applied to exactly the slot chunk the ring
    ingest just scattered (pad slots ``>= local_capacity`` drop). In the
    d4pglint ``MEGASTEP_FUNCTIONS`` manifest: jit-traced, host coercions
    here would smuggle a per-flush sync into the device loop."""
    return set_leaves(
        sums_lane, slots, max_priority ** jnp.float32(alpha), local_capacity
    )


def make_tree_ingest(alpha: float, local_capacity: int, mesh=None):
    """The jitted donated-buffer tree-seed program: ``(tree, slots) ->
    tree``. One fixed slot-chunk shape (the ring sync's) → exactly one
    compile for the run (recompile-sentinel budget 1, the ``make_ingest``
    contract — a fresh wrapper per call so two trees never share a jit
    specialization cache).

    Unsharded: ``slots`` is the ring sync's ``[chunk_cap]`` int32 (pads =
    capacity). Sharded: ``slots`` is ``[dp, chunk_local]`` local slot ids
    (pads = local capacity), tree lanes and slot rows both split over
    "dp" by shard_map — seeding stays shard-local, no collectives."""
    if mesh is None:

        def _ingest(tree, slots):
            lane = tree_ingest_lane_body(
                alpha, local_capacity, tree.sums[0], tree.max_priority, slots
            )
            return DevicePerTree(lane[None], tree.max_priority)

        return jax.jit(_ingest, donate_argnums=(0,))

    from jax.sharding import NamedSharding, PartitionSpec as P

    from d4pg_tpu.parallel.compat import shard_map
    from d4pg_tpu.parallel.partition import tree_partition_specs

    n_shards = int(mesh.shape["dp"])
    template = DevicePerTree(
        sums=np.zeros((n_shards, 2), np.float32),
        max_priority=np.zeros((), np.float32),
    )
    tree_specs = tree_partition_specs(template)
    slots_spec = P("dp", None)

    def _lane(tree, slots):
        lane = tree_ingest_lane_body(
            alpha, local_capacity, tree.sums[0], tree.max_priority, slots[0]
        )
        return DevicePerTree(lane[None], tree.max_priority)

    mapped = shard_map(
        _lane,
        mesh=mesh,
        in_specs=(tree_specs, slots_spec),
        out_specs=tree_specs,
        check_vma=False,
    )
    to_sh = lambda s: jax.tree_util.tree_map(  # noqa: E731
        lambda x: NamedSharding(mesh, x), s,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.jit(
        mapped,
        in_shardings=(to_sh(tree_specs), NamedSharding(mesh, slots_spec)),
        out_shardings=to_sh(tree_specs),
        donate_argnums=(0,),
    )


class DevicePerSync:
    """The trainer-side holder of the device tree between dispatches.

    Rides the ring sync's ``tree_hook`` seam
    (``device_ring.DeviceRingSync.flush``): every slot chunk the ring
    ingest ships is immediately seeded into the tree at
    ``max_priority**α`` from the SAME already-staged device slot array —
    zero extra H2D bytes, and the ring row and its priority leaf can
    never desynchronize. The megastep consumes ``self.tree`` (donated)
    and the trainer stores the returned tree back; ingest and dispatch
    both run on the learner thread, so the holder needs no lock.
    """

    def __init__(self, capacity: int, alpha: float, *, mesh=None,
                 max_priority: float = 1.0):
        self.capacity = int(capacity)
        self.alpha = float(alpha)
        self._mesh = mesh
        self.n_shards = int(mesh.shape["dp"]) if mesh is not None else 1
        self.local_capacity = self.capacity // self.n_shards
        self.tree = device_per_init(
            self.capacity, n_shards=self.n_shards, mesh=mesh,
            max_priority=max_priority,
        )
        self._ingest = make_tree_ingest(
            self.alpha, self.local_capacity, mesh=mesh
        )

    @property
    def ingest_fn(self):
        """The jitted tree-seed entry point (recompile-sentinel tracking)."""
        return self._ingest

    def on_chunk(self, slots_dev) -> None:
        """The ring sync's tree_hook target: seed this chunk's rows."""
        self.tree = self._ingest(self.tree, slots_dev)

    # ------------------------------------------------- snapshot / restore
    def snapshot_host(self) -> tuple[np.ndarray, float]:
        """Fetch the α-exponentiated leaf priorities in HOST slot order
        (``[capacity]`` f32) plus the pre-α max priority — the replay
        snapshot's priority sidecar (cold path: one D2H per checkpoint,
        never per step). On a process-spanning mesh the fetch routes
        through ``gather_global`` (a bare ``device_get`` raises on arrays
        spanning non-addressable devices), making this a COLLECTIVE there:
        every process must call it at the same point."""
        from d4pg_tpu.parallel.distributed import gather_global

        sums = gather_global(self.tree.sums)
        half = sums.shape[1] // 2
        lanes = sums[:, half: half + self.local_capacity]  # [S, local_cap]
        out = np.zeros(self.capacity, np.float32)
        from d4pg_tpu.replay.device_ring import striped_perm

        perm = striped_perm(self.capacity, self.n_shards)  # [S, local_cap]
        out[perm.reshape(-1)] = lanes.reshape(-1)
        return out, float(np.asarray(jax.device_get(self.tree.max_priority)))

    def restore_host(self, pa_host: np.ndarray, max_priority: float) -> None:
        """Rebuild the tree from snapshotted host-order α-exponentiated
        priorities (zeros stay zero-mass: rows the snapshot never
        covered). Setup path, never per step."""
        self.tree = tree_from_priorities(
            pa_host, self.capacity, n_shards=self.n_shards,
            max_priority=max_priority, mesh=self._mesh,
        )


def tree_from_priorities(
    pa_host: np.ndarray, capacity: int, *, n_shards: int = 1,
    max_priority: float = 1.0, mesh=None,
) -> DevicePerTree:
    """Build a :class:`DevicePerTree` from HOST-slot-order α-exponentiated
    priorities — the snapshot-restore path and the parity tests' oracle
    seeding. Plain numpy level-wise construction with the same f32
    pairwise sums the device repair computes, then one committed
    device_put (placed per ``PER_TREE_RULES`` when ``mesh`` is given)."""
    from d4pg_tpu.replay.device_ring import striped_perm

    pa_host = np.asarray(pa_host, np.float32)
    if pa_host.shape != (capacity,):
        raise ValueError(
            f"device PER tree: priorities shape {pa_host.shape} != "
            f"({capacity},)"
        )
    local_capacity = capacity // n_shards
    perm = striped_perm(capacity, n_shards)
    width = tree_width(local_capacity)
    half = width // 2
    sums = np.zeros((n_shards, width), np.float32)
    sums[:, half: half + local_capacity] = pa_host[perm]
    lo, hi = half, width
    while lo > 1:
        child = sums[:, lo:hi]
        parents = child[:, 0::2] + child[:, 1::2]
        lo, hi = lo // 2, lo
        sums[:, lo:hi] = parents
    tree = DevicePerTree(
        sums=jnp.asarray(sums), max_priority=jnp.float32(max_priority)
    )
    return _place_tree(tree, mesh)


# --------------------------------------------------------- host-side oracle
def host_prefixes(key, k: int, batch: int, total: float) -> np.ndarray:
    """The parity oracle's half of the RNG contract: reproduce the
    megastep's prefix draws on host from the same key (threefry is
    backend-deterministic — the ``draw_uniform_indices`` precedent).
    Feed these to the HOST tree's ``find_prefixsum_idx`` and the index
    draws must match the device descent exactly
    (tests/test_device_per.py pins the frozen literals)."""
    return np.asarray(
        stratified_prefixes(key, k, batch, jnp.float32(total))
    )
