"""One data plane: the capability seam every replay ingestion path answers to.

The repo has three ways experience reaches the learner's replay —

- **local collection** (host pool / sync env loops → n-step writers →
  the host sum-tree/ring),
- **fleet ingest** (remote actor hosts → ``WINDOWS``/``WINDOWS2`` frames
  → ``ReplayBuffer.add_batch``),
- **device/hybrid placement** (the host buffer mirrored into an
  HBM-resident ring, sampled in-kernel) —

and, until ISSUE 13, a matrix of hard refusals glued them together:
``--fleet-listen`` refused ``--her``/``--obs-norm``/pixels, device
placement refused pixels/obs-norm/dp_hogwild, hybrid refused dp, and the
same checks lived twice (train.py AND the Trainer constructor), drifting
a little more each PR. This module replaces that with ONE rule table:

- :func:`negotiate` maps a :class:`RequestedCaps` (what a config asks
  for) to a :class:`Negotiation` — verdict ``pass``, ``negotiated``
  (the request is honored with a declared action, e.g. hybrid placement
  keeping the legacy host-tree PER round-trip), or ``gap`` (a declared
  capability gap with a machine-readable reason code). Every refusal the
  system can utter lives HERE, once; the messages below are the exact
  strings the CLI and the Trainer raise, so they can never drift again.
  Since ISSUE 14, ``device`` placement composes with PER outright (the
  priority structure is device-resident, ``replay/device_per.py``) —
  the old ``per_downgraded_uniform`` action is gone.
- :func:`validate_train_config` is the single call site both entry
  points use (``train.py`` pre-env, ``Trainer.__init__`` post-env).
- :func:`learner_fleet_caps` / :func:`negotiate_fleet` are the fleet
  HELLO handshake's capability vector: the learner states what its
  replay requires (obs wire mode f32/u8/bf16, actor-side HER on/off,
  generation-tagged obs-norm stats on/off), the actor declares what it
  supports, and a mismatch is refused with a STRUCTURED reason the actor
  can print/alert on — never a silent wrong-distribution stream.
- :func:`composition_matrix` enumerates scenario × placement over the
  same table; the committed ``benchmarks/composition_matrix.json`` is
  its output, schema-gated (tools/d4pglint/schema_check.py) so every
  cell is pass/negotiated or a DECLARED gap — zero undeclared refusals.

Deliberately JAX-free (stdlib only): imported by train.py before any
backend decision and by the fleet ingest server.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# Fleet wire observation encodings (d4pg_tpu/fleet/wire.py implements the
# codecs; the names here are the negotiation vocabulary):
#   f32  — 4 bytes/elem, byte-identical to the in-process writer path;
#   u8   — 1 byte/elem, pixel rows quantized at the SAME point
#          ReplayBuffer._encode_obs quantizes (rint(obs*255)), so the
#          stored buffer bytes stay fleet-vs-local identical;
#   bf16 — 2 bytes/elem, flat rows truncated to bfloat16 on the wire
#          (deterministic round-to-nearest-even; content is bf16-rounded
#          f32 by declaration — the one mode that is NOT byte-identical
#          to local collection, and says so in the matrix).
OBS_MODES = ("f32", "u8", "bf16")


@dataclass(frozen=True)
class CapabilityGap:
    """One declared gap: ``code`` is the machine-readable reason (stable,
    matrix/artifact vocabulary), ``message`` the human refusal text."""

    code: str
    message: str


@dataclass(frozen=True)
class Negotiation:
    """Outcome of negotiating one requested composition."""

    verdict: str                              # "pass" | "negotiated" | "gap"
    actions: Tuple[str, ...] = ()             # declared downgrades applied
    gaps: Tuple[CapabilityGap, ...] = ()      # non-empty iff verdict=="gap"

    @property
    def ok(self) -> bool:
        return self.verdict != "gap"

    def message(self) -> str:
        return "; ".join(g.message for g in self.gaps)


@dataclass(frozen=True)
class RequestedCaps:
    """What one training configuration asks of the data plane. Built from
    a TrainConfig (:func:`from_train_config`); plain flags so scenario
    rows in the composition matrix can state them directly."""

    placement: str = "host"
    prioritized: bool = True
    pixel: bool = False
    obs_norm: bool = False
    her: bool = False
    fleet: bool = False
    fleet_only: bool = False
    fleet_bundle: bool = False
    fleet_wire: str = "auto"        # auto | float32 | bfloat16
    on_device: bool = False
    async_collect: bool = False
    num_envs: int = 1
    dp: int = 0                     # 0 = no data parallelism
    tp: int = 1
    dp_hogwild: bool = False
    steps_per_dispatch: int = 1
    transfer_dtype: str = "float32"
    prefetch: bool = False
    # ISSUE 16 — the large-batch/fused-kernel tier's capability flags:
    # fused_descent asks for the descent-in-scan Pallas program (device-
    # PER only, single device, pallas_fused projection, categorical
    # head); ingest_prefetch asks for the double-buffered ring staging
    # (device placement; a declared no-op elsewhere). projection /
    # dist_kind ride along so the fused-descent preconditions are
    # negotiable facts, not trainer-side asserts.
    fused_descent: bool = False
    ingest_prefetch: bool = False
    projection: str = "xla"         # xla | pallas | pallas_fused
    dist_kind: str = "categorical"  # categorical | quantile | iqn
    chaos: bool = False
    batch_size: int = 256
    replay_capacity: Optional[int] = None
    # Multi-host (ISSUE 17): how many jax.distributed processes share the
    # mesh. 1 = single-controller. >1 requires the dp-sharded device data
    # plane (the striped layout is what makes per-host replay shards
    # exact), with dp and capacity dealt evenly across processes.
    processes: int = 1
    # League variant id (ISSUE 15): which population member this learner
    # IS. 0 = the default/pre-league variant; the fleet HELLO negotiates
    # it so an actor host assigned to variant A can never stream into
    # variant B's replay (silent cross-variant contamination).
    variant: int = 0
    # None = not yet known (train.py validates before the env exists;
    # the Trainer re-validates after, with the env kind resolved).
    is_jax_env: Optional[bool] = None


def from_train_config(config, *, on_device: bool = False,
                      is_jax_env: Optional[bool] = None) -> RequestedCaps:
    """Project a ``TrainConfig`` onto the capability vocabulary."""
    return RequestedCaps(
        placement=config.replay_placement,
        prioritized=bool(config.prioritized),
        pixel=bool(config.agent.pixel_shape),
        obs_norm=bool(config.obs_norm),
        her=bool(config.her),
        fleet=config.fleet_listen is not None,
        fleet_only=config.fleet_listen is not None and config.num_envs == 0,
        fleet_bundle=bool(config.fleet_bundle),
        fleet_wire=getattr(config, "fleet_wire_dtype", "auto"),
        on_device=on_device,
        async_collect=bool(config.async_collect),
        num_envs=int(config.num_envs),
        dp=int(config.dp or 0),
        tp=int(config.tp),
        dp_hogwild=bool(config.dp_hogwild),
        steps_per_dispatch=int(config.steps_per_dispatch),
        transfer_dtype=config.transfer_dtype,
        prefetch=bool(config.prefetch),
        fused_descent=bool(getattr(config, "fused_descent", False)),
        ingest_prefetch=bool(getattr(config, "ingest_prefetch", False)),
        projection=config.agent.projection_backend,
        dist_kind=config.agent.dist.kind,
        chaos=bool(config.chaos),
        batch_size=int(config.batch_size),
        replay_capacity=config.replay_capacity,
        processes=int(getattr(config, "num_processes", 1) or 1),
        variant=int(getattr(config, "variant_id", None) or 0),
        is_jax_env=is_jax_env,
    )


def negotiate(caps: RequestedCaps) -> Negotiation:
    """THE rule table: every composition verdict the system can reach.

    The message strings are the exact refusal texts both entry points
    raise — single-sourced so CLI and constructor can never drift.
    """
    gaps: List[CapabilityGap] = []
    actions: List[str] = []

    def gap(code: str, message: str) -> None:
        gaps.append(CapabilityGap(code, message))

    if caps.placement not in ("host", "device", "hybrid"):
        gap(
            "unknown_placement",
            f"replay_placement must be host|device|hybrid, got "
            f"{caps.placement!r}",
        )
        return Negotiation("gap", (), tuple(gaps))

    # Device placement composes with PER outright since ISSUE 14: the
    # priority structure itself is device-resident (replay/device_per.py
    # — stratified descent, IS weights, and write-back inside the fused
    # megastep), so device×PER is a PASS, not the old uniform downgrade.
    if caps.placement == "hybrid" and caps.prioritized:
        # Hybrid is now the LEGACY placement: the host sum-tree still
        # owns the descent and ships [K, B] index/weight blocks every
        # dispatch. It stays supported as the byte-parity oracle of the
        # host data plane — a declared action, so the matrix says which
        # cells still pay the host round-trip.
        actions.append("hybrid_legacy_host_tree")
    if caps.placement == "hybrid" and not caps.prioritized:
        gap(
            "hybrid_requires_per",
            "replay_placement=hybrid is the PER mode (host sum-tree "
            "indices + on-device gather); use replay_placement=device "
            "for uniform replay",
        )

    if caps.placement != "host":
        if caps.pixel:
            gap(
                "device_ring_f32_only",
                "replay_placement=device/hybrid mirrors f32 rows into "
                "HBM; pixel (uint8-quantized) buffers are host-path only "
                "for now",
            )
        if caps.obs_norm:
            gap(
                "obs_norm_host_sampling",
                "--obs-norm normalizes sampled batches on the host; "
                "it is incompatible with a device-resident ring "
                "(rows are gathered in-kernel)",
            )
        if caps.transfer_dtype != "float32":
            gap(
                "transfer_dtype_host_only",
                "--transfer-dtype compresses the per-dispatch batch "
                "upload, which replay_placement=device/hybrid removes "
                "entirely; use float32",
            )
        if caps.dp:
            if caps.placement == "hybrid":
                gap(
                    "hybrid_single_device",
                    "replay_placement=hybrid is single-device: the "
                    "host sum-tree's [K, B] index blocks are global, "
                    "so shard-local gathers can't serve them; use "
                    "--replay-placement device for the sharded "
                    "(uniform) megastep",
                )
            if caps.tp != 1:
                gap(
                    "sharded_megastep_dp_only",
                    "the sharded megastep mesh is dp-only (tp=1); "
                    "tensor parallelism composes via the host-path "
                    "GSPMD step (--replay-placement host --tp N)",
                )
            if caps.dp_hogwild:
                gap(
                    "dp_hogwild_host_only",
                    "--dp-hogwild is a host-path DP mode; the sharded "
                    "megastep syncs gradients every step",
                )
            if caps.batch_size % caps.dp:
                gap(
                    "batch_not_divisible",
                    f"--batch-size {caps.batch_size} must be "
                    f"divisible by --dp {caps.dp} (each shard draws "
                    "batch/dp rows)",
                )
            if caps.replay_capacity and caps.replay_capacity % caps.dp:
                gap(
                    "capacity_not_divisible",
                    f"replay capacity {caps.replay_capacity} must "
                    f"be divisible by --dp {caps.dp} (each shard "
                    "owns capacity/dp ring rows)",
                )
        if caps.prefetch:
            actions.append("prefetch_ignored")
        if caps.fleet:
            # Opened by ISSUE 13 at the HOST placement; the device ring
            # composes with ingest through the same host-buffer mirror
            # local collection uses, so nothing refuses here.
            pass

    # ISSUE 17 — the process-spanning mesh. Every structural requirement
    # is a declared gap: multihost exists only where the dp-sharded device
    # data plane's striped layout makes per-host replay shards exact.
    if caps.processes > 1:
        if caps.placement != "device":
            gap(
                "multihost_device_placement_only",
                "--num-processes > 1 requires --replay-placement device: "
                "per-host replay shards ride the sharded ring's striped "
                "layout (host/hybrid keep a single global host buffer "
                "no process owns)",
            )
        if not caps.dp:
            gap(
                "multihost_requires_dp",
                "--num-processes > 1 requires --dp: the multi-host mesh "
                "IS the dp-sharded megastep mesh spanning processes",
            )
        elif caps.dp % caps.processes:
            gap(
                "multihost_dp_not_divisible",
                f"--dp {caps.dp} must be divisible by --num-processes "
                f"{caps.processes} (each process owns dp/num_processes "
                "contiguous mesh shards)",
            )
        if caps.replay_capacity and caps.replay_capacity % caps.processes:
            gap(
                "multihost_capacity_not_divisible",
                f"replay capacity {caps.replay_capacity} must be "
                f"divisible by --num-processes {caps.processes} (each "
                "process owns a capacity/num_processes local shard)",
            )

    # ISSUE 16 — fused descent-in-scan tier. Every precondition is a
    # declared gap, not a trainer assert: the fused kernel pipelines the
    # NEXT step's tree descent into the loss program, which only exists
    # where loss and descent are both Pallas programs over a device-
    # resident tree.
    if caps.fused_descent:
        if caps.placement != "device":
            gap(
                "fused_descent_device_only",
                "--fused-descent fuses the device-PER tree descent into "
                "the megastep's loss kernel; it requires "
                "--replay-placement device",
            )
        elif not caps.prioritized:
            gap(
                "fused_descent_requires_per",
                "--fused-descent pipelines the PRIORITY-tree descent; "
                "uniform replay has no descent to fuse (drop the flag)",
            )
        if caps.dp:
            gap(
                "fused_descent_single_device",
                "--fused-descent is single-device: the sharded megastep "
                "keeps separate per-shard descent programs (drop the "
                "flag or --dp)",
            )
        if caps.projection != "pallas_fused":
            gap(
                "fused_descent_requires_pallas_fused",
                "--fused-descent extends the pallas_fused loss kernel "
                "with the descent tile; use --projection pallas_fused",
            )
        if caps.dist_kind != "categorical":
            gap(
                "fused_descent_categorical_only",
                "--fused-descent fuses into the CATEGORICAL projection "
                "kernel; quantile/IQN heads keep the separate-programs "
                "tier",
            )

    # Double-buffered ingest staging: meaningful only where a DeviceRing
    # flush exists on the dispatch path AND is unsharded (the sharded
    # sync stages per-shard inside its own flush rounds).
    if caps.ingest_prefetch and (caps.placement != "device" or caps.dp):
        actions.append("ingest_prefetch_ignored")

    if caps.dp_hogwild:
        if not caps.dp:
            gap(
                "dp_hogwild_requires_dp",
                "--dp-hogwild is a DP mode: it requires --dp",
            )
        elif caps.placement == "host" and caps.steps_per_dispatch <= 1:
            gap(
                "dp_hogwild_needs_fused_window",
                "--dp-hogwild needs --steps-per-dispatch > 1: the "
                "dispatch window IS the staleness bound (K local "
                "steps between param resyncs)",
            )

    if caps.transfer_dtype == "uint8" and not caps.pixel:
        gap(
            "uint8_wire_requires_pixel",
            "--transfer-dtype uint8 requires a pixel env (uint8-"
            "quantized replay); use bfloat16 for flat observations",
        )
    elif caps.transfer_dtype not in ("float32", "bfloat16", "uint8"):
        gap(
            "unknown_transfer_dtype",
            "transfer_dtype must be float32|bfloat16|uint8, "
            f"got {caps.transfer_dtype!r}",
        )

    if caps.obs_norm and (caps.pixel or caps.is_jax_env):
        # is_jax_env may be None (unknown pre-env at the CLI): the
        # Trainer re-validates with it resolved. Pure-JAX envs act AND
        # evaluate inside jit, so the host-boundary normalizer never
        # sees their forwards — fleet-only mode included (eval would
        # silently run un-normalized).
        gap(
            "obs_norm_flat_envs_only",
            "--obs-norm supports host state-feature envs only "
            "(pure-JAX envs act inside jit; pixel obs are uint8 "
            "frames the conv encoder already scales)",
        )

    if caps.fleet_bundle and not caps.fleet:
        gap(
            "fleet_bundle_requires_listen",
            "--fleet-bundle does nothing without --fleet-listen: the "
            "bundle is published at ingest generation bumps (use "
            "--export-bundle for a one-shot export)",
        )

    if caps.fleet:
        if caps.obs_norm and not caps.fleet_only:
            # ISSUE 13 opens fleet+obs-norm, but with exactly ONE
            # statistics writer: the ingest writer thread folds stats per
            # ingested window. Local collection folds per acted step —
            # two unsynchronized Welford writers would tear the merge.
            gap(
                "obs_norm_fleet_single_writer",
                "--fleet-listen with --obs-norm requires --num-envs 0 "
                "(fleet-only): normalizer statistics fold at exactly one "
                "boundary — the ingest writer — and concurrent local "
                "collection would race the Welford merge",
            )
        if caps.fleet_only and caps.async_collect:
            gap(
                "fleet_only_async_collect",
                "--async-collect needs local envs; with --num-envs 0 "
                "the fleet is the only collector (drop --async-collect)",
            )
        if caps.fleet_wire == "bfloat16" and caps.pixel:
            gap(
                "fleet_wire_bf16_flat_only",
                "--fleet-wire-dtype bfloat16 compresses FLAT rows; pixel "
                "rows already stream u8-quantized at 1/4 the f32 bytes",
            )
    elif caps.fleet_wire not in ("auto", "float32"):
        gap(
            "fleet_wire_requires_listen",
            "--fleet-wire-dtype shapes the fleet ingest wire; it does "
            "nothing without --fleet-listen",
        )
    if caps.fleet_wire not in ("auto", "float32", "bfloat16"):
        gap(
            "unknown_fleet_wire",
            "fleet_wire_dtype must be auto|float32|bfloat16, got "
            f"{caps.fleet_wire!r}",
        )

    if caps.num_envs == 0 and not caps.fleet:
        gap(
            "no_collection_source",
            "--num-envs 0 means no local collection at all; it requires "
            "--fleet-listen so remote actor hosts supply the experience",
        )

    if caps.on_device:
        if caps.fleet:
            gap(
                "on_device_fleet",
                "--fleet-listen feeds the HOST replay buffer; --on-device "
                "keeps replay inside one XLA program (the flag would be "
                "silently ignored)",
            )
        if caps.transfer_dtype != "float32":
            gap(
                "on_device_transfer_dtype",
                "--transfer-dtype is a HOST-path link optimization; "
                "--on-device envs never transfer batches (the flag would "
                "be silently ignored)",
            )
        if caps.obs_norm:
            gap(
                "on_device_obs_norm",
                "--obs-norm is a host data-boundary feature; the on-device "
                "path keeps observations inside jit (the flag would be "
                "silently ignored)",
            )
        if caps.chaos:
            gap(
                "on_device_chaos",
                "--chaos targets the host runtime's fault surfaces (pool "
                "workers, flusher, checkpoint commit); the on-device path "
                "has none of them (the flag would be silently ignored)",
            )
        if caps.placement != "host":
            gap(
                "on_device_placement",
                "--replay-placement configures the HOST trainer's data "
                "plane; --on-device already keeps rollout+replay+learn in "
                "one XLA program (the flag would be silently ignored)",
            )

    if gaps:
        return Negotiation("gap", tuple(actions), tuple(gaps))
    if actions:
        return Negotiation("negotiated", tuple(actions), ())
    return Negotiation("pass", (), ())


def validate_train_config(config, *, on_device: bool = False,
                          is_jax_env: Optional[bool] = None,
                          raise_on_gap: bool = True) -> Negotiation:
    """THE validation call site (train.py and Trainer.__init__ both land
    here). Raises ``ValueError`` carrying every gap message when the
    composition has a declared gap; returns the :class:`Negotiation` so
    callers apply/announce the declared actions (prefetch ignored,
    hybrid's legacy note) — mutation stays with the owner of the config
    object."""
    n = negotiate(
        from_train_config(config, on_device=on_device, is_jax_env=is_jax_env)
    )
    if raise_on_gap and not n.ok:
        raise ValueError(n.message())
    return n


# ------------------------------------------------------------ fleet HELLO
# What a pre-ISSUE-13 actor implicitly declares: v1 wire, plain f32
# windows, no actor-side HER, no stats tagging — and (ISSUE 15) variant 0,
# the default/pre-league variant, so a pre-variant actor negotiates
# byte-compatibly against a default learner and is REFUSED by any league
# variant learner (it cannot know which population member it feeds). A
# HELLO without a "caps" key negotiates as this.
LEGACY_ACTOR_CAPS = {
    "wire": 1,
    "obs_modes": ["f32"],
    "her": False,
    "obs_norm": False,
    "variant": 0,
    # ISSUE 18: which experience stream this connection feeds ("actor" =
    # collection fleet, "mirror" = flywheel serving tap). Informational —
    # it selects the ingest server's per-source counter, never a refusal.
    "source": "actor",
}


def learner_fleet_caps(caps: RequestedCaps) -> dict:
    """What the learner's replay config REQUIRES of fleet actors: the
    server half of the HELLO capability vector."""
    if caps.pixel:
        obs_mode = "u8"      # the 17.4 MB/s ingest wall rules out f32 pixels
    elif caps.fleet_wire == "bfloat16":
        obs_mode = "bf16"
    else:
        obs_mode = "f32"
    return {
        "obs_mode": obs_mode,
        "her": caps.her,
        "obs_norm": caps.obs_norm,
        "variant": int(caps.variant),
    }


def negotiate_fleet(learner: dict, actor: dict
                    ) -> Tuple[Optional[dict], Tuple[CapabilityGap, ...]]:
    """Negotiate one actor connection against the learner's requirements.

    Returns ``(chosen, gaps)``: ``chosen`` is the capability set the
    connection will speak (None when refused), ``gaps`` the structured
    refusal reasons (the ingest server ships them back as JSON so a
    mis-deployed actor host fails with an actionable, machine-readable
    reason instead of streaming a silently-wrong distribution)."""
    gaps: List[CapabilityGap] = []
    modes = tuple(actor.get("obs_modes") or ("f32",))
    want_mode = learner["obs_mode"]
    if want_mode not in modes:
        gaps.append(CapabilityGap(
            "obs_mode_unsupported",
            f"learner streams obs as {want_mode!r}, actor supports "
            f"{list(modes)} (upgrade the actor host: WINDOWS2 frames)",
        ))
    actor_her = bool(actor.get("her", False))
    if learner["her"] and not actor_her:
        gaps.append(CapabilityGap(
            "her_required",
            "learner trains on hindsight-relabeled windows; this actor "
            "does not relabel (run it with --her)",
        ))
    elif actor_her and not learner["her"]:
        gaps.append(CapabilityGap(
            "her_unexpected",
            "actor ships hindsight-relabeled windows but the learner "
            "did not ask for HER (drop the actor's --her)",
        ))
    learner_variant = int(learner.get("variant", 0))
    actor_variant = int(actor.get("variant", 0))
    if learner_variant != actor_variant:
        # League assignment is an exact-match capability: windows from a
        # host assigned to another variant (or to none — pre-variant
        # actors declare 0) would silently train the wrong population
        # member on the wrong policy's experience.
        gaps.append(CapabilityGap(
            "variant_mismatch",
            f"learner is league variant {learner_variant}, actor is "
            f"assigned variant {actor_variant} (re-point the actor host "
            "at its assigned variant's ingest port)",
        ))
    actor_norm = bool(actor.get("obs_norm", False))
    if learner["obs_norm"] and not actor_norm:
        gaps.append(CapabilityGap(
            "obs_norm_required",
            "learner normalizes observations; this actor does not apply "
            "the bundle's generation-tagged stats (upgrade the actor "
            "host / re-point it at the published bundle)",
        ))
    elif actor_norm and not learner["obs_norm"]:
        gaps.append(CapabilityGap(
            "obs_norm_unexpected",
            "actor acts on normalized observations but the learner "
            "publishes no statistics (bundle/learner config skew)",
        ))
    if gaps:
        return None, tuple(gaps)
    return (
        {
            "obs_mode": want_mode,
            "her": learner["her"],
            "obs_norm": learner["obs_norm"],
            "variant": learner_variant,
            # pure passthrough: a mirror tap's windows count under their
            # own ingest counter but are otherwise ordinary experience
            "source": str(actor.get("source", "actor")),
        },
        (),
    )


# ------------------------------------------------------ composition matrix
# Scenario rows: named config fragments over the capability vocabulary.
# Placements are the columns. The committed artifact
# benchmarks/composition_matrix.json is negotiate() evaluated over this
# grid — regenerate with `python benchmarks/composition_matrix.py`.
SCENARIOS: Tuple[Tuple[str, dict], ...] = (
    ("flat", dict()),
    ("flat_uniform", dict(prioritized=False)),
    ("pixel", dict(pixel=True, transfer_dtype="uint8")),
    ("obs_norm", dict(obs_norm=True, is_jax_env=False)),
    ("her", dict(her=True, is_jax_env=False)),
    ("her_obs_norm", dict(her=True, obs_norm=True, is_jax_env=False)),
    ("dp2", dict(dp=2)),
    ("dp2_hogwild", dict(dp=2, dp_hogwild=True, steps_per_dispatch=8)),
    ("fleet_flat", dict(fleet=True, fleet_only=True, fleet_bundle=True,
                        num_envs=0)),
    ("fleet_pixel", dict(fleet=True, fleet_only=True, fleet_bundle=True,
                         num_envs=0, pixel=True)),
    ("fleet_obs_norm", dict(fleet=True, fleet_only=True, fleet_bundle=True,
                            num_envs=0, obs_norm=True, is_jax_env=False)),
    ("fleet_her", dict(fleet=True, fleet_only=True, fleet_bundle=True,
                       num_envs=0, her=True, is_jax_env=False)),
    ("fleet_her_obs_norm", dict(fleet=True, fleet_only=True,
                                fleet_bundle=True, num_envs=0, her=True,
                                obs_norm=True, is_jax_env=False)),
    ("fleet_bf16_wire", dict(fleet=True, fleet_only=True, fleet_bundle=True,
                             num_envs=0, fleet_wire="bfloat16")),
    ("fleet_mixed_obs_norm", dict(fleet=True, num_envs=2, obs_norm=True,
                                  is_jax_env=False)),
    # ISSUE 16: the large-batch flagship recipe's full capability ask —
    # fused descent-in-scan + double-buffered ingest at a wide batch.
    # device = pass; host/hybrid = declared gaps (the fused tier only
    # exists where the tree is device-resident).
    ("large_batch_fused", dict(fused_descent=True, ingest_prefetch=True,
                               projection="pallas_fused",
                               batch_size=2048)),
)

PLACEMENTS = ("host", "device", "hybrid")


def composition_matrix() -> List[dict]:
    """Every scenario × placement cell, negotiated. The artifact rows."""
    cells: List[dict] = []
    for name, fragment in SCENARIOS:
        for placement in PLACEMENTS:
            caps = RequestedCaps(placement=placement, **fragment)
            n = negotiate(caps)
            cell = {
                "scenario": name,
                "placement": placement,
                "verdict": n.verdict,
            }
            if n.actions:
                cell["actions"] = list(n.actions)
            if n.gaps:
                cell["gaps"] = [
                    {"code": g.code, "message": g.message} for g in n.gaps
                ]
            cells.append(cell)
    return cells
