"""Agent core: the D4PG algorithm as one fused, jittable train step."""

from d4pg_tpu.agent.state import D4PGConfig, TrainState
from d4pg_tpu.agent.d4pg import (
    act,
    act_deterministic,
    build_networks,
    create_train_state,
    jit_train_step,
    support_of,
    train_step,
)

__all__ = [
    "D4PGConfig",
    "TrainState",
    "act",
    "act_deterministic",
    "build_networks",
    "create_train_state",
    "jit_train_step",
    "support_of",
    "train_step",
]
