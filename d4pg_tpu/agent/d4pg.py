"""D4PG algorithm core: one fused, jittable SGD step.

Everything the reference does between ``sample()`` and
``update_priorities`` (``ddpg.py:200-255``, SURVEY.md §3.2) — two target
forwards, the categorical Bellman projection, critic CE loss with PER
importance weights, actor −E[Q] loss, both Adam updates, the Polyak target
update, and new priorities — compiles into ONE XLA computation with no
host↔device hops (the reference round-trips through NumPy every step at
``ddpg.py:214`` and ``utils.py:7-10``).

The functions are pure: (state, batch) → (state, metrics, priorities). Data
parallelism wraps them unchanged (``d4pg_tpu.parallel``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import optax

from d4pg_tpu.agent.state import D4PGConfig, TrainState
from d4pg_tpu.models import Actor, Critic
from d4pg_tpu.ops import (
    CategoricalSupport,
    categorical_projection,
    categorical_td_loss,
    expected_value,
    gaussian_noise_init,
    gaussian_noise_sample,
    make_support,
    ou_noise_init,
    ou_noise_reset,
    ou_noise_sample,
    polyak_update,
)
from d4pg_tpu.models.critic import mixture_gaussian_mean


def _dtype(config: D4PGConfig):
    return jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32


def build_networks(config: D4PGConfig) -> tuple[Actor, Critic]:
    pixel_shape = tuple(config.pixel_shape) if config.pixel_shape else None
    actor = Actor(
        action_dim=config.action_dim,
        hidden_sizes=tuple(config.hidden_sizes),
        dtype=_dtype(config),
        pixel_shape=pixel_shape,
        encoder_embed_dim=config.encoder_embed_dim,
    )
    critic = Critic(
        dist=config.dist,
        hidden_sizes=tuple(config.hidden_sizes),
        dtype=_dtype(config),
        pixel_shape=pixel_shape,
        encoder_embed_dim=config.encoder_embed_dim,
    )
    return actor, critic


def make_optimizers(config: D4PGConfig):
    adam = partial(optax.adam, b1=config.adam_b1, b2=config.adam_b2)
    return adam(config.lr_actor), adam(config.lr_critic)


def support_of(config: D4PGConfig) -> CategoricalSupport:
    return make_support(config.dist.v_min, config.dist.v_max, config.dist.num_atoms)


def _stacked_critics(config: D4PGConfig) -> int:
    """Leading critic-stack size: 2 (twin), E (ensemble), or 0 (single).

    Twin and ensemble are mutually exclusive — the ensemble subsumes the
    twin (E=2, M=2 is exactly clipped double-Q with a per-step subset
    redraw that happens to always pick both)."""
    if config.critic_ensemble:
        if config.twin_critic:
            raise ValueError(
                "critic_ensemble and twin_critic are mutually exclusive: "
                "an E=2, ensemble_min_targets=2 ensemble IS the twin"
            )
        if config.critic_ensemble < 2:
            raise ValueError(
                f"critic_ensemble must be >= 2 (got "
                f"{config.critic_ensemble}); 0 disables"
            )
        if not 1 <= config.ensemble_min_targets <= config.critic_ensemble:
            raise ValueError(
                f"ensemble_min_targets must be in [1, critic_ensemble="
                f"{config.critic_ensemble}], got {config.ensemble_min_targets}"
            )
        return config.critic_ensemble
    return 2 if config.twin_critic else 0


def create_train_state(config: D4PGConfig, key: jax.Array) -> TrainState:
    """Initialize params, hard-copy targets (reference ``ddpg.py:57-64,92-94``).

    With ``config.twin_critic`` the critic pytree carries a leading [2]
    axis (two independent inits); Adam moments and Polyak targets stack
    along with it, and :func:`train_step` vmaps the critic over it.
    ``config.critic_ensemble`` generalizes the same stacking to E
    independent inits (REDQ).
    """
    actor, critic = build_networks(config)
    k_actor, k_critic, k_state = jax.random.split(key, 3)
    obs = jnp.zeros((1, config.obs_dim))
    action = jnp.zeros((1, config.action_dim))
    actor_params = actor.init(k_actor, obs)
    n_stack = _stacked_critics(config)
    if n_stack:
        stack_keys = jax.random.split(k_critic, n_stack)
        critic_params = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves),
            *[critic.init(k, obs, action) for k in stack_keys],
        )
    else:
        critic_params = critic.init(k_critic, obs, action)
    actor_opt, critic_opt = make_optimizers(config)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        actor_params=actor_params,
        critic_params=critic_params,
        target_actor_params=jax.tree_util.tree_map(jnp.copy, actor_params),
        target_critic_params=jax.tree_util.tree_map(jnp.copy, critic_params),
        actor_opt_state=actor_opt.init(actor_params),
        critic_opt_state=critic_opt.init(critic_params),
        key=k_state,
    )


def act(
    config: D4PGConfig,
    actor_params: Any,
    obs: jax.Array,
    key: jax.Array,
    noise_scale: jax.Array | float = 1.0,
) -> jax.Array:
    """Stateless exploration policy: tanh actor + scaled Gaussian noise,
    clipped to [−1, 1] (reference ``main.py:145-147``). jit/vmap-able.

    OU noise is stateful; use :func:`make_noise` + a stateful rollout policy
    for it (``config.noise_kind`` is honored there, not here).
    """
    actor, _ = build_networks(config)
    a = actor.apply(actor_params, obs)
    noise = gaussian_noise_sample(
        gaussian_noise_init(config.noise_epsilon),
        key,
        a.shape,
        sigma=config.noise_sigma,
    )
    return jnp.clip(a + noise_scale * noise, -1.0, 1.0)


def make_noise(config: D4PGConfig):
    """Noise process selected by ``config.noise_kind`` as an (init, sample,
    reset) triple of pure functions over an explicit state.

    The reference hardcodes Gaussian and parses-but-ignores the ``ou_*``
    flags (SURVEY.md quirk #13); here both are first-class:

      - ``init() -> state``
      - ``sample(state, key, shape) -> (noise, state)``
      - ``reset(state) -> state``  (per-episode; applies the ε-decay the
        reference defines but never triggers — quirk #10)
    """
    if config.noise_kind == "gaussian":
        base = gaussian_noise_init(config.noise_epsilon)

        def init():
            return base

        def sample(state, key, shape):
            return (
                gaussian_noise_sample(state, key, shape, sigma=config.noise_sigma),
                state,
            )

        def reset(state):
            return state  # ε-decay handled by the trainer's noise_scale schedule

    elif config.noise_kind == "ou":

        def init():
            return ou_noise_init(config.action_dim, epsilon=config.noise_epsilon)

        def sample(state, key, shape):
            x, state = ou_noise_sample(
                state,
                key,
                theta=config.ou_theta,
                mu=config.ou_mu,
                sigma=config.ou_sigma,
            )
            return jnp.broadcast_to(x, shape), state

        def reset(state):
            return ou_noise_reset(state, decay=0.0)

    else:
        raise ValueError(f"unknown noise kind: {config.noise_kind}")
    return init, sample, reset


def act_deterministic(config: D4PGConfig, actor_params: Any, obs: jax.Array) -> jax.Array:
    """Greedy policy for evaluation (reference ``main.py:122,324``)."""
    actor, _ = build_networks(config)
    return actor.apply(actor_params, obs)


def noisy_explore(config: D4PGConfig, noise_sample, a, key, nstate, scale):
    """Shared collection-action builder used by EVERY collection path
    (host/pool/HER closures in runtime/trainer.py and the segment collector
    in runtime/collect.py): additive noise + clip, then the ε-uniform
    mixture. Key discipline: the mixture key is split off ONLY when
    random_eps > 0, so eps=0 configs keep the exact pre-round-5 noise
    stream — seed-for-seed reproducibility against recorded baselines."""
    if config.random_eps:
        key, ke = jax.random.split(key)
    n, nstate = noise_sample(nstate, key, a.shape)
    a = jnp.clip(a + scale * n, -1.0, 1.0)
    if config.random_eps:
        a = exploration_mixture(config, ke, a)
    return a, nstate


def exploration_mixture(config: D4PGConfig, key: jax.Array, a: jax.Array) -> jax.Array:
    """ε-uniform action mixture for collection (HER-DDPG, Andrychowicz et
    al. 2017 §4.4): with probability ``config.random_eps`` the WHOLE action
    vector is replaced by a uniform draw from the box. Complements Gaussian
    noise, which cannot escape a saturated tanh corner (clip pins most of
    its mass there). Identity when random_eps == 0 (every non-goal config).
    Broadcasting: ``a`` is [..., act_dim]; one Bernoulli per action vector."""
    if not config.random_eps:
        return a
    ku, kb = jax.random.split(key)
    u = jax.random.uniform(ku, a.shape, minval=-1.0, maxval=1.0)
    take = jax.random.bernoulli(kb, config.random_eps, a.shape[:-1] + (1,))
    return jnp.where(take, u, a)


def _critic_value(config: D4PGConfig, support, head: jax.Array) -> jax.Array:
    """E[Z] under whichever head the critic is configured with."""
    kind = config.dist.kind
    if kind == "categorical":
        return expected_value(support, jax.nn.softmax(head, axis=-1))
    if kind == "scalar":
        return head[..., 0]
    if kind == "mixture_gaussian":
        return mixture_gaussian_mean(head, config.dist.num_mixtures)
    raise ValueError(kind)


def train_step(
    config: D4PGConfig,
    state: TrainState,
    batch: Mapping[str, jax.Array],
    axis_name: str | None = None,
    sync_fn=None,
    descent=None,
):
    """One full D4PG SGD step (the reference §3.2 hot loop, fused).

    Args:
      config: static hyperparameters (close over it or mark static in jit).
      state: complete learner state.
      batch: obs [B,O], action [B,A], reward [B], next_obs [B,O],
        discount [B] (= γ^m·(1−terminal), from the n-step writer), and
        optionally weights [B] (PER importance weights; absent → ones).
      axis_name: when running under ``shard_map`` over a device mesh, the
        mesh axis to ``pmean`` gradients/metrics over. This single hook is
        the synchronous-DP replacement for the reference's entire
        shared-memory gradient scheme (``ddpg.py:104-108``,
        ``shared_adam.py``): each device computes grads on its batch shard,
        one AllReduce over ICI averages them, every replica applies the same
        Adam update. ``None`` → single-device semantics.
      sync_fn: overrides the cross-shard combine entirely (a ``tree ->
        tree`` callable). The sharded megastep passes the DETERMINISTIC
        mean (``parallel.dp.det_pmean``: all_gather + fixed-order sum),
        whose bits a single-device vmap oracle can replay exactly —
        ``pmean``'s backend AllReduce cannot be (its accumulation order is
        the backend's choice). ``None`` keeps the pmean/axis_name path.
      descent: ``(leaves [L], next_prefixes [B])`` — the fused-tier
        pipelining seam (ISSUE 16, ``ops/pallas_fused_step.py``): the
        step's fused-loss Pallas program ALSO descends the device-PER
        segment tree for the NEXT scan step's stratified prefixes, so the
        megastep's steady state runs one program per step instead of a
        separate descent program per dispatch. Requires the categorical
        head with ``projection_backend="pallas_fused"`` (raises
        otherwise). When set, the return grows a fourth element:
        ``next_idx [B] int32`` (unclamped-to-fill leaf indices; the
        megastep body applies ``lane_draw``'s fill clamp). Under stacked
        critics every member computes the identical descent; member 0's
        is returned.

    Returns:
      (new_state, metrics, priorities[B] — local shard under shard_map),
      plus ``next_idx [B]`` when ``descent`` is given.
    """
    if descent is not None and not (
        config.dist.kind == "categorical"
        and config.projection_backend == "pallas_fused"
    ):
        raise ValueError(
            "descent= (the fused descent-in-scan tier) requires the "
            "categorical head with projection_backend='pallas_fused' "
            f"(got kind={config.dist.kind!r}, "
            f"backend={config.projection_backend!r})"
        )

    def _sync(tree):
        if sync_fn is not None:
            return sync_fn(tree)
        if axis_name is None:
            return tree
        return jax.lax.pmean(tree, axis_name)

    actor, critic = build_networks(config)
    actor_opt, critic_opt = make_optimizers(config)
    support = support_of(config)

    # ---- bf16 hot-path dtype policy ----
    # Master weights, Adam moments, Polyak targets and every loss reduction
    # stay float32 (the nets cast their head back to f32, so losses/metrics
    # accumulate in f32 regardless of compute dtype). Under bfloat16 the
    # TARGET networks — forward-only, never differentiated — are cast to
    # bf16 ONCE here, so all target-path matmuls read 2-byte params from
    # HBM instead of converting f32 reads per layer; the flax modules see
    # params already in their compute dtype and skip the promotion. The
    # ONLINE params are left f32 and cast per-op inside the loss closures:
    # value_and_grad must differentiate w.r.t. the f32 masters.
    tgt_actor_params = state.target_actor_params
    tgt_critic_params = state.target_critic_params
    if _dtype(config) == jnp.bfloat16:
        def _to_bf16(tree):
            return jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == jnp.float32
                else x,
                tree,
            )

        tgt_actor_params = _to_bf16(tgt_actor_params)
        tgt_critic_params = _to_bf16(tgt_critic_params)
    weights = batch.get("weights")
    if weights is None:
        weights = jnp.ones_like(batch["reward"])

    # DrQ random shift on pixel batches (ops/augment.py): the one
    # regularizer that makes Q-learning from images train at all. Keys come
    # from the TrainState's PRNG slot, so the scan/jit stays pure and every
    # step shifts differently.
    new_key = state.key
    if config.pixel_shape and config.augment_pad > 0:
        from d4pg_tpu.ops.augment import random_shift

        k_obs, k_next, new_key = jax.random.split(state.key, 3)
        shape = tuple(config.pixel_shape)
        batch = dict(batch)
        batch["obs"] = random_shift(
            batch["obs"], k_obs, shape, config.augment_pad
        )
        batch["next_obs"] = random_shift(
            batch["next_obs"], k_next, shape, config.augment_pad
        )

    # ---- target: y = Φ(r + γ_eff · Z_target(s', μ_target(s'))) ----
    next_action = actor.apply(tgt_actor_params, batch["next_obs"])
    if config.critic_ensemble:
        # REDQ in-target minimization, distributionally: back up whichever
        # member of a per-step RANDOM SUBSET of M target critics has the
        # smallest expected value, per sample — the whole distribution of
        # the argmin member, same rationale as the twin branch below
        # (an elementwise min of probs would not be a distribution).
        E = config.critic_ensemble
        M = config.ensemble_min_targets
        heads = jax.vmap(
            lambda p: critic.apply(p, batch["next_obs"], next_action)
        )(tgt_critic_params)                                    # [E, B, H]
        vals = jax.vmap(lambda h: _critic_value(config, support, h))(heads)
        k_subset, new_key = jax.random.split(new_key)
        subset = jax.random.permutation(k_subset, E)[:M]        # [M]
        sub_vals = vals[subset]                                 # [M, B]
        sub_heads = heads[subset]                               # [M, B, H]
        which = jnp.argmin(sub_vals, axis=0)                    # [B]
        target_head = jnp.take_along_axis(
            sub_heads, which[None, :, None], axis=0
        )[0]                                                    # [B, H]
    elif config.twin_critic:
        # Clipped double-Q, distributionally: back up whichever target
        # critic's WHOLE distribution has the smaller mean, per sample —
        # the distributional analogue of TD3's min(Q1, Q2) (taking an
        # elementwise min of probs would not be a distribution).
        heads = jax.vmap(
            lambda p: critic.apply(p, batch["next_obs"], next_action)
        )(tgt_critic_params)
        vals = jax.vmap(lambda h: _critic_value(config, support, h))(heads)
        target_head = jnp.where(
            (vals[0] <= vals[1])[..., None], heads[0], heads[1]
        )
    else:
        target_head = critic.apply(
            tgt_critic_params, batch["next_obs"], next_action
        )

    if config.dist.kind == "categorical":
        # Atom-layout audit: every per-atom op below (softmax, projection,
        # CE, E[Z]) reduces/broadcasts over the LAST axis of a [B, A]
        # tensor — atoms live in the 128-lane dimension, so the critic-head
        # "gathers" are contiguous lane reads, never a strided HBM walk.
        # Keep it that way: any new head-side op must put atoms last.
        target_probs = jax.nn.softmax(target_head, axis=-1)
        if config.projection_backend == "pallas_fused":
            # Projection + log-softmax CE + IS/priority signals in ONE
            # Pallas kernel: the projected target distribution is never
            # materialized in HBM (fwd or bwd — the VJP recomputes Φ in
            # VMEM). The XLA branch below stays the reference oracle.
            from d4pg_tpu.ops.pallas_projection import fused_categorical_loss

            fused_target_probs = jax.lax.stop_gradient(target_probs)
            interpret = jax.default_backend() != "tpu"  # CPU tests

            def critic_loss_fn(critic_params):
                pred = critic.apply(critic_params, batch["obs"], batch["action"])
                if descent is not None:
                    from d4pg_tpu.ops.pallas_fused_step import (
                        fused_categorical_loss_descent,
                    )

                    leaves, next_prefixes = descent
                    ce, overlap, next_idx = fused_categorical_loss_descent(
                        support,
                        pred,
                        fused_target_probs,
                        batch["reward"],
                        batch["discount"],
                        next_prefixes,
                        leaves,
                        interpret,
                    )
                else:
                    next_idx = None
                    ce, overlap = fused_categorical_loss(
                        support,
                        pred,
                        fused_target_probs,
                        batch["reward"],
                        batch["discount"],
                        interpret,
                    )
                # f32 weighted reduction on [B] vectors — byte-trivial.
                loss = jnp.mean(weights * ce)
                per_sample = (
                    overlap if config.priority_kind == "overlap" else ce
                )
                if descent is not None:
                    return loss, (per_sample, next_idx)
                return loss, per_sample

        elif config.projection_backend == "pallas":
            from d4pg_tpu.ops.pallas_projection import categorical_projection_pallas

            proj = categorical_projection_pallas(
                support,
                target_probs,
                batch["reward"],
                batch["discount"],
                jax.default_backend() != "tpu",  # interpret mode off-TPU
            )
        else:
            proj = categorical_projection(
                support, target_probs, batch["reward"], batch["discount"]
            )
        if config.projection_backend != "pallas_fused":
            proj = jax.lax.stop_gradient(proj)

            def critic_loss_fn(critic_params):
                pred = critic.apply(critic_params, batch["obs"], batch["action"])
                loss, per_sample_ce = categorical_td_loss(pred, proj, weights)
                if config.priority_kind == "overlap":
                    # Reference-compatible surrogate |−Σ m·p| (ddpg.py:220-222).
                    per_sample = jnp.abs(
                        -jnp.sum(proj * jax.nn.softmax(pred, axis=-1), axis=-1)
                    )
                else:
                    per_sample = per_sample_ce
                return loss, per_sample
    elif config.dist.kind == "scalar":
        # Plain DDPG TD(0)/TD(n) target (BASELINE.json config 1).
        y = batch["reward"] + batch["discount"] * target_head[..., 0]
        y = jax.lax.stop_gradient(y)

        def critic_loss_fn(critic_params):
            pred = critic.apply(critic_params, batch["obs"], batch["action"])[..., 0]
            td = pred - y
            loss = jnp.mean(weights * jnp.square(td))
            return loss, jnp.abs(td)
    elif config.dist.kind == "mixture_gaussian":
        # TRUE distributional MoG Bellman backup (the D4PG paper's
        # alternative head; reference declares but never implements it,
        # ddpg.py:48-50). The target DISTRIBUTION is the affine transform
        # T Z' = r + γ_eff·Z' of the target-critic mixture — each component
        # N(m_j, s_j) maps to N(r + d·m_j, d·s_j) — and the loss is the
        # cross-entropy H(T Z', Z_online), evaluated per target component
        # with Gauss–Hermite quadrature (deterministic, differentiable, no
        # PRNG; M components × Q nodes of log-density evaluations vectorize
        # to one fused elementwise block on the MXU path). Terminal
        # transitions (d=0) collapse every component onto the point mass at
        # r; the std floor keeps the quadrature nodes finite there.
        from d4pg_tpu.ops.mog import mog_bellman_targets, mog_cross_entropy

        M = config.dist.num_mixtures
        y_nodes, node_w = mog_bellman_targets(
            target_head, batch["reward"], batch["discount"], M,
            config.dist.quadrature_points,
        )
        # Scalar TD magnitude for PER priorities (the CE of a continuous
        # density can be negative, which scrambles |·|-based rankings).
        y_mean = batch["reward"] + batch["discount"] * _critic_value(
            config, support, target_head
        )
        y_mean = jax.lax.stop_gradient(y_mean)

        def critic_loss_fn(critic_params):
            head = critic.apply(critic_params, batch["obs"], batch["action"])
            ce = mog_cross_entropy(head, y_nodes, node_w, M)
            td = jnp.abs(y_mean - mixture_gaussian_mean(head, M))
            return jnp.mean(weights * ce), td
    else:
        raise ValueError(config.dist.kind)

    if config.twin_critic or config.critic_ensemble:
        # Every stacked critic (twin pair or E-wide ensemble) regresses
        # the same min target; one vmap over the stacked params turns the
        # single-critic loss into all of them. PER priority = mean of the
        # stack's TD magnitudes (less noisy than any one member).
        _single_loss_fn = critic_loss_fn

        def critic_loss_fn(stacked_params):
            losses, per_sample = jax.vmap(_single_loss_fn)(stacked_params)
            if descent is not None:
                # Every member ran the identical descent (same leaves,
                # same prefixes, exact int32) — member 0 IS the result.
                per_sample, next_idx = per_sample
                return jnp.sum(losses), (
                    jnp.mean(per_sample, axis=0), next_idx[0]
                )
            return jnp.sum(losses), jnp.mean(per_sample, axis=0)

    (critic_loss, loss_aux), critic_grads = jax.value_and_grad(
        critic_loss_fn, has_aux=True
    )(state.critic_params)
    if descent is not None:
        priorities, descent_idx = loss_aux
    else:
        priorities = loss_aux
    critic_grads = _sync(critic_grads)
    critic_updates, critic_opt_state = critic_opt.update(
        critic_grads, state.critic_opt_state
    )
    critic_params = optax.apply_updates(state.critic_params, critic_updates)

    # ---- actor: maximize E[Q(s, μ(s))] against the UPDATED critic ----
    # (critic 0 under twin critics — TD3 convention; the ensemble-MEAN
    # value under REDQ — averaging E critics' gradients is what lets the
    # aggressive min-subset target stay trainable)
    actor_critic_params = (
        jax.tree_util.tree_map(lambda x: x[0], critic_params)
        if config.twin_critic
        else critic_params
    )

    def actor_loss_fn(actor_params):
        a = actor.apply(actor_params, batch["obs"])
        if config.critic_ensemble:
            heads = jax.vmap(
                lambda p: critic.apply(p, batch["obs"], a)
            )(critic_params)                                    # [E, B, H]
            q = jax.vmap(lambda h: _critic_value(config, support, h))(heads)
            q_mean = jnp.mean(q)          # mean over members AND batch
        else:
            head = critic.apply(actor_critic_params, batch["obs"], a)
            q_mean = jnp.mean(_critic_value(config, support, head))
        loss = -q_mean
        if config.action_l2:
            # HER-DDPG action regularizer (Andrychowicz et al. 2017, §4.4:
            # the "square of the preactivations" penalty): counters the
            # tanh-corner collapse sparse goal tasks induce — the critic's
            # dQ/da rarely flips sign early, so unregularized ascent
            # saturates the actor (observed on FetchReach round 5: constant
            # [-1,1,-1,-1] policy fleeing the goal). Penalizing post-tanh
            # squares is equivalent in effect near the corners.
            loss = loss + config.action_l2 * jnp.mean(jnp.square(a))
        # aux carries the UNpenalized E[Q]: q_mean / q_support_frac metrics
        # must stay comparable across action_l2 settings.
        return loss, q_mean

    (actor_loss, batch_q_mean), actor_grads = jax.value_and_grad(
        actor_loss_fn, has_aux=True
    )(state.actor_params)
    actor_grads = _sync(actor_grads)
    actor_updates, actor_opt_state = actor_opt.update(
        actor_grads, state.actor_opt_state
    )
    actor_params = optax.apply_updates(state.actor_params, actor_updates)

    # ---- Polyak target updates (reference ddpg.py:250 → 110-116) ----
    new_state = state.replace(
        step=state.step + 1,
        key=new_key,
        actor_params=actor_params,
        critic_params=critic_params,
        target_actor_params=polyak_update(
            state.target_actor_params, actor_params, config.tau
        ),
        target_critic_params=polyak_update(
            state.target_critic_params, critic_params, config.tau
        ),
        actor_opt_state=actor_opt_state,
        critic_opt_state=critic_opt_state,
    )
    n_stack = _stacked_critics(config)
    step_metrics = {
        # Per-critic scale: the stacked loss SUMS its members (right for
        # the gradient), but the logged metric must stay comparable to
        # single-critic runs.
        "critic_loss": critic_loss / n_stack if n_stack else critic_loss,
        "actor_loss": actor_loss,
        "priority_mean": jnp.mean(priorities),
        # From the loss aux, NOT -actor_loss: with action_l2 the loss
        # carries the penalty term and would understate E[Q].
        "q_mean": batch_q_mean,
    }
    if config.dist.kind == "categorical":
        # Support-saturation monitor: fraction of the categorical support
        # [v_min, v_max] the mean Q occupies. The Humanoid v1500 study
        # (runs/humanoid_ondevice_v1500) found q_mean pinned at v_max
        # costing ~15% of final return — and nothing in the curves showed
        # it. Values creeping toward 1.0 mean the support is clipping the
        # value distribution; widen v_max. Categorical head only: the
        # scalar and MoG heads are unbounded, so the ratio would be an
        # alarm with no referent there.
        step_metrics["q_support_frac"] = (batch_q_mean - config.dist.v_min) / (
            config.dist.v_max - config.dist.v_min
        )
    metrics = _sync(step_metrics)
    if descent is not None:
        return new_state, metrics, priorities, descent_idx
    return new_state, metrics, priorities


def jit_train_step(config: D4PGConfig, donate: bool = True):
    """The train step specialized + jitted for a fixed config, with the state
    buffer donated so params/moments update in place on device."""
    fn = partial(train_step, config)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def gather_batches(store, idx: jax.Array) -> dict:
    """Bulk-gather [K, B] batches from a columnar store (device replay or
    pool) in ONE op per field. Doing this before the train scan instead of
    per-step inside it measured ~2.2x on v5e (per-step RBG PRNG + scattered
    HBM reads dominate otherwise)."""
    batches = {
        k: getattr(store, k)[idx] if not isinstance(store, dict) else store[k][idx]
        for k in ("obs", "action", "reward", "next_obs", "discount")
    }
    batches["weights"] = jnp.ones(idx.shape, jnp.float32)
    return batches


def fused_train_scan(
    config: D4PGConfig,
    state: TrainState,
    batches: dict,
    axis_name: str | None = None,
    sync_fn=None,
):
    """Scan ``train_step`` over pre-gathered [K, B] batches — the shared
    inner loop of the on-device trainer, the benchmark, and the host
    trainer's ``steps_per_dispatch`` mode (one dispatch per K grad steps
    amortizes per-call latency, which dominates on remote/tunneled TPUs).
    ``axis_name``/``sync_fn`` thread through to each step's gradient
    combine (DP under shard_map; the sharded megastep's deterministic
    mean). Returns (state, metrics pytree with leading K axis,
    priorities [K, B])."""

    def body(st, batch):
        st, metrics, priorities = train_step(
            config, st, batch, axis_name=axis_name, sync_fn=sync_fn
        )
        return st, (metrics, priorities)

    state, (metrics, priorities) = jax.lax.scan(body, state, batches)
    return state, metrics, priorities
