"""Train state and algorithm configuration.

The reference scatters algorithm state across a ``DDPG`` object, two local
Adams, two ``SharedAdam``s, a shared counter tensor, and three global RNGs
(``ddpg.py:18-89``, ``main.py:382-386``). Here ALL mutable training state is
one immutable pytree — params, targets, optimizer moments, step counter, PRNG
key — so it jits, shards, donates, and checkpoints as a unit (SURVEY.md §5
'checkpoint/resume' and 'distributed comm backend').
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
from flax import struct

from d4pg_tpu.models.critic import DistConfig


@dataclass(frozen=True)
class D4PGConfig:
    """Static algorithm hyperparameters.

    Covers every in-code default the reference hides (SURVEY.md §5 'config'):
    lrs (``ddpg.py:19``), tau (``main.py:40``), gamma, n-step, PER α/β/ε
    (``ddpg.py:81-87``), Adam betas (``shared_adam.py:4``), noise scale
    (``random_process.py:13``), support (``main.py:373-376``).
    """

    obs_dim: int = 3
    action_dim: int = 1
    hidden_sizes: tuple = (256, 256, 256)
    # Pixel observations (BASELINE.json config 4): when set to (H, W, C),
    # obs arrive flattened with obs_dim == H·W·C and both networks conv-encode
    # them (d4pg_tpu/models/encoders.py) in front of the MLP trunk.
    pixel_shape: tuple | None = None
    encoder_embed_dim: int = 50
    # DrQ random-shift augmentation of pixel batches inside the train step
    # (ops/augment.py). Effectively required: without it the conv critic
    # overfits and pixel tasks sit at random-policy return indefinitely
    # (measured on pixel_pendulum). 0 disables.
    augment_pad: int = 4
    dist: DistConfig = field(default_factory=DistConfig)
    gamma: float = 0.99
    n_step: int = 1
    tau: float = 0.001
    lr_actor: float = 1e-4
    lr_critic: float = 1e-4
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    noise_kind: str = "gaussian"  # "gaussian" | "ou"
    noise_epsilon: float = 0.3
    noise_sigma: float = 1.0
    ou_theta: float = 0.15
    ou_sigma: float = 0.2
    ou_mu: float = 0.0
    # exploration-scale annealing over env steps (0 = constant, the
    # reference's effective behavior — its ε-decay never fires, quirk #10)
    noise_decay_steps: int = 0
    noise_scale_final: float = 0.1
    # HER-DDPG additions (Andrychowicz et al. 2017, §4.4) — both default
    # OFF so every non-goal config is byte-identical to before:
    # with probability random_eps a collection action is replaced by a
    # uniform draw from the action box (the anti-corner-collapse mixture),
    # and action_l2 penalizes mean(a^2) in the actor loss.
    random_eps: float = 0.0
    action_l2: float = 0.0
    # PER
    prioritized: bool = True
    per_alpha: float = 0.6
    per_beta0: float = 0.4
    per_beta_steps: int = 100_000
    per_eps: float = 1e-6
    # priority signal: "ce" (true distributional TD) or "overlap"
    # (reference-compatible surrogate, ddpg.py:220-222)
    priority_kind: str = "ce"
    # compute dtype for network matmuls ("float32" | "bfloat16"). The
    # bf16 policy is: fp32 master weights / Adam moments / Polyak targets
    # and fp32 loss accumulation always; bf16 activations through the
    # actor/critic trunks; target-network params cast to bf16 once per
    # train step (forward-only — halves target-path param bytes, the
    # HBM-bound part of the step per bench.py's roofline).
    compute_dtype: str = "float32"
    # categorical projection implementation, an oracle ladder:
    #   "xla"          — one-hot matmul reference (ops/categorical.py);
    #   "pallas"       — hand-written projection kernel, XLA loss
    #                    (d4pg_tpu/ops/pallas_projection.py);
    #   "pallas_fused" — ONE kernel for projection + log-softmax CE +
    #                    priority signals; the projected distribution never
    #                    touches HBM (fwd or bwd). Each rung is validated
    #                    against the one above it in tests.
    projection_backend: str = "xla"
    # Twin critics with a clipped-min target (TD3's fix for the DDPG-family
    # overestimation spiral, applied distributionally: the Bellman backup
    # uses whichever target critic's distribution has the SMALLER expected
    # value, per sample). Beyond-reference capability: measured necessary
    # for Hopper/Walker2d-class tasks, where single-critic D4PG plateaus at
    # ~2000 while the true policy ceiling is ~3000+ (runs/hopper_ondevice_*
    # hyperparameter study, round 3). Critic params/targets/opt-state gain
    # a leading [2] axis; the actor trains against critic 0 (TD3
    # convention); PER priorities average the two critics' TD magnitudes.
    twin_critic: bool = False
    # REDQ-style critic ensemble (Chen et al. 2021), the capacity arc the
    # sharded learner unlocks (ROADMAP item 2): E independent critics
    # stacked on a leading [E] axis (params/targets/opt-state — the twin
    # stack generalized), each Bellman target taking the min over a RANDOM
    # SUBSET of ``ensemble_min_targets`` target critics (redrawn per grad
    # step from the TrainState key), the actor ascending the ensemble-MEAN
    # value. 0 disables (the single/twin paths are byte-unchanged); E >= 2
    # enables and is mutually exclusive with twin_critic (the ensemble
    # subsumes it). The stack axis is a first-class mesh-shardable dim in
    # the partition rules (parallel/partition.py:stack_axes_for), so wide
    # ensembles shard members across the mesh instead of replicating E×
    # the params.
    critic_ensemble: int = 0
    # Size M of the random target subset (REDQ's in-target minimization):
    # min over M of E controls the under/overestimation trade — M=2 is
    # the paper's setting; M=E recovers "min over all".
    ensemble_min_targets: int = 2


class TrainState(struct.PyTreeNode):
    """The complete learner state as a single pytree."""

    step: jax.Array
    actor_params: Any
    critic_params: Any
    target_actor_params: Any
    target_critic_params: Any
    actor_opt_state: Any
    critic_opt_state: Any
    key: jax.Array
