"""Dynamic micro-batching around the jitted deterministic actor.

The SEED-RL-shaped core of the serving subsystem: requests from any number
of connections funnel into ONE bounded queue consumed by ONE device thread,
which assembles batches under a ``(max_batch, max_wait_us)`` window — a
batch dispatches when it reaches ``max_batch`` rows or when ``max_wait_us``
has elapsed since its first request, whichever comes first. Batching turns
N tiny actor forwards into one device call, which is the entire throughput
story: per-call dispatch latency dominates a 3×256 MLP forward by orders of
magnitude (docs/REMOTE_TPU.md measures ~100 ms per call through a tunneled
link; even locally a dispatch is ~ms against a ~µs forward).

Shape discipline: batches are padded up to a small fixed ladder of bucket
sizes (powers of two up to ``max_batch``), so ``act_deterministic``
compiles ONCE per bucket at warmup and never again — in particular a
checkpoint hot-reload swaps ``params`` as a traced argument (same pytree
structure/shapes/dtypes ⇒ jit cache hit). :attr:`DynamicBatcher.compile_count`
counts actual traces via a trace-time side effect, so tests assert the
no-recompile property directly.

The staged observation batch is donated to the device computation
(``donate_argnums``): the input buffer's device memory is reused for the
output instead of holding both live — the same donation discipline as the
train step.

Load shedding is explicit and immediate: a full queue rejects the request
with ``queue_full`` (the caller replies ``OVERLOADED`` — clients see a
fast, honest no instead of a diverging latency tail), and requests whose
deadline expired while queued are dropped at assembly time with
``deadline`` (running them would waste a batch slot on an answer the
client already gave up on).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.analysis.ledger import NULL_LEDGER
from d4pg_tpu.serve.stats import ServeStats
from d4pg_tpu.utils.profiling import StageTimers
from d4pg_tpu.analysis import lockwitness


class ShedError(Exception):
    """The request was load-shed, not failed. ``reason`` is the wire reason
    (``queue_full`` | ``deadline`` | ``draining``)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _Request:
    __slots__ = ("obs", "deadline", "future", "t_submit")

    def __init__(self, obs, deadline, future, t_submit):
        self.obs = obs
        self.deadline = deadline    # absolute perf_counter seconds, or None
        self.future = future
        self.t_submit = t_submit


def default_buckets(max_batch: int) -> tuple:
    """Powers of two up to ``max_batch``, always ending exactly at it."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(sorted(set(out)))


class DynamicBatcher:
    """Single-device-thread dynamic batcher over ``act_deterministic``.

    ``submit(obs, deadline_s)`` → Future resolving to the env-scale action
    (normalize → actor → clip(−1,1) → affine to [low, high]); raises
    :class:`ShedError` through the future (or synchronously on queue-full)
    when shed.
    """

    # Unguarded cross-thread writes, each safe by argument (d4pglint
    # shared-mutable-state contract):
    _THREAD_SAFE = (
        # single transition None→exception; readers check-then-raise
        "_thread_error",
        # device thread is the ONLY writer (single-device-thread design);
        # the reply thread never touches the rotation
        "_staging_flip",
    )

    def __init__(
        self,
        config: D4PGConfig,
        params,
        *,
        max_batch: int = 64,
        max_wait_us: int = 2000,
        queue_limit: int = 256,
        buckets: Optional[Sequence[int]] = None,
        action_low=None,
        action_high=None,
        obs_norm_stats: Optional[dict] = None,
        obs_norm_clip: float = 5.0,
        obs_norm_eps: float = 1e-2,
        stats: Optional[ServeStats] = None,
        timers: Optional[StageTimers] = None,
        ledger=None,
        sentinel=None,
        guard_transfers: bool = False,
        name: str = "serve",
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_limit < max_batch:
            raise ValueError(
                f"queue_limit ({queue_limit}) must be >= max_batch "
                f"({max_batch}): a full window must fit in the queue"
            )
        self.config = config
        self.max_batch = int(max_batch)
        self.max_wait_s = max_wait_us / 1e6
        self.queue_limit = int(queue_limit)
        self.buckets = (
            tuple(sorted(set(int(b) for b in buckets)))
            if buckets
            else default_buckets(max_batch)
        )
        if self.buckets[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket ({self.buckets[-1]}) must equal max_batch "
                f"({self.max_batch})"
            )
        self.stats = stats or ServeStats(
            batch_edges=self.buckets,
            queue_edges=default_buckets(max(queue_limit, 1)),
        )
        self.timers = timers or StageTimers(annotate_prefix="serve/")

        self._obs_clip = float(obs_norm_clip)
        self._obs_norm_eps = float(obs_norm_eps)
        # Published as ONE (mean, std) tuple read exactly once per
        # normalize — hot reload (set_obs_norm) swaps it atomically from
        # the watcher thread while submit() reads it (the obs_norm.py
        # single-tuple-publication discipline).
        self._obs_pub = self._derive_obs_pub(obs_norm_stats)

        low = (
            np.full(config.action_dim, -1.0, np.float32)
            if action_low is None
            else np.asarray(action_low, np.float32)
        )
        high = (
            np.full(config.action_dim, 1.0, np.float32)
            if action_high is None
            else np.asarray(action_high, np.float32)
        )

        import jax
        import jax.numpy as jnp

        from d4pg_tpu.agent import act_deterministic

        self._trace_count = 0
        identity_bounds = bool(np.all(low == -1.0) and np.all(high == 1.0))
        low_j, high_j = jnp.asarray(low), jnp.asarray(high)

        def infer(params, obs):
            # Trace-time side effect: this line executes only when jit
            # actually (re)traces — the compile counter hot-reload tests
            # assert on.
            self._trace_count += 1
            a = jnp.clip(act_deterministic(config, params, obs), -1.0, 1.0)
            if not identity_bounds:
                a = low_j + (a + 1.0) * 0.5 * (high_j - low_j)
            return a

        # The obs batch is DONATED: its device buffer is dead after the
        # forward and XLA may write the actions into it.
        self._infer = jax.jit(infer, donate_argnums=(1,))
        # Recompile sentinel (--debug-guards): the jit cache must hold
        # exactly one program per bucket after warmup; a hot reload or a
        # stray dtype drift that retraces trips check(). The trace-count
        # side effect above stays as the wire-visible compile_count.
        # ``name`` scopes the sentinel entry and the ledger staging groups:
        # a multi-policy server runs one batcher PER resident policy, and
        # two batchers sharing the literal "serve.infer" key would pool
        # their compile budgets (hiding a per-policy retrace) and alias
        # each other's staging-slot generations. Default stays "serve" so
        # single-policy traces/budgets are unchanged.
        self.name = name
        self._sentinel = sentinel
        if sentinel is not None:
            sentinel.track(
                f"{name}.infer", self._infer, budget=len(self.buckets)
            )
        # Transfer guard (--debug-guards): steady-state dispatch must see
        # only device-resident operands; the staging device_put below is
        # the one explicit, exempt copy. Resolved once here — the device
        # loop must not pay import machinery per batch.
        self._dispatch_guard = contextlib.nullcontext
        if guard_transfers:
            from d4pg_tpu.analysis.transfer import no_implicit_transfers

            self._dispatch_guard = no_implicit_transfers
        self._jnp = jnp
        # Params live on device once; set_params swaps this reference
        # atomically (device thread reads it once per batch, so an in-flight
        # batch finishes on the params it started with).
        self._params = jax.device_put(params)
        self._device_put = jax.device_put

        # Preallocated per-bucket host staging, TWO rotating slots per
        # bucket: device_put may copy from host memory asynchronously, so
        # the buffer a dispatch was staged from must not be overwritten
        # while its H2D can still be in flight. Two slots are sufficient
        # ONLY because ``_inflight`` below bounds the device thread to two
        # outstanding batches: the reply thread's ``np.asarray`` on batch N
        # synchronizes on N's compute — which device-order implies N's H2D
        # finished — before releasing the permit that lets the device
        # thread stage batch N+2 into N's slot. Without that bound an
        # async backend (TPU dispatch returns immediately) would let the
        # host run arbitrarily far ahead, overwriting live staging and
        # growing the reply queue without limit.
        self._staging = {
            b: [np.zeros((b, config.obs_dim), np.float32) for _ in range(2)]
            for b in self.buckets
        }
        self._staging_flip = {b: 0 for b in self.buckets}
        self._inflight = threading.Semaphore(2)
        # Staging ledger (--debug-guards): generation-tags the 2-slot
        # rotation above; a write into a slot whose dispatch the reply
        # thread hasn't fetched yet raises at the overwrite site. Group
        # names precomputed — no per-batch f-string on the device loop.
        self._ledger = ledger if ledger is not None else NULL_LEDGER
        self._staging_group = {b: f"{name}.staging[{b}]" for b in self.buckets}
        # Test hook (staging-ledger stress test): pin the rotation to one
        # slot to seed the PR-2/PR-3 early-reuse bug class deliberately.
        self._test_force_flip: Optional[int] = None

        self._queue: deque[_Request] = deque()
        # Witnessed under --debug-guards: the name is the lock's static
        # node id in benchmarks/lock_order_graph.json (lockwitness docs).
        self._cond = lockwitness.named_condition("DynamicBatcher._cond")
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._thread_error: Optional[BaseException] = None
        # Reply distribution runs on its OWN thread: resolving futures fires
        # the callers' callbacks (the server writes a socket frame per
        # reply), and doing that inline would stall the device thread for
        # the whole fan-out — the next batch's assembly+dispatch should
        # overlap it instead. The device thread hands over the DEVICE
        # result array; the reply thread pays the D2H fetch too.
        self._reply_q: deque = deque()
        self._reply_cond = lockwitness.named_condition(
            "DynamicBatcher._reply_cond"
        )
        self._reply_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self, warmup: bool = True) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("batcher device thread already running")
        if warmup:
            self.warmup()
        with self._cond:  # same guard as every other _draining/_stopped write
            self._draining = False
            self._stopped = False
        self._thread = threading.Thread(
            target=self._device_loop, name="serve-batcher", daemon=True
        )
        self._thread.start()
        self._reply_thread = threading.Thread(
            target=self._reply_loop, name="serve-reply", daemon=True
        )
        self._reply_thread.start()

    def warmup(self) -> None:
        """Compile every bucket up front so no live request ever pays a
        compile (first-request latency would otherwise be seconds)."""
        import warnings

        with warnings.catch_warnings():
            # The CPU backend cannot honor donation and says so once per
            # bucket compile; on accelerators the donation is real. The
            # condition is expected, not actionable — keep serve logs clean.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            for b in self.buckets:
                a = self._infer(
                    self._params, self._jnp.zeros((b, self.config.obs_dim))
                )
            np.asarray(a)

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the device thread. ``drain=True``: new submissions shed
        ``draining`` but everything already queued is answered first."""
        with self._cond:
            self._draining = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._shed(req, "draining")
            self._stopped = not drain or not self._queue
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise RuntimeError("batcher device thread failed to drain")
            self._thread = None
        if self._reply_thread is not None:
            with self._reply_cond:
                self._reply_q.append(None)  # sentinel AFTER the last batch
                self._reply_cond.notify()
            self._reply_thread.join(timeout)
            if self._reply_thread.is_alive():
                raise RuntimeError("batcher reply thread failed to drain")
            self._reply_thread = None

    @property
    def compile_count(self) -> int:
        """Number of times the inference function was traced (== compiled
        programs). Stable across hot reloads by construction."""
        return self._trace_count

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def check_alive(self) -> None:
        if self._thread_error is not None:
            raise RuntimeError("batcher device thread died") from self._thread_error

    # ------------------------------------------------------------ hot reload
    def set_params(self, params, version: Optional[int] = None) -> None:
        """Swap serving params. The new pytree must match the compiled
        structure/shapes (same actor architecture) — then the swap is a jit
        cache hit and costs zero recompiles; a mismatch raises here, before
        the live reference moves."""
        import jax

        new = jax.device_put(params)
        old_td = jax.tree_util.tree_structure(self._params)
        new_td = jax.tree_util.tree_structure(new)
        if old_td != new_td:
            raise ValueError("new params tree structure differs from serving tree")
        for a, b in zip(
            jax.tree_util.tree_leaves(self._params), jax.tree_util.tree_leaves(new)
        ):
            if np.shape(a) != np.shape(b):
                raise ValueError(
                    f"new params leaf shape {np.shape(b)} differs from "
                    f"serving shape {np.shape(a)}"
                )
        self._params = new  # atomic reference swap
        self.stats.inc("params_reloads")
        if version is not None:
            with self.stats._lock:
                self.stats.params_version = version
        else:
            self.stats.inc("params_version")

    def _derive_obs_pub(self, stats: Optional[dict]):
        """(mean_f32, std_f32_floored) from persisted Welford stats, or
        None when normalization is off — the same derivation the trainer's
        RunningObsNorm.load_state_dict applies."""
        if stats is None:
            return None
        count = float(stats["count"])
        mean = np.asarray(stats["mean"], np.float64)
        if mean.shape != (self.config.obs_dim,):
            raise ValueError(
                f"obs_norm stats are {mean.shape}-shaped, obs_dim is "
                f"{self.config.obs_dim}"
            )
        m2 = np.asarray(stats["m2"], np.float64)
        std = (
            np.sqrt(np.maximum(m2 / count, 0.0))
            if count > 0
            else np.ones_like(mean)
        )
        return (
            mean.astype(np.float32),
            np.maximum(std, self._obs_norm_eps).astype(np.float32),
        )

    def set_obs_norm(self, stats: Optional[dict]) -> None:
        """Hot-swap the normalizer statistics (bundle re-export flow):
        params trained under fresher running statistics must be served
        with them — swapping one without the other silently scales the
        net's inputs off its trained distribution."""
        self._obs_pub = self._derive_obs_pub(stats)  # atomic publication

    # ------------------------------------------------------------ submission
    def _normalize(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32).reshape(self.config.obs_dim)
        pub = self._obs_pub  # one read: matched (mean, std), never torn
        if pub is None:
            return obs
        mean, std = pub
        return np.clip((obs - mean) / std, -self._obs_clip, self._obs_clip)

    def submit(self, obs: np.ndarray, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one observation. ``deadline_s`` is relative seconds the
        client is willing to wait; past it the request is shed rather than
        computed. Raises :class:`ShedError` synchronously on queue-full /
        draining (the fast path for the overload reply)."""
        self.check_alive()
        self.stats.inc("requests_total")
        t = time.perf_counter()
        req = _Request(
            self._normalize(obs),
            None if deadline_s is None else t + deadline_s,
            Future(),
            t,
        )
        with self._cond:
            if self._draining:
                self.stats.inc("shed_draining")
                raise ShedError("draining")
            if len(self._queue) >= self.queue_limit:
                self.stats.inc("shed_queue_full")
                raise ShedError("queue_full")
            self._queue.append(req)
            self.stats.inc("inflight")
            self.stats.queue_hist.add(len(self._queue))
            self._cond.notify()
        # Outside the lock: the callback may fire inline if the device
        # thread already resolved the future, and it takes the stats lock.
        # add_done_callback fires exactly once on EVERY resolution path
        # (reply, shed, device/reply-thread death sweep, cancel), which is
        # what makes the gauge trustworthy as a dispatch-weight signal.
        req.future.add_done_callback(self._dec_inflight)
        return req.future

    def _dec_inflight(self, _fut) -> None:
        self.stats.inc("inflight", -1)

    def _shed(self, req: _Request, reason: str) -> None:
        if reason == "deadline":
            self.stats.inc("shed_deadline")
        elif reason == "draining":
            self.stats.inc("shed_draining")
        if not req.future.set_running_or_notify_cancel():
            return
        req.future.set_exception(ShedError(reason))

    # ------------------------------------------------------------ device loop
    def _take_batch(self) -> Optional[list]:
        """Block for the first request, then fill the window: up to
        ``max_batch`` rows or ``max_wait_s`` after the first row, whichever
        first. Returns None when stopped and drained."""
        with self._cond:
            while not self._queue:
                if self._stopped or (self._draining and not self._queue):
                    return None
                self._cond.wait(0.05)
            batch = [self._queue.popleft()]
            window_end = time.perf_counter() + self.max_wait_s
            while len(batch) < self.max_batch:
                while self._queue and len(batch) < self.max_batch:
                    batch.append(self._queue.popleft())
                if len(batch) >= self.max_batch or self._draining:
                    break
                remaining = window_end - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                if not self._queue and time.perf_counter() >= window_end:
                    break
            return batch

    def _device_loop(self) -> None:
        live: list = []  # the in-hand batch; ownership moves to the reply
        # queue on append, so the except sweep below never double-resolves
        try:
            while True:
                batch = self._take_batch()
                if batch is None:
                    return
                now = time.perf_counter()
                live = []
                for req in batch:
                    if req.deadline is not None and now > req.deadline:
                        self._shed(req, "deadline")
                    elif req.future.set_running_or_notify_cancel():
                        live.append(req)
                if not live:
                    continue
                n = len(live)
                bucket = next(b for b in self.buckets if b >= n)
                # Backpressure: at most 2 batches between here and the
                # reply thread's fetch (staging-slot safety + bounded
                # reply queue). The timeout loop keeps a dead reply
                # thread from wedging this one forever.
                while not self._inflight.acquire(timeout=0.5):
                    if self._thread_error is not None:
                        raise RuntimeError(
                            "reply thread died; device thread stopping"
                        ) from self._thread_error
                with self.timers.stage("assemble"):
                    flip = self._staging_flip[bucket]
                    if self._test_force_flip is not None:
                        flip = self._test_force_flip
                    self._staging_flip[bucket] = 1 - flip
                    self._ledger.write(self._staging_group[bucket], flip)
                    staging = self._staging[bucket][flip]
                    for i, req in enumerate(live):
                        staging[i] = req.obs
                with self.timers.stage("device_infer"):
                    # device_put copies the staging slot to a fresh device
                    # buffer (which infer then donates) — the one explicit,
                    # guard-exempt transfer. The dispatch is async — the
                    # reply thread pays the D2H fetch, so this thread moves
                    # straight on to the next batch.
                    dev_obs = self._device_put(staging)
                    with self._dispatch_guard():
                        dev_actions = self._infer(self._params, dev_obs)
                # The hold pins the staging slot until the reply thread's
                # D2H fetch proves the dispatch (and its H2D) finished.
                # holder formatted only for a real ledger — guards-off
                # batches must not pay a per-batch f-string.
                hold = self._ledger.hold(
                    self._staging_group[bucket], flip,
                    holder=(
                        f"dispatch(n={n})"
                        if self._ledger is not NULL_LEDGER
                        else None
                    ),
                )
                with self._reply_cond:
                    self._reply_q.append((live, dev_actions, hold))
                    self._reply_cond.notify()
                live = []  # resolved (or failed) by the reply thread now
                self.stats.observe_batch(n, bucket)
                with self._cond:
                    if self._draining and not self._queue:
                        self._stopped = True
                        self._cond.notify_all()
        except BaseException as e:
            self._thread_error = e
            # Fail everything this thread still owns — the queue AND the
            # in-hand `live` batch (whose futures are already RUNNING but
            # were never handed to the reply queue): a dead device thread
            # must not leave any client waiting out its full timeout.
            for req in live:
                if not req.future.done():
                    req.future.set_exception(e)
            with self._cond:
                pending, self._queue = list(self._queue), deque()
                self._stopped = True
                self._cond.notify_all()
            for req in pending:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(e)
            raise

    def _reply_loop(self) -> None:
        try:
            while True:
                with self._reply_cond:
                    # Bounded wait: the notifier (device thread) can die
                    # without stop() ever pushing the sentinel — this
                    # thread must wake on its own clock and EXIT once the
                    # device thread is gone and the reply queue is drained
                    # (its death sweep already failed everything queued
                    # behind us).
                    while not self._reply_q:
                        if self._thread_error is not None:
                            return
                        self._reply_cond.wait(0.5)
                    item = self._reply_q.popleft()
                if item is None:
                    return
                live, dev_actions, hold = item
                with self.timers.stage("reply"):
                    # D2H fetch synchronizes on this batch's compute (and
                    # transitively its H2D) — its staging slot is free the
                    # moment this returns, so the permit (and the ledger
                    # hold) is released here.
                    actions = np.asarray(dev_actions)
                    hold.release()
                    self._inflight.release()
                    t_done = time.perf_counter()
                    for i, req in enumerate(live):
                        # per-row copy: the futures outlive this loop and
                        # must not alias one shared buffer — aliasing IS
                        # the bug class the ledger polices
                        req.future.set_result(actions[i].copy())  # d4pglint: disable=hot-path-alloc
                        self.stats.latency.add(t_done - req.t_submit)
                    self.stats.inc("replies_ok", len(live))
        except BaseException as e:
            self._thread_error = e
            # fail the batches still queued for reply, then everything in
            # the submit queue via the device-thread contract; the device
            # thread notices _thread_error in its bounded acquire loop
            with self._reply_cond:
                items, self._reply_q = list(self._reply_q), deque()
            for item in items:
                if item is None:
                    continue
                for req in item[0]:
                    if not req.future.done():
                        req.future.set_exception(e)
            raise
