"""Socket client for the policy server: blocking or pipelined.

``act`` is the simple call; ``act_async`` pipelines — many requests in
flight on one connection, matched to replies by the echoed ``req_id`` on a
dedicated reader thread. The pipelined form is what the open-loop load
generator (``bench.py bench_serve``) is built on: an open-loop arrival
process must keep issuing at its offered rate regardless of reply latency,
which a blocking call cannot do.

Bounded retry (``retries=``, OFF by default): ``act`` re-attempts on
:class:`Overloaded` / :class:`ConnectionClosed` under a seeded jittered
:class:`~d4pg_tpu.utils.retry.Backoff`, transparently re-dialing a dead
link between attempts. Off by default on purpose — a shed is an explicit
server signal and most callers (the load generators, the shed-rate tests)
must SEE it, not have it retried away. The retry path serializes
reconnects behind a lock but is meant for blocking single-caller use;
``act_async`` never retries (a pipelined caller owns its own policy).
The replica front-end (``serve/router.py``) keeps its dispatch links at
``retries=0`` — its recovery is failover to a DIFFERENT replica, not a
hammer on the same one — and implements that failover with the same
``Backoff`` budget.
"""

from __future__ import annotations

import random
import socket
import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np

from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.utils.retry import Backoff
from d4pg_tpu.analysis import lockwitness


class Overloaded(RuntimeError):
    """The server shed the request (reason: queue_full | deadline |
    draining). Retry with backoff if you must; the action was not computed."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class ServerError(RuntimeError):
    """Server-side failure or protocol violation reply."""


class ConnectionClosed(RuntimeError):
    """The connection died with requests still in flight."""


class PolicyClient:
    # d4pglint shared-mutable-state: single transition None→exception by
    # the reader thread; submitters read it check-then-fail (the
    # mark-dead-then-sweep ordering note in _read_loop)
    _THREAD_SAFE = ("_dead",)

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        *,
        retries: int = 0,
        retry_seed: Optional[int] = None,
        policy_id: Optional[str] = None,
        qos: Optional[str] = None,
        tenant: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        # Multi-tenant identity (all optional): with NONE of them set the
        # client emits v1 ``ACT`` frames byte-identical to the PR-8 wire —
        # full interop with old servers. Setting any switches requests to
        # the v2 ``ACT2`` frame (policy routing + router QoS/quota
        # admission); against an OLD server those fail loudly with the
        # server's "protocol version" ERROR, never a decode crash.
        self.policy_id = policy_id
        self.tenant = tenant or ""
        if qos is not None and qos not in ("interactive", "bulk"):
            raise ValueError(f"qos must be 'interactive' or 'bulk', got {qos!r}")
        self.qos = qos
        # Opt-in bounded retry for act(): attempts beyond the first on
        # Overloaded/ConnectionClosed, paced by a seeded Backoff (jitter
        # must not synchronize a retrying fleet; seeding keeps chaos runs
        # deterministic). 0 = historical fast-fail semantics.
        self._retries = int(retries)
        self._retry_rng = random.Random(retry_seed)
        # Serializes _reconnect against concurrent act() retries; never
        # held while blocking on a reply (only during dial/teardown).
        self._conn_lock = lockwitness.named_lock("PolicyClient._conn_lock")
        self._send_lock = lockwitness.named_lock("PolicyClient._send_lock")
        self._pending: dict[int, Future] = {}
        self._pending_lock = lockwitness.named_lock(
            "PolicyClient._pending_lock"
        )
        self._next_id = 0
        self._closed = False
        self._connect()

    def _connect(self) -> None:
        """Dial and arm a fresh link (init + the retry path's re-dial)."""
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        # ``timeout`` governs CONNECT and the default future wait in act();
        # the socket itself must block indefinitely — the reader thread sits
        # in read() between replies, and a socket timeout there would kill
        # the reader (and with it the whole client) after `timeout` idle
        # seconds on a perfectly healthy connection.
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Buffered read side (same rationale as the server): one kernel
        # read per burst of pipelined replies, not per frame piece.
        self._rfile = self._sock.makefile("rb")
        with self._pending_lock:
            self._pending = {}
        # Terminal error once the reader exits: without it, a request
        # issued AFTER the reader died would register a future nobody can
        # ever resolve (the send usually still succeeds into the kernel
        # buffer of a FIN'd socket) and hang its caller for the full
        # timeout instead of failing fast.
        self._dead: Exception | None = None
        self._reader = threading.Thread(
            target=self._read_loop, name="policy-client-reader", daemon=True
        )
        self._reader.start()

    def _reconnect(self) -> None:
        """Tear down a dead link and dial a new one (retry path only).
        The old reader is joined BEFORE the new link arms so its death
        sweep (which writes ``_dead``) can never clobber the fresh link's
        state; pending futures of the old link were already failed by
        that sweep."""
        with self._conn_lock:
            if self._closed:
                # close() is final: the retry path must not resurrect a
                # closed client with a fresh socket + reader thread the
                # owner will never tear down
                raise ConnectionClosed("client closed")
            if self._dead is None:
                return  # another retrying caller already re-dialed
            try:
                self._sock.close()
            except OSError:
                pass
            # Bounded join under a lock only retrying act() callers ever
            # take (never the reader or any hot path); the old reader MUST
            # be dead before the new link arms, or its death sweep would
            # clobber the fresh link's _dead/_pending.
            self._reader.join(timeout=5)  # d4pglint: disable=lock-blocking-call -- see above: reconnect-only lock, bounded join ordering requirement
            try:
                self._rfile.close()
            except OSError:
                pass
            self._connect()

    # ------------------------------------------------------------------ plumbing
    def _register(self) -> tuple[int, Future]:
        fut: Future = Future()
        with self._pending_lock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            req_id = self._next_id
            self._pending[req_id] = fut
        return req_id, fut

    def _read_loop(self) -> None:
        err: Exception = ConnectionClosed("server closed the connection")
        try:
            while True:
                frame = protocol.read_frame(self._rfile)
                if frame is None:
                    break
                msg_type, req_id, payload = frame
                with self._pending_lock:
                    fut = self._pending.pop(req_id, None)
                if fut is None:
                    # ERROR with req_id 0 is the server's "your framing is
                    # broken, closing" notice — surface it to every waiter.
                    if msg_type == protocol.ERROR:
                        err = ServerError(payload.decode("utf-8", "replace"))
                        break
                    continue
                if msg_type == protocol.ACT_OK:
                    fut.set_result(protocol.decode_action(payload))
                elif msg_type == protocol.FEEDBACK_OK:
                    fut.set_result(True)
                elif msg_type == protocol.HEALTHZ_OK:
                    fut.set_result(payload.decode("utf-8", "replace"))
                elif msg_type == protocol.OVERLOADED:
                    fut.set_exception(
                        Overloaded(payload.decode("utf-8", "replace"))
                    )
                elif msg_type == protocol.ERROR:
                    fut.set_exception(
                        ServerError(payload.decode("utf-8", "replace"))
                    )
                else:
                    fut.set_exception(
                        ProtocolError(f"unexpected reply type {msg_type}")
                    )
        except (OSError, ProtocolError) as e:
            if not self._closed:
                err = ConnectionClosed(str(e))
        finally:
            # Order: mark dead FIRST, then sweep — a racing act_async
            # either lands in the swept dict (failed here) or sees _dead
            # after registering and fails itself.
            self._dead = err
            with self._pending_lock:
                pending, self._pending = list(self._pending.values()), {}
            for fut in pending:
                if not fut.done():
                    fut.set_exception(err)

    def _send(self, msg_type: int, req_id: int, payload: bytes) -> None:
        with self._send_lock:
            protocol.write_frame(self._sock, msg_type, req_id, payload)

    # ------------------------------------------------------------------ API
    def _fail_if_dead(self, req_id: int, fut: Future) -> bool:
        if self._dead is None:
            return False
        with self._pending_lock:
            self._pending.pop(req_id, None)
        if not fut.done():
            fut.set_exception(self._dead)
        return True

    def act_async(
        self,
        obs: np.ndarray,
        deadline_ms: Optional[float] = None,
        *,
        policy_id: Optional[str] = None,
        qos: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Future:
        req_id, fut = self._register()
        if self._fail_if_dead(req_id, fut):
            return fut
        deadline_us = int(deadline_ms * 1e3) if deadline_ms else 0
        policy_id = policy_id if policy_id is not None else self.policy_id
        qos = qos if qos is not None else self.qos
        tenant = tenant if tenant is not None else self.tenant
        if policy_id is None and qos is None and not tenant:
            # pure v1 request: byte-identical to the PR-8 client's frame
            msg_type = protocol.ACT
            payload = protocol.encode_act(obs, deadline_us)
        else:
            msg_type = protocol.ACT2
            payload = protocol.encode_act2(
                obs, deadline_us,
                policy_id=policy_id or protocol.DEFAULT_POLICY,
                qos=(
                    protocol.QOS_BULK if qos == "bulk"
                    else protocol.QOS_INTERACTIVE
                ),
                tenant=tenant,
            )
        try:
            self._send(msg_type, req_id, payload)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            if not fut.done():
                fut.set_exception(ConnectionClosed(str(e)))
        return fut

    def act(
        self,
        obs: np.ndarray,
        deadline_ms: Optional[float] = None,
        timeout: Optional[float] = None,
        *,
        policy_id: Optional[str] = None,
        qos: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> np.ndarray:
        """One action, blocking. Raises :class:`Overloaded` when shed
        (after the bounded ``retries=`` budget, when one was configured —
        a dead link is re-dialed between attempts). ``policy_id`` /
        ``qos`` / ``tenant`` override the client-level defaults per call."""
        timeout = timeout if timeout is not None else self.timeout
        kw = dict(policy_id=policy_id, qos=qos, tenant=tenant)
        if not self._retries:
            return self.act_async(obs, deadline_ms, **kw).result(timeout)
        last: Optional[Exception] = None
        backoff = Backoff(
            base_s=0.05,
            max_s=2.0,
            max_attempts=self._retries,
            rng=self._retry_rng,
        )
        for _attempt in backoff:
            if self._dead is not None:
                try:
                    self._reconnect()
                except OSError as e:
                    last = ConnectionClosed(f"reconnect failed: {e}")
                    continue
            try:
                return self.act_async(obs, deadline_ms, **kw).result(timeout)
            except (Overloaded, ConnectionClosed) as e:
                last = e  # bounded: the Backoff iterator sleeps, then stops
        assert last is not None
        raise last

    def feedback_async(
        self,
        reward: float,
        action: np.ndarray,
        next_obs: np.ndarray,
        *,
        log_prob: float = 0.0,
        terminated: bool = False,
        truncated: bool = False,
        policy_id: Optional[str] = None,
    ) -> Future:
        """The flywheel reward echo (``FEEDBACK``, frame version 2): the
        env outcome of the EXECUTED action for this connection's previous
        request, with its behavior log-prob. Resolves True on the
        server's ack; against an old server it fails loudly with the
        version ERROR — plain v1 traffic never emits this frame."""
        req_id, fut = self._register()
        if self._fail_if_dead(req_id, fut):
            return fut
        payload = protocol.encode_feedback(
            reward,
            action,
            next_obs,
            log_prob=log_prob,
            terminated=terminated,
            truncated=truncated,
            policy_id=(
                policy_id if policy_id is not None
                else (self.policy_id or protocol.DEFAULT_POLICY)
            ),
        )
        try:
            self._send(protocol.FEEDBACK, req_id, payload)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            if not fut.done():
                fut.set_exception(ConnectionClosed(str(e)))
        return fut

    def feedback(self, *args, timeout: Optional[float] = None, **kw) -> bool:
        return self.feedback_async(*args, **kw).result(
            timeout if timeout is not None else self.timeout
        )

    def healthz(self, timeout: Optional[float] = None) -> dict:
        import json

        req_id, fut = self._register()
        if not self._fail_if_dead(req_id, fut):
            self._send(protocol.HEALTHZ, req_id, b"")
        return json.loads(
            fut.result(timeout if timeout is not None else self.timeout)
        )

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        self._reader.join(timeout=5)
        try:
            self._rfile.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
