"""``python -m d4pg_tpu.serve``: run a policy server from a bundle.

Installs SIGTERM/SIGINT handlers that trigger the graceful drain: stop
accepting, answer everything admitted, then exit 0 — so an orchestrator's
preemption notice never drops admitted requests. A second signal hard-kills
(the handler restores the default disposition after the first).
"""

from __future__ import annotations

import argparse
import sys

from d4pg_tpu.utils.signals import install_graceful_signals


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.serve", description=__doc__
    )
    p.add_argument("--bundle", required=True,
                   help="bundle directory from train.py --export-bundle "
                        "(the DEFAULT policy: v1 clients with no policy-id "
                        "field land here)")
    p.add_argument("--policy", action="append", default=[],
                   metavar="NAME=DIR",
                   help="additional resident policy (repeatable): NAME is "
                        "the ACT2 policy_id, DIR its bundle. Each policy "
                        "gets its own batcher, compile budget, and "
                        "hot-reload watch")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7431,
                   help="0 = ephemeral (printed on startup)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="batch window cap; also the largest compile bucket")
    p.add_argument("--max-wait-us", type=int, default=2000,
                   help="batching window: max microseconds a batch waits "
                        "for more requests after its first")
    p.add_argument("--queue-limit", type=int, default=256,
                   help="bounded request queue; past it requests shed with "
                        "an explicit 'overloaded' reply")
    p.add_argument("--default-deadline-ms", type=float, default=0.0,
                   help="deadline applied to requests that carry none "
                        "(0 = unbounded)")
    p.add_argument("--watch-run", default=None,
                   help="training run dir to hot-reload best_actor.npz "
                        "from when its best_eval.json changes")
    p.add_argument("--no-watch-bundle", dest="watch_bundle",
                   action="store_false",
                   help="disable hot-reloading the bundle dir on re-export")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="hot-reload poll seconds")
    p.add_argument("--log-dir", default=None,
                   help="append serve metrics rows (metrics.jsonl) here")
    p.add_argument("--metrics-interval", type=float, default=30.0)
    p.add_argument("--replica-id", type=int, default=None,
                   help="fleet identity: stamped into healthz and every "
                        "metrics.jsonl row so multi-replica soak logs are "
                        "attributable per process")
    p.add_argument("--mirror-fraction", type=float, default=0.0,
                   help="flywheel mirror tap: fraction of served EPISODES "
                        "(Bresenham-striped per connection) whose "
                        "obs/action/reward traffic is mirrored into "
                        "training windows; needs clients that echo reward "
                        "via FEEDBACK frames (flywheel/sim_client.py)")
    p.add_argument("--mirror-ingest", default=None, metavar="HOST:PORT",
                   help="fleet ingest to stream mirrored WINDOWS2 frames "
                        "to (the learner's --fleet-listen port)")
    p.add_argument("--mirror-spool", default=None, metavar="DIR",
                   help="on-disk spool of mirrored frames (what the "
                        "router's off-policy promotion gate reads); "
                        "independent of --mirror-ingest liveness")
    p.add_argument("--io-read-stall-s", type=float, default=30.0,
                   help="event loop: evict a connection whose partial "
                        "frame makes no completion progress for this long "
                        "(the slowloris bound)")
    p.add_argument("--io-write-stall-s", type=float, default=10.0,
                   help="event loop: evict a connection that drains none "
                        "of its buffered replies for this long (the "
                        "zero-window bound)")
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="deterministic fault injection (d4pg_tpu/chaos.py): "
                        "e.g. 'sock_reset@5' force-resets the serving "
                        "connection at its 5th frame — proves reader/reply "
                        "paths survive abrupt client death; "
                        "'slowloris@N:bps' / 'zero_window@N:ms' / "
                        "'fd_exhaust@N:ms' launch connection-level attacks "
                        "at the Nth accept (netio deadlines must evict)")
    p.add_argument("--debug-guards", action="store_true",
                   help="runtime invariant guards (d4pg_tpu/analysis): "
                        "staging ledger on the batcher's slot rotation, "
                        "recompile sentinel (one program per bucket, "
                        "checked at drain), transfer guard around "
                        "dispatch; trips raise instead of corrupting")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if args.debug_guards:
        # Arm the lock-order witness BEFORE the server builds its locks;
        # drain() checks the recorded nesting against the committed graph,
        # and the conservation ledger checks the serve/tap accounting
        # identities at drain/close.
        from d4pg_tpu.analysis import flowledger, lockwitness

        lockwitness.enable()
        flowledger.enable()
    from d4pg_tpu.serve.bundle import load_bundle
    from d4pg_tpu.serve.server import PolicyServer

    chaos = None
    if args.chaos:
        from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

        chaos = ChaosInjector(ChaosPlan.parse(args.chaos))
    bundle = load_bundle(args.bundle)
    policies = {}
    for spec in args.policy:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise SystemExit(f"--policy wants NAME=DIR, got {spec!r}")
        if name in policies:
            raise SystemExit(f"--policy {name!r} given twice")
        policies[name] = load_bundle(path)
    tap = None
    if args.mirror_fraction > 0:
        from d4pg_tpu.flywheel.spool import MirrorSpool
        from d4pg_tpu.flywheel.tap import MirrorTap

        ingest_addr = None
        if args.mirror_ingest:
            ih, _, ip = args.mirror_ingest.rpartition(":")
            ingest_addr = (ih, int(ip))
        spool = MirrorSpool(args.mirror_spool) if args.mirror_spool else None
        tap = MirrorTap(
            obs_dim=bundle.obs_dim,
            action_dim=bundle.action_dim,
            n_step=bundle.config.n_step,
            gamma=bundle.config.gamma,
            fraction=args.mirror_fraction,
            ingest_addr=ingest_addr,
            spool=spool,
            bundle_dir=args.bundle,
            env=bundle.meta.get("env", "unknown"),
            tap_id=f"mirror-replica-{args.replica_id}"
            if args.replica_id is not None else "mirror-replica",
            chaos=chaos,
        )
    server = PolicyServer(
        bundle,
        policies=policies or None,
        host=args.host,
        port=args.port,
        max_batch=args.max_batch,
        max_wait_us=args.max_wait_us,
        queue_limit=args.queue_limit,
        default_deadline_ms=args.default_deadline_ms,
        watch_run=args.watch_run,
        watch_bundle=args.watch_bundle,
        poll_interval_s=args.poll_interval,
        log_dir=args.log_dir,
        metrics_interval_s=args.metrics_interval,
        debug_guards=args.debug_guards,
        chaos=chaos,
        replica_id=args.replica_id,
        mirror_tap=tap,
        io_read_stall_s=args.io_read_stall_s,
        io_write_stall_s=args.io_write_stall_s,
    )

    install_graceful_signals(
        server.request_shutdown,
        "[serve] {sig}: draining (second signal hard-kills)",
    )

    server.start()
    rid = f"replica_id={args.replica_id} " if args.replica_id is not None else ""
    print(
        f"[serve] listening on {server.host}:{server.port} {rid}"
        f"obs_dim={bundle.obs_dim} action_dim={bundle.action_dim} "
        f"buckets={list(server.batcher.buckets)} "
        f"policies={sorted(server._policies)} "
        f"source={bundle.meta.get('source', '?')}",
        flush=True,
    )
    server.serve_until_shutdown()
    if tap is not None:
        # Drain the tap AFTER the server: every admitted request's
        # feedback has been acked, so the mirror books are final.
        tap.close()
        mc = tap.counters()
        print(
            "[serve] mirror: "
            + " ".join(f"{k}={mc[k]}" for k in sorted(mc)),
            flush=True,
        )
    snap = server.healthz()
    # aggregate across every resident policy (top-level counters are the
    # DEFAULT policy's — the PR-3 schema)
    served = sum(r["replies_ok"] for r in snap["policies"].values())
    shed = snap["shed_total"] + sum(
        r["shed_total"] for pid, r in snap["policies"].items()
        if pid != "default"
    )
    print(
        f"[serve] drained: {served} served, "
        f"{shed} shed, p99={snap.get('p99_ms')} ms",
        flush=True,
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
