"""Length-prefixed binary wire protocol for the policy server.

One frame per message, fixed little-endian header, raw float32 payloads —
no serialization library on the hot path (a pickle/JSON round-trip per
request would dwarf the actor forward itself at serving batch sizes).

Frame layout::

    magic    2s   b"D4"
    version  u8   PROTOCOL_VERSION
    type     u8   MsgType
    req_id   u32  client-chosen id, echoed verbatim in the reply (enables
                  pipelining: many requests in flight per connection)
    length   u32  payload byte count (<= MAX_PAYLOAD)
    payload  bytes

Message types and payloads:

- ``ACT``          → ``u32 deadline_us`` (0 = none, relative to arrival)
                     followed by ``obs_dim`` float32s.
- ``ACT_OK``       ← ``action_dim`` float32s.
- ``OVERLOADED``   ← utf-8 reason (``queue_full`` | ``deadline`` |
                     ``draining``). The request was SHED, not failed: the
                     client may retry with backoff. This is the explicit
                     load-shedding reply — under overload the server says
                     so immediately instead of letting latency diverge.
- ``ERROR``        ← utf-8 message. Protocol violations (bad magic/size);
                     the server closes the connection after sending.
- ``HEALTHZ``      → empty payload.
- ``HEALTHZ_OK``   ← utf-8 JSON: server stats snapshot (see
                     docs/serving.md for the schema).

The experience-ingest service (``d4pg_tpu/fleet``) speaks the SAME frame
layout on its own port with four more message types (payload codecs in
``d4pg_tpu/fleet/wire.py``; full table in docs/fleet.md):

- ``HELLO``        → utf-8 JSON: actor handshake (dims, n_step, gamma,
                     bundle generation). First frame on every connection.
- ``HELLO_OK``     ← utf-8 JSON: accepted; carries the learner's current
                     generation and the flow-control window.
- ``WINDOWS``      → binary batch of complete n-step windows, tagged with
                     the producing bundle generation.
- ``WINDOWS_OK``   ← per-frame ack: (accepted, dropped_stale) counts. A
                     shed frame is answered ``OVERLOADED`` instead.

``read_frame`` returns ``None`` on clean EOF (peer closed between frames)
and raises :class:`ProtocolError` on anything malformed — oversized
declared length, bad magic, version mismatch, or EOF mid-frame.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

MAGIC = b"D4"
PROTOCOL_VERSION = 1
# Generous for observation vectors (a 348-dim Humanoid obs is ~1.4 KB;
# even a flattened 96×96×4 pixel obs is ~147 KB) while bounding what a
# malicious/buggy client can make the server buffer per frame.
MAX_PAYLOAD = 1 << 20

HEADER = struct.Struct("<2sBBII")
_DEADLINE = struct.Struct("<I")

# message types (one id space across serving AND fleet ingest: the framing
# layer is shared, so a frame routed at the wrong port fails loudly on type)
ACT = 1
ACT_OK = 2
OVERLOADED = 3
ERROR = 4
HEALTHZ = 5
HEALTHZ_OK = 6
HELLO = 7         # fleet actor handshake (d4pg_tpu/fleet/wire.py)
HELLO_OK = 8
WINDOWS = 9       # batch of complete n-step windows
WINDOWS_OK = 10


class ProtocolError(Exception):
    """Malformed frame — the connection is unrecoverable past this point
    (framing is lost), so handlers reply ERROR once and close."""


def abortive_close(sock) -> None:
    """Close with SO_LINGER 0 — an RST on real stacks, so the peer (and
    any frame in flight) sees an immediate reset instead of an orderly
    FIN. The shared teardown for the chaos fault sites (serve
    ``sock_reset``, ingest ``partition``) and ``FleetLink.abort``."""
    import socket as _socket

    try:
        sock.setsockopt(
            _socket.SOL_SOCKET, _socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def probe_healthz(host: str, port: int, timeout_s: float = 2.0) -> dict:
    """One-shot healthz probe: connect, ask, decode, close.

    Deliberately NOT a :class:`~d4pg_tpu.serve.client.PolicyClient`: the
    prober in the replica front-end (``serve/router.py``) runs this on a
    timer against possibly-dead backends — a persistent pipelined client
    would hide exactly the connect-failure signal ejection keys on, and
    a probe must never outlive its timeout (``settimeout`` bounds every
    recv). Raises ``OSError`` (connect/timeout) or :class:`ProtocolError`
    (malformed reply) — the caller maps both to "unhealthy"."""
    import json
    import socket

    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        write_frame(s, HEALTHZ, 0)
        frame = read_frame(s)
        if frame is None:
            raise ProtocolError("EOF before healthz reply")
        msg_type, _req_id, payload = frame
        if msg_type != HEALTHZ_OK:
            raise ProtocolError(f"unexpected healthz reply type {msg_type}")
        return json.loads(payload.decode("utf-8", "replace"))


def recv_exact(stream, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on EOF at a frame boundary (n>0 and
    zero bytes read); ProtocolError on EOF mid-read.

    ``stream`` is either a raw socket or a buffered file over one
    (``sock.makefile("rb")``). Both hot paths use the buffered form — one
    kernel read typically services a whole frame (or several, pipelined)
    instead of a recv syscall per header/payload piece, which measured as
    a large share of per-request cost on the serving hot path."""
    read = getattr(stream, "read", None)
    if read is not None:  # buffered file: read(n) is already exact-or-EOF
        buf = read(n)
        if not buf:
            return None
        if len(buf) < n:
            raise ProtocolError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        return buf
    buf = bytearray()
    while len(buf) < n:
        chunk = stream.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ProtocolError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(stream) -> Optional[Tuple[int, int, bytes]]:
    """One ``(msg_type, req_id, payload)`` frame from a socket or buffered
    file; None on clean EOF."""
    hdr = recv_exact(stream, HEADER.size)
    if hdr is None:
        return None
    magic, version, msg_type, req_id, length = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version != PROTOCOL_VERSION:
        raise ProtocolError(
            f"protocol version {version} (this server speaks {PROTOCOL_VERSION})"
        )
    if length > MAX_PAYLOAD:
        raise ProtocolError(f"payload length {length} > max {MAX_PAYLOAD}")
    payload = b""
    if length:
        payload = recv_exact(stream, length)
        if payload is None:
            raise ProtocolError("EOF before payload")
    return msg_type, req_id, payload


def write_frame(sock, msg_type: int, req_id: int, payload: bytes = b"") -> None:
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(f"payload length {len(payload)} > max {MAX_PAYLOAD}")
    # ONE sendall per frame: header+payload concatenated so a concurrent
    # writer on the same socket (replies come from batcher callbacks, the
    # healthz reply from the reader thread) can never interleave a frame —
    # callers still hold a per-connection send lock for ordering.
    sock.sendall(
        HEADER.pack(MAGIC, PROTOCOL_VERSION, msg_type, req_id, len(payload))
        + payload
    )


# ----------------------------------------------------------- ACT payloads
def encode_act(obs: np.ndarray, deadline_us: int = 0) -> bytes:
    obs = np.ascontiguousarray(obs, dtype=np.float32)
    return _DEADLINE.pack(int(deadline_us)) + obs.tobytes()


def decode_act(payload: bytes, obs_dim: int) -> Tuple[np.ndarray, int]:
    """Returns ``(obs [obs_dim] f32, deadline_us)``; ProtocolError on any
    size mismatch (the oversized/undersized-request fault path)."""
    want = _DEADLINE.size + 4 * obs_dim
    if len(payload) != want:
        raise ProtocolError(
            f"ACT payload is {len(payload)} bytes, expected {want} "
            f"(obs_dim={obs_dim})"
        )
    (deadline_us,) = _DEADLINE.unpack_from(payload)
    obs = np.frombuffer(payload, np.float32, offset=_DEADLINE.size).copy()
    return obs, deadline_us


def encode_action(action: np.ndarray) -> bytes:
    return np.ascontiguousarray(action, dtype=np.float32).tobytes()


def decode_action(payload: bytes) -> np.ndarray:
    if len(payload) % 4:
        raise ProtocolError(f"ACT_OK payload length {len(payload)} not float32")
    return np.frombuffer(payload, np.float32).copy()
