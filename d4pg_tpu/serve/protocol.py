"""Length-prefixed binary wire protocol for the policy server.

One frame per message, fixed little-endian header, raw float32 payloads —
no serialization library on the hot path (a pickle/JSON round-trip per
request would dwarf the actor forward itself at serving batch sizes).

Frame layout::

    magic    2s   b"D4"
    version  u8   PROTOCOL_VERSION
    type     u8   MsgType
    req_id   u32  client-chosen id, echoed verbatim in the reply (enables
                  pipelining: many requests in flight per connection)
    length   u32  payload byte count (<= MAX_PAYLOAD)
    payload  bytes

Frame versioning (the multi-tenant compat contract): every frame carries
the LOWEST version that can express its type — the v1 sublanguage is
byte-for-byte what PR-8-era peers speak, so an old client against this
code sees identical reply bytes, and this code's probes/plain-ACT traffic
work against old servers unchanged. Only ``ACT2`` (policy-id routing)
needs version 2; an old server's ``read_frame`` rejects the version byte
with a clear ``protocol version 2 (this server speaks 1)`` ERROR reply —
a new client fails loudly, never with a decode crash. ``read_frame`` here
accepts every version in ``SUPPORTED_VERSIONS``.

Message types and payloads:

- ``ACT``          → ``u32 deadline_us`` (0 = none, relative to arrival)
                     followed by ``obs_dim`` float32s. v1: no policy id —
                     a server holding N policies serves it the DEFAULT
                     policy (old clients negotiate down implicitly).
- ``ACT2``         → ``u8 qos  u8 policy_len  u8 tenant_len  u8 reserved
                     u32 deadline_us`` + policy_id utf-8 + tenant utf-8 +
                     obs float32s. The multi-tenant request frame:
                     ``policy_id`` routes to a resident bundle, ``qos``
                     (0 = interactive, 1 = bulk) and ``tenant`` feed the
                     router's class-aware shed + per-tenant quotas.
                     Unknown policy → per-request ``ERROR`` reply (the
                     frame is well-formed; the connection survives).
- ``ACT_OK``       ← ``action_dim`` float32s.
- ``OVERLOADED``   ← utf-8 reason (``queue_full`` | ``deadline`` |
                     ``draining``). The request was SHED, not failed: the
                     client may retry with backoff. This is the explicit
                     load-shedding reply — under overload the server says
                     so immediately instead of letting latency diverge.
- ``ERROR``        ← utf-8 message. Protocol violations (bad magic/size);
                     the server closes the connection after sending.
- ``HEALTHZ``      → empty payload.
- ``HEALTHZ_OK``   ← utf-8 JSON: server stats snapshot (see
                     docs/serving.md for the schema).

The experience-ingest service (``d4pg_tpu/fleet``) speaks the SAME frame
layout on its own port with four more message types (payload codecs in
``d4pg_tpu/fleet/wire.py``; full table in docs/fleet.md):

- ``HELLO``        → utf-8 JSON: actor handshake (dims, n_step, gamma,
                     bundle generation, and — since ISSUE 13 — an
                     optional capability vector the ingest server
                     negotiates: supported obs wire modes, actor-side
                     HER, generation-tagged obs-norm stats). First frame
                     on every connection.
- ``HELLO_OK``     ← utf-8 JSON: accepted; carries the learner's current
                     generation and the flow-control window (plus the
                     negotiated capability set when the actor sent one —
                     a caps-less HELLO gets the byte-identical v1 reply).
- ``WINDOWS``      → binary batch of complete n-step windows, tagged with
                     the producing bundle generation. Always float32 flat
                     rows — the pre-ISSUE-13 wire, kept byte-identical.
- ``WINDOWS2``     → the capability-era window frame (rides frame
                     version 2): adds a stats generation, an obs wire
                     mode (f32 / u8-quantized pixel rows / bf16), and a
                     relabeled-window flag. Codec in fleet/wire.py.
- ``WINDOWS_OK``   ← per-frame ack: (accepted, dropped_stale) counts
                     (stale covers both bundle-generation and obs-norm
                     stats-generation drops). A shed frame is answered
                     ``OVERLOADED`` instead.

The flywheel (ISSUE 18) adds one client→server pair, rides frame
version 2 — plain v1/v2 ACT traffic never carries it, so the v1
sublanguage stays byte-identical both directions:

- ``FEEDBACK``     → ``u8 policy_len  u8 action_dim  u8 flags (bit 0
                     terminated, bit 1 truncated)  u8 reserved
                     f32 reward  f32 log_prob`` + policy_id utf-8 +
                     executed action float32s + next_obs float32s. A
                     sim-attached client's reward echo for its PREVIOUS
                     request on this connection: the env outcome of the
                     action it executed (served action + client-side
                     exploration noise), with the behavior-policy
                     log-prob of that executed action — the logged
                     propensity the off-policy promotion gate weights
                     by. Carrying next_obs explicitly lets the mirror
                     tap close episode ends without a following ACT.
- ``FEEDBACK_OK``  ← empty. Ack (the client may pipeline feedback like
                     requests). A server without the tap enabled still
                     acks — feedback is then simply not mirrored.

``read_frame`` returns ``None`` on clean EOF (peer closed between frames)
and raises :class:`ProtocolError` on anything malformed — oversized
declared length, bad magic, version mismatch, or EOF mid-frame.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

MAGIC = b"D4"
# Highest version this code speaks; frames go out at the lowest version
# that can carry their type (``_frame_version``) so the v1 sublanguage
# stays byte-identical to PR-8-era peers in both directions.
PROTOCOL_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)
# Generous for observation vectors (a 348-dim Humanoid obs is ~1.4 KB;
# even a flattened 96×96×4 pixel obs is ~147 KB) while bounding what a
# malicious/buggy client can make the server buffer per frame.
MAX_PAYLOAD = 1 << 20

HEADER = struct.Struct("<2sBBII")
_DEADLINE = struct.Struct("<I")
_ACT2_HEAD = struct.Struct("<BBBBI")  # qos, policy_len, tenant_len, rsvd, deadline
# policy_len, action_dim, flags, rsvd, reward, log_prob
_FEEDBACK_HEAD = struct.Struct("<BBBBff")

# message types (one id space across serving AND fleet ingest: the framing
# layer is shared, so a frame routed at the wrong port fails loudly on type)
ACT = 1
ACT_OK = 2
OVERLOADED = 3
ERROR = 4
HEALTHZ = 5
HEALTHZ_OK = 6
HELLO = 7         # fleet actor handshake (d4pg_tpu/fleet/wire.py)
HELLO_OK = 8
WINDOWS = 9       # batch of complete n-step windows
WINDOWS_OK = 10
ACT2 = 11         # versioned multi-tenant request: policy_id + QoS + tenant
WINDOWS2 = 12     # capability-era window frame: obs mode + stats generation
FEEDBACK = 13     # flywheel reward echo: env outcome of the served action
FEEDBACK_OK = 14

# QoS classes carried in the ACT2 frame. Interactive is the protected
# tier (the router sheds bulk FIRST under overload — docs/serving.md);
# bulk is the best-effort batch tier.
QOS_INTERACTIVE = 0
QOS_BULK = 1
QOS_NAMES = {QOS_INTERACTIVE: "interactive", QOS_BULK: "bulk"}

# Per-type frame-version floor: a type absent here rides version 1 (the
# PR-8 wire language). ``write_frame`` applies it, so call sites never
# choose a version — interop with old peers is automatic for old types,
# and new types fail loudly on old peers with a version error.
_FRAME_MIN_VERSION = {ACT2: 2, WINDOWS2: 2, FEEDBACK: 2, FEEDBACK_OK: 2}


class ProtocolError(Exception):
    """Malformed frame — the connection is unrecoverable past this point
    (framing is lost), so handlers reply ERROR once and close."""


def abortive_close(sock) -> None:
    """Close with SO_LINGER 0 — an RST on real stacks, so the peer (and
    any frame in flight) sees an immediate reset instead of an orderly
    FIN. The shared teardown for the chaos fault sites (serve
    ``sock_reset``, ingest ``partition``) and ``FleetLink.abort``."""
    import socket as _socket

    try:
        sock.setsockopt(
            _socket.SOL_SOCKET, _socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def probe_healthz(host: str, port: int, timeout_s: float = 2.0) -> dict:
    """One-shot healthz probe: connect, ask, decode, close.

    Deliberately NOT a :class:`~d4pg_tpu.serve.client.PolicyClient`: the
    prober in the replica front-end (``serve/router.py``) runs this on a
    timer against possibly-dead backends — a persistent pipelined client
    would hide exactly the connect-failure signal ejection keys on, and
    a probe must never outlive its timeout (``settimeout`` bounds every
    recv). Raises ``OSError`` (connect/timeout) or :class:`ProtocolError`
    (malformed reply) — the caller maps both to "unhealthy"."""
    import json
    import socket

    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        write_frame(s, HEALTHZ, 0)
        frame = read_frame(s)
        if frame is None:
            raise ProtocolError("EOF before healthz reply")
        msg_type, _req_id, payload = frame
        if msg_type != HEALTHZ_OK:
            raise ProtocolError(f"unexpected healthz reply type {msg_type}")
        return json.loads(payload.decode("utf-8", "replace"))


def recv_exact(stream, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on EOF at a frame boundary (n>0 and
    zero bytes read); ProtocolError on EOF mid-read.

    ``stream`` is either a raw socket or a buffered file over one
    (``sock.makefile("rb")``). Both hot paths use the buffered form — one
    kernel read typically services a whole frame (or several, pipelined)
    instead of a recv syscall per header/payload piece, which measured as
    a large share of per-request cost on the serving hot path."""
    read = getattr(stream, "read", None)
    if read is not None:  # buffered file: read(n) is already exact-or-EOF
        buf = read(n)
        if not buf:
            return None
        if len(buf) < n:
            raise ProtocolError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        return buf
    buf = bytearray()
    while len(buf) < n:
        chunk = stream.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ProtocolError(f"EOF mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(stream) -> Optional[Tuple[int, int, bytes]]:
    """One ``(msg_type, req_id, payload)`` frame from a socket or buffered
    file; None on clean EOF. Accepts every version in
    ``SUPPORTED_VERSIONS`` — the version byte gates frame-level features
    (``ACT2`` rides v2), not the connection."""
    hdr = recv_exact(stream, HEADER.size)
    if hdr is None:
        return None
    magic, version, msg_type, req_id, length = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version not in SUPPORTED_VERSIONS:
        # Wording matters: this exact string is an old peer's loud answer
        # to a too-new frame (the compat regression pins it) — keep the
        # "protocol version" prefix so clients can tell a version skew
        # from a framing bug.
        raise ProtocolError(
            f"protocol version {version} (this server speaks "
            f"{PROTOCOL_VERSION})"
        )
    if length > MAX_PAYLOAD:
        raise ProtocolError(f"payload length {length} > max {MAX_PAYLOAD}")
    payload = b""
    if length:
        payload = recv_exact(stream, length)
        if payload is None:
            raise ProtocolError("EOF before payload")
    return msg_type, req_id, payload


class FrameAssembler:
    """Incremental decoder for the non-blocking I/O core (``d4pg_tpu/
    netio``): ``feed()`` whatever bytes arrived, then drain complete
    frames with ``next_frame()``. Header validation (magic, version,
    MAX_PAYLOAD) happens the moment 16 header bytes exist — a declared-
    oversize frame is rejected before one payload byte is buffered, same
    as ``read_frame``.

    Parity contract (pinned by tests/test_netio.py): for any byte
    sequence, the frames and the ``ProtocolError`` messages produced here
    are EXACTLY those of ``read_frame`` over a blocking socket — including
    the EOF cases, which the owner reports by calling :meth:`check_eof`
    when the peer closes. Framing lives here, in the protocol module,
    so the wire-format single-point-of-truth rule (PROTOCOL_WIRE_MODULES)
    holds: netio moves bytes, it never parses headers."""

    __slots__ = ("_buf", "_head")

    def __init__(self) -> None:
        self._buf = bytearray()
        self._head: Optional[Tuple[int, int, int]] = None

    def feed(self, data: bytes) -> None:
        self._buf += data

    @property
    def mid_frame(self) -> bool:
        """True while a partial frame is pending — the loop's read-progress
        deadline (the slowloris eviction) arms on exactly this state."""
        return self._head is not None or bool(self._buf)

    def next_frame(self) -> Optional[Tuple[int, int, bytes]]:
        """One ``(msg_type, req_id, payload)`` if a complete frame is
        buffered, else None. Raises :class:`ProtocolError` with
        ``read_frame``'s exact wording on a malformed header."""
        if self._head is None:
            if len(self._buf) < HEADER.size:
                return None
            magic, version, msg_type, req_id, length = HEADER.unpack_from(
                self._buf
            )
            if magic != MAGIC:
                raise ProtocolError(f"bad magic {magic!r}")
            if version not in SUPPORTED_VERSIONS:
                raise ProtocolError(
                    f"protocol version {version} (this server speaks "
                    f"{PROTOCOL_VERSION})"
                )
            if length > MAX_PAYLOAD:
                raise ProtocolError(
                    f"payload length {length} > max {MAX_PAYLOAD}"
                )
            del self._buf[:HEADER.size]
            self._head = (msg_type, req_id, length)
        msg_type, req_id, length = self._head
        if len(self._buf) < length:
            return None
        payload = bytes(self._buf[:length])
        del self._buf[:length]
        self._head = None
        return msg_type, req_id, payload

    def check_eof(self) -> None:
        """Peer closed: raise exactly what ``read_frame`` would have — a
        clean frame boundary returns silently, a torn frame raises with
        the blocking path's wording (``recv_exact``'s k/n counts)."""
        if self._head is not None:
            _msg_type, _req_id, length = self._head
            if not self._buf:
                raise ProtocolError("EOF before payload")
            raise ProtocolError(
                f"EOF mid-frame ({len(self._buf)}/{length} bytes)"
            )
        if self._buf:
            raise ProtocolError(
                f"EOF mid-frame ({len(self._buf)}/{HEADER.size} bytes)"
            )


def encode_frame(msg_type: int, req_id: int, payload: bytes = b"") -> bytes:
    """THE frame bytes: header + payload as one object. ``write_frame``
    sends exactly this and the event-loop write path (``d4pg_tpu/netio``)
    enqueues exactly this, so thread and loop servers are byte-identical
    on the wire by construction, not by parallel maintenance.

    The version byte is the TYPE's floor (v1 unless the type needs v2):
    replies to an old client are byte-identical to PR-8's, and only a
    frame that actually uses v2 features can trip an old peer's version
    check."""
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(f"payload length {len(payload)} > max {MAX_PAYLOAD}")
    return (
        HEADER.pack(
            MAGIC,
            _FRAME_MIN_VERSION.get(msg_type, 1),
            msg_type,
            req_id,
            len(payload),
        )
        + payload
    )


def write_frame(sock, msg_type: int, req_id: int, payload: bytes = b"") -> None:
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(f"payload length {len(payload)} > max {MAX_PAYLOAD}")
    # ONE sendall per frame: header+payload concatenated so a concurrent
    # writer on the same socket (replies come from batcher callbacks, the
    # healthz reply from the reader thread) can never interleave a frame —
    # callers still hold a per-connection send lock for ordering.
    sock.sendall(encode_frame(msg_type, req_id, payload))


def write_truncated_frame(
    sock, msg_type: int, req_id: int, payload: bytes, keep: int
) -> None:
    """CHAOS-ONLY: emit a frame whose header declares the full payload
    but whose body stops after ``keep`` bytes (the ``pixel_truncate``
    fault — a peer dying mid-``sendall``). Lives here because the header
    layout is this module's single point of truth; the receiver's
    ``read_frame`` must die with ``ProtocolError`` (EOF mid-frame) and
    the torn frame must never half-land."""
    keep = max(0, min(int(keep), len(payload)))
    sock.sendall(
        HEADER.pack(
            MAGIC,
            _FRAME_MIN_VERSION.get(msg_type, 1),
            msg_type,
            req_id,
            len(payload),
        )
        + payload[:keep]
    )


# ----------------------------------------------------------- ACT payloads
def encode_act(obs: np.ndarray, deadline_us: int = 0) -> bytes:
    obs = np.ascontiguousarray(obs, dtype=np.float32)
    return _DEADLINE.pack(int(deadline_us)) + obs.tobytes()


def decode_act(payload: bytes, obs_dim: int) -> Tuple[np.ndarray, int]:
    """Returns ``(obs [obs_dim] f32, deadline_us)``; ProtocolError on any
    size mismatch (the oversized/undersized-request fault path)."""
    want = _DEADLINE.size + 4 * obs_dim
    if len(payload) != want:
        raise ProtocolError(
            f"ACT payload is {len(payload)} bytes, expected {want} "
            f"(obs_dim={obs_dim})"
        )
    (deadline_us,) = _DEADLINE.unpack_from(payload)
    obs = np.frombuffer(payload, np.float32, offset=_DEADLINE.size).copy()
    return obs, deadline_us


DEFAULT_POLICY = "default"


def encode_act2(
    obs: np.ndarray,
    deadline_us: int = 0,
    *,
    policy_id: str = DEFAULT_POLICY,
    qos: int = QOS_INTERACTIVE,
    tenant: str = "",
) -> bytes:
    """The v2 multi-tenant request payload (see module docstring layout).
    ``policy_id``/``tenant`` are utf-8, each bounded to 255 bytes by the
    u8 length fields — plenty for ids, and the bound keeps the decode
    allocation-free beyond the obs copy."""
    pid = policy_id.encode("utf-8")
    ten = tenant.encode("utf-8")
    if len(pid) > 255:
        raise ProtocolError(f"policy_id longer than 255 bytes: {policy_id!r}")
    if len(ten) > 255:
        raise ProtocolError(f"tenant longer than 255 bytes: {tenant!r}")
    if qos not in QOS_NAMES:
        raise ProtocolError(f"unknown qos class {qos!r}")
    obs = np.ascontiguousarray(obs, dtype=np.float32)
    return (
        _ACT2_HEAD.pack(qos, len(pid), len(ten), 0, int(deadline_us))
        + pid
        + ten
        + obs.tobytes()
    )


def decode_act2(payload: bytes) -> Tuple[np.ndarray, int, str, int, str]:
    """Returns ``(obs f32, deadline_us, policy_id, qos, tenant)``. The obs
    length is self-described (total minus headers) — the SERVER validates
    it against the routed policy's obs_dim and answers a per-request
    ``ERROR`` on mismatch, because unlike v1 ``ACT`` the framing here is
    intact either way."""
    if len(payload) < _ACT2_HEAD.size:
        raise ProtocolError(
            f"ACT2 payload is {len(payload)} bytes, header needs "
            f"{_ACT2_HEAD.size}"
        )
    qos, plen, tlen, _rsvd, deadline_us = _ACT2_HEAD.unpack_from(payload)
    if qos not in QOS_NAMES:
        raise ProtocolError(f"unknown qos class {qos}")
    off = _ACT2_HEAD.size
    if len(payload) < off + plen + tlen:
        raise ProtocolError(
            f"ACT2 payload is {len(payload)} bytes, ids declare "
            f"{off + plen + tlen}"
        )
    policy_id = payload[off:off + plen].decode("utf-8", "replace")
    tenant = payload[off + plen:off + plen + tlen].decode("utf-8", "replace")
    obs_off = off + plen + tlen
    if (len(payload) - obs_off) % 4:
        raise ProtocolError(
            f"ACT2 obs bytes ({len(payload) - obs_off}) not float32"
        )
    obs = np.frombuffer(payload, np.float32, offset=obs_off).copy()
    return obs, deadline_us, policy_id or DEFAULT_POLICY, qos, tenant


# Flags carried in the FEEDBACK frame (episode-boundary bits; both unset
# for a mid-episode step).
FEEDBACK_TERMINATED = 1
FEEDBACK_TRUNCATED = 2


def encode_feedback(
    reward: float,
    action: np.ndarray,
    next_obs: np.ndarray,
    *,
    log_prob: float = 0.0,
    terminated: bool = False,
    truncated: bool = False,
    policy_id: str = DEFAULT_POLICY,
) -> bytes:
    """The flywheel reward echo (see module docstring layout). ``action``
    is the EXECUTED action (served action + any client-side exploration
    noise) and ``log_prob`` its density under the client's behavior
    policy — the logged propensity the IS promotion gate divides by."""
    pid = policy_id.encode("utf-8")
    if len(pid) > 255:
        raise ProtocolError(f"policy_id longer than 255 bytes: {policy_id!r}")
    action = np.ascontiguousarray(action, dtype=np.float32)
    if action.ndim != 1 or action.shape[0] > 255:
        raise ProtocolError(
            f"FEEDBACK action must be 1-D with dim <= 255, got "
            f"shape {action.shape}"
        )
    flags = (FEEDBACK_TERMINATED if terminated else 0) | (
        FEEDBACK_TRUNCATED if truncated else 0
    )
    next_obs = np.ascontiguousarray(next_obs, dtype=np.float32)
    return (
        _FEEDBACK_HEAD.pack(
            len(pid), action.shape[0], flags, 0,
            float(reward), float(log_prob),
        )
        + pid
        + action.tobytes()
        + next_obs.tobytes()
    )


def decode_feedback(payload: bytes) -> dict:
    """→ ``{policy_id, reward, log_prob, terminated, truncated, action,
    next_obs}``. The next_obs length is self-described (remainder); the
    SERVER validates both dims against the routed policy and answers a
    per-request ``ERROR`` on mismatch (framing intact, connection
    survives) — the same contract as ``ACT2``."""
    if len(payload) < _FEEDBACK_HEAD.size:
        raise ProtocolError(
            f"FEEDBACK payload is {len(payload)} bytes, header needs "
            f"{_FEEDBACK_HEAD.size}"
        )
    plen, adim, flags, _rsvd, reward, log_prob = _FEEDBACK_HEAD.unpack_from(
        payload
    )
    off = _FEEDBACK_HEAD.size
    if len(payload) < off + plen + 4 * adim:
        raise ProtocolError(
            f"FEEDBACK payload is {len(payload)} bytes, ids+action declare "
            f"{off + plen + 4 * adim}"
        )
    policy_id = payload[off:off + plen].decode("utf-8", "replace")
    off += plen
    action = np.frombuffer(payload, np.float32, adim, offset=off).copy()
    off += 4 * adim
    if (len(payload) - off) % 4:
        raise ProtocolError(
            f"FEEDBACK next_obs bytes ({len(payload) - off}) not float32"
        )
    next_obs = np.frombuffer(payload, np.float32, offset=off).copy()
    return {
        "policy_id": policy_id or DEFAULT_POLICY,
        "reward": float(reward),
        "log_prob": float(log_prob),
        "terminated": bool(flags & FEEDBACK_TERMINATED),
        "truncated": bool(flags & FEEDBACK_TRUNCATED),
        "action": action,
        "next_obs": next_obs,
    }


def encode_action(action: np.ndarray) -> bytes:
    return np.ascontiguousarray(action, dtype=np.float32).tobytes()


def decode_action(payload: bytes) -> np.ndarray:
    if len(payload) % 4:
        raise ProtocolError(f"ACT_OK payload length {len(payload)} not float32")
    return np.frombuffer(payload, np.float32).copy()
