"""Socket front-end: event-loop I/O, hot reload, SLOs.

Pure stdlib — serving must not drag in an RPC framework the container
doesn't have. All connection I/O (accept, reads, frame reassembly,
buffered writes, progress deadlines) lives on ONE ``d4pg_tpu.netio``
event-loop thread, so thread count is O(1) in connections: the loop,
the batcher's single device thread per policy, a reload watcher, and a
metrics ticker. Replies are queued by whichever thread completes the
future (the device thread via ``add_done_callback``) through the
thread-safe ``Connection.send`` and flushed by the loop; the ``req_id``
echo makes pipelining safe, so a connection can have many requests in
flight and replies may arrive out of order.

Checkpoint hot-reload: a watcher polls two sources —

- the serving bundle's ``bundle.json`` mtime (the re-export flow:
  ``train.py --export-bundle`` into the live directory; the exporter's
  params-then-json write ordering makes the mtime an attestation), and
- a training run directory (``--watch-run``): the trainer's
  ``best_eval.json`` mtime, whose write-ordering contract says
  ``checkpoints/best_actor.npz`` is already on disk when it moves.

Either way the swap is :meth:`DynamicBatcher.set_params` — params are a
traced argument of the compiled-per-bucket inference function, so a reload
costs zero recompiles and in-flight batches finish on the params they
started with.

Graceful drain (SIGTERM path, wired in ``__main__``): stop accepting,
shed new submissions with ``draining``, answer everything queued, then
close. A preempted replica finishes its admitted work instead of dropping
it on the floor.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Optional

import numpy as np

from d4pg_tpu import netio
from d4pg_tpu.netio import attack as netio_attack
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.batcher import DynamicBatcher, ShedError
from d4pg_tpu.serve.bundle import PolicyBundle, bundle_mtime, load_bundle
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.analysis import flowledger, lockwitness


def load_best_actor_params(run_dir: str, config):
    """``checkpoints/best_actor.npz`` from a training run, unflattened into
    the bundle config's actor tree (the trainer saves leaves in
    tree_flatten order under zero-padded keys)."""
    import jax

    from d4pg_tpu.serve.bundle import actor_template

    path = os.path.join(run_dir, "checkpoints", "best_actor.npz")
    template = actor_template(config)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(path) as z:
        leaves = [z[k] for k in sorted(z.files)]
    if len(leaves) != len(t_leaves):
        raise ValueError(
            f"{path} has {len(leaves)} leaves, bundle config implies "
            f"{len(t_leaves)}"
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


class _PolicyRuntime:
    """One resident policy: its bundle, its own batcher (own device
    thread, own per-bucket compile budget under the shared sentinel), and
    its reload bookkeeping. The multi-tenant tier is N of these behind
    one socket front-end — the v1 ``ACT`` path serves the DEFAULT one.

    No threads of its own; the server's reload watcher is the only writer
    of the mutable fields below after construction (d4pglint
    shared-mutable-state: readers take atomic reference snapshots and
    tolerate being one reload stale — the same contract the single-policy
    server carried on PolicyServer itself)."""

    _THREAD_SAFE = (
        "bundle", "_bundle_mtime", "_serving_bundle_mtime", "_last_reload",
    )

    def __init__(self, policy_id: str, bundle: PolicyBundle, batcher,
                 watch_bundle: bool):
        self.policy_id = policy_id
        self.bundle = bundle
        self.batcher = batcher
        self._watch_bundle = watch_bundle and bundle.path is not None
        self._bundle_mtime = (
            bundle_mtime(bundle.path) if self._watch_bundle else None
        )
        # The json mtime of the bundle this policy is actually SERVING —
        # the per-policy rollout version vector the router's prober keys
        # on. Distinct from ``_bundle_mtime`` (the watch bookmark), which
        # advances even when a reload FAILS: a canary offered a corrupt
        # bundle must keep attesting the OLD version, or the router would
        # promote a rollout nobody loaded.
        self._serving_bundle_mtime = (
            bundle_mtime(bundle.path) if bundle.path is not None else None
        )
        self._last_reload: Optional[str] = None

    def healthz_row(self) -> dict:
        """The per-policy healthz surface (docs/serving.md schema): the
        rollout version vector, reload outcome, and this policy's own
        stats — the router's per-policy canary machinery attests and
        observes on exactly these fields."""
        snap = self.batcher.stats.snapshot()
        last_reload = self._last_reload
        return {
            "bundle_mtime": self._serving_bundle_mtime,
            "last_reload": last_reload,
            "status": (
                "degraded"
                if last_reload is not None and last_reload.startswith("failed")
                else "ok"
            ),
            "compile_count": self.batcher.compile_count,
            "buckets": list(self.batcher.buckets),
            "queue_depth": self.batcher.queue_depth,
            "obs_dim": self.bundle.obs_dim,
            "action_dim": self.bundle.action_dim,
            "inflight": snap["inflight"],
            "requests_total": snap["requests_total"],
            "replies_ok": snap["replies_ok"],
            "shed_total": snap["shed_total"],
            "params_reloads": snap["params_reloads"],
            "p99_ms": snap["p99_ms"],
        }


class PolicyServer:
    # d4pglint shared-mutable-state: the reload watcher thread is the ONLY
    # writer of both after start() (check_reload is watcher-only); readers
    # (healthz, conn threads) take atomic reference snapshots and tolerate
    # being one reload stale. Per-policy reload state lives on
    # _PolicyRuntime (same contract, declared there).
    _THREAD_SAFE = (
        "bundle", "_best_mtime",
    )

    def __init__(
        self,
        bundle: PolicyBundle,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_batch: int = 64,
        max_wait_us: int = 2000,
        queue_limit: int = 256,
        default_deadline_ms: float = 0.0,
        watch_run: Optional[str] = None,
        watch_bundle: bool = True,
        poll_interval_s: float = 2.0,
        log_dir: Optional[str] = None,
        metrics_interval_s: float = 30.0,
        debug_guards: bool = False,
        chaos=None,
        replica_id: Optional[int] = None,
        policies: Optional[dict] = None,
        mirror_tap=None,
        io_read_stall_s: float = netio.loop.DEFAULT_READ_STALL_S,
        io_write_stall_s: float = netio.loop.DEFAULT_WRITE_STALL_S,
        io_write_buffer_limit: int = netio.loop.DEFAULT_WRITE_BUFFER_LIMIT,
    ):
        self.bundle = bundle
        # Fleet attribution (--replica-id): stamped into healthz and every
        # metrics.jsonl row so a multi-replica soak's logs are attributable
        # per process without cross-referencing ports against pids.
        self.replica_id = replica_id
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.default_deadline_s = (
            default_deadline_ms / 1e3 if default_deadline_ms else None
        )
        # --debug-guards: staging ledger on the batcher's slot rotation,
        # recompile sentinel on the per-bucket jit cache (budget = bucket
        # count, asserted at drain), transfer guard around dispatch.
        self.ledger = None
        self.sentinel = None
        if debug_guards:
            from d4pg_tpu.analysis import RecompileSentinel, StagingLedger

            self.ledger = StagingLedger("serve")
            self.sentinel = RecompileSentinel().start()
        # N resident policies behind one front-end: ``bundle`` is the
        # DEFAULT (the one a v1 ACT frame — an old client — lands on);
        # ``policies`` maps extra policy ids to their bundles. Each policy
        # gets its OWN DynamicBatcher (own device thread, own compile
        # budget, own ledger staging groups via the batcher ``name``) and
        # its own hot-reload watch — a reload/rollout on policy A never
        # touches policy B's compiled programs or params.
        def _mk_batcher(pid: str, b: PolicyBundle):
            return DynamicBatcher(
                b.config,
                b.actor_params,
                max_batch=max_batch,
                max_wait_us=max_wait_us,
                queue_limit=queue_limit,
                action_low=b.action_low,
                action_high=b.action_high,
                obs_norm_stats=b.obs_norm,
                ledger=self.ledger,
                sentinel=self.sentinel,
                guard_transfers=debug_guards,
                name="serve" if pid == protocol.DEFAULT_POLICY
                else f"serve[{pid}]",
            )

        extra = dict(policies) if policies else {}
        if protocol.DEFAULT_POLICY in extra:
            raise ValueError(
                f"policy id {protocol.DEFAULT_POLICY!r} is reserved for the "
                "--bundle default policy (the v1 backward-compat target)"
            )
        self._policies: dict = {}
        for pid, b in [(protocol.DEFAULT_POLICY, bundle)] + sorted(
            extra.items()
        ):
            self._policies[pid] = _PolicyRuntime(
                pid, b, _mk_batcher(pid, b), watch_bundle
            )
        self._default = self._policies[protocol.DEFAULT_POLICY]
        self.batcher = self._default.batcher
        self.stats = self.batcher.stats
        # Chaos harness (ChaosInjector or None): the sock_reset site ticks
        # once per received frame and force-resets the connection — proves
        # the reader/reply paths survive abrupt client death end-to-end.
        self._chaos = chaos
        # Flywheel mirror tap (ISSUE 18, or None): mirrors DEFAULT-policy
        # obs→action traffic whose reward the client echoes back with
        # FEEDBACK frames. Externally owned (``serve/__main__`` builds and
        # closes it) — the server only feeds it request/feedback pairs and
        # surfaces its counters in healthz.
        self._tap = mirror_tap
        self._watch_run = watch_run
        self._poll_interval_s = poll_interval_s
        self._best_mtime = self._stat_best() if watch_run else None
        self._log_dir = log_dir
        self._metrics_interval_s = metrics_interval_s
        self._metrics = None

        self._listen_sock: Optional[socket.socket] = None
        # ONE event-loop thread owns every connection (reads, frame
        # reassembly, buffered writes, progress deadlines, bounded
        # accept) — thread count is O(1) in connections.
        self._loop = netio.FrameLoop(
            name="serve-io",
            read_stall_s=io_read_stall_s,
            write_stall_s=io_write_stall_s,
            write_buffer_limit=io_write_buffer_limit,
        )
        self._watch_thread: Optional[threading.Thread] = None
        self._metrics_thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        self._started = False

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            raise RuntimeError("server already started")
        self._started = True
        for p in self._policies.values():
            # every bucket of every policy compiled before accept
            p.batcher.start(warmup=True)
        self._listen_sock = socket.create_server(
            (self.host, self._requested_port)
        )
        self.port = self._listen_sock.getsockname()[1]
        self._loop.serve(
            self._listen_sock,
            on_frame=self._serve_conn,
            on_open=self._on_conn_open,
            on_close=self._on_conn_close,
            on_protocol_error=self._on_protocol_error,
        )
        self._loop.start()
        if any(p._watch_bundle for p in self._policies.values()) or self._watch_run:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="serve-reload", daemon=True
            )
            self._watch_thread.start()
        if self._log_dir:
            from d4pg_tpu.runtime.metrics import MetricsLogger

            self._metrics = MetricsLogger(self._log_dir, use_tensorboard=False)
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="serve-metrics", daemon=True
            )
            self._metrics_thread.start()

    def request_shutdown(self) -> None:
        """Signal-handler-safe: just set the event; the draining work
        happens on whoever waits (serve_until_shutdown / drain)."""
        self._shutdown.set()

    def serve_until_shutdown(self) -> None:
        # The main thread's park-until-signal IS the design: nothing but
        # the signal handler (request_shutdown) ends a serving process.
        self._shutdown.wait()  # d4pglint: disable=thread-lifecycle  -- blocking forever is the serve loop
        self.drain()

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful stop: no new connections, shed new requests, answer
        everything already admitted, then tear down."""
        self._shutdown.set()
        # Drain choreography on the loop: (1) stop accepting — the
        # listener closes on the loop thread, no new connections; (2)
        # drain the batchers — everything already admitted is answered
        # (replies flow through the still-running loop) while new
        # submissions shed ``draining``; (3) close the loop — flush every
        # connection's queued replies (bounded by the write-progress
        # deadline) and join the one I/O thread.
        self._loop.stop_accepting()
        for p in self._policies.values():
            p.batcher.stop(drain=True, timeout=timeout)
        self._loop.close(flush_timeout_s=5.0)
        self._listen_sock = None
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=self._poll_interval_s + 5)
        if self._metrics_thread is not None:
            self._metrics_thread.join(timeout=self._metrics_interval_s + 5)
        if self._metrics is not None:
            self._metrics.log(self.stats.batches_total, self._metrics_row())
            self._metrics.close()
            self._metrics = None
        if self.sentinel is not None:
            # Budget: one compiled program per bucket for the whole run —
            # hot reloads and traffic shape must never have retraced. Last
            # on purpose: a budget trip must fail the drain loudly WITHOUT
            # leaking the shutdown path above (metrics flush, client
            # socket closes, thread joins).
            self.sentinel.check("serve drain")
            # Runtime lock-order witness vs the committed static graph
            # (benchmarks/lock_order_graph.json): a nesting this process
            # performed that contradicts the graph fails the drain.
            lockwitness.check_against_committed(where="serve drain")
        # --debug-guards: every admitted request must have resolved as
        # exactly one of ok/shed (inflight 0 after the batcher drained)
        flowledger.check("serve-stats", self.stats.snapshot(),
                         where="serve drain")

    # ------------------------------------------------------------- hot reload
    def _stat_best(self) -> Optional[float]:
        try:
            return os.stat(
                os.path.join(self._watch_run, "best_eval.json")
            ).st_mtime
        except (OSError, TypeError):
            return None

    def _check_policy_reload(self, p: _PolicyRuntime) -> bool:
        """One reload poll for one resident policy. Returns True if its
        params swapped."""
        if not p._watch_bundle:
            return False
        m = bundle_mtime(p.bundle.path)
        if m is None or m == p._bundle_mtime:
            return False
        swapped = False
        try:
            # Reload the WHOLE bundle, not just the params: a
            # re-export from a live --obs-norm run carries fresher
            # normalizer statistics, and serving new params under
            # stale μ/σ silently scales the net's inputs off its
            # trained distribution. Config/bounds changes are
            # REFUSED (they are baked into the compiled bucket
            # programs — honoring them needs a restart).
            fresh = load_bundle(p.bundle.path)
            if fresh.config != p.bundle.config:
                raise ValueError(
                    "agent config changed; restart the server to "
                    "serve it (compiled programs are config-shaped)"
                )
            if not (
                np.array_equal(fresh.action_low, p.bundle.action_low)
                and np.array_equal(
                    fresh.action_high, p.bundle.action_high
                )
            ):
                raise ValueError(
                    "action bounds changed; restart the server to "
                    "serve them (bounds are baked into the "
                    "compiled programs)"
                )
            p.batcher.set_params(fresh.actor_params)
            p.batcher.set_obs_norm(fresh.obs_norm)
            p.bundle = fresh
            if p is self._default:
                self.bundle = fresh  # keep the compat alias current
            swapped = True
            p._serving_bundle_mtime = m
            p._last_reload = "ok: bundle"
            print(
                f"[serve] reloaded bundle {p.bundle.path} "
                f"(policy {p.policy_id})"
            )
        except Exception as e:
            # ANY load/validation failure (a malformed bundle.json
            # raises KeyError/TypeError, not just OSError/
            # ValueError) means: keep serving the old params. The
            # mtime bookmark still advances below, so a bad export
            # logs once instead of retrying every poll forever.
            p._last_reload = f"failed: {e}"
            print(
                f"[serve] bundle reload failed (policy {p.policy_id} "
                f"serving old params): {e}"
            )
        p._bundle_mtime = m
        return swapped

    def check_reload(self) -> bool:
        """One reload poll across every resident policy (also callable
        directly from tests — the watch thread is just this on a timer).
        Returns True if any policy's params swapped."""
        swapped = False
        for p in self._policies.values():
            swapped = self._check_policy_reload(p) or swapped
        if self._watch_run:
            m = self._stat_best()
            if m is not None and m != self._best_mtime:
                try:
                    # best_actor.npz carries PARAMS ONLY — a run using
                    # --obs-norm should hot-reload via bundle re-export
                    # (which refreshes the statistics too); docs/serving.md
                    # states this limitation.
                    params = load_best_actor_params(
                        self._watch_run, self.bundle.config
                    )
                    self.batcher.set_params(params)
                    swapped = True
                    # --watch-run is a default-policy contract (the
                    # training-run fast path); extra policies reload via
                    # their own bundle dirs only
                    self._default._last_reload = "ok: best_actor.npz"
                    print(
                        f"[serve] reloaded best_actor.npz from {self._watch_run}"
                    )
                except Exception as e:  # same contract as the bundle branch
                    self._default._last_reload = f"failed: {e}"
                    print(f"[serve] run-dir reload failed (serving old params): {e}")
                self._best_mtime = m
        return swapped

    def _watch_loop(self) -> None:
        while not self._shutdown.wait(self._poll_interval_s):
            try:
                self.check_reload()
            except Exception as e:  # watcher must never die silently mid-run
                print(f"[serve] reload watcher error: {e}")

    # ---------------------------------------------------------------- metrics
    def _metrics_row(self) -> dict:
        """Stats row with the replica identity stamped in (numeric-only,
        per the MetricsLogger contract) — multi-replica soak logs stay
        attributable per process. Extra resident policies contribute
        their own rows under a ``policy_<id>_`` prefix (the default
        policy keeps the bare PR-3 keys so existing plots don't move)."""
        row = self.stats.metrics_row()
        for pid, p in self._policies.items():
            if p is self._default:
                continue
            for k, v in p.batcher.stats.metrics_row().items():
                row[f"policy_{pid}_{k}"] = v
        if self.replica_id is not None:
            row["replica_id"] = float(self.replica_id)
        return row

    def _metrics_loop(self) -> None:
        while not self._shutdown.wait(self._metrics_interval_s):
            self._metrics.log(
                self.stats.batches_total,
                self._metrics_row(),
                timers=self.batcher.timers,
            )

    # ------------------------------------------------------------ connections
    def _on_conn_open(self, conn: netio.Connection) -> None:
        # Connection-level chaos sites fire at accept: each launches a
        # loop-timer-driven attacker against this server's own listener
        # (slowloris trickle / zero-window staller / fd hoard), proving
        # the eviction machinery on live traffic.
        if self._chaos is not None:
            netio_attack.tick_attacks(
                self._chaos, self._loop, self.host, self.port
            )

    def _on_conn_close(self, conn: netio.Connection) -> None:
        if self._tap is not None:
            # Episode boundary is the CONNECTION: a vanished client's
            # half-built window is dropped whole, never flushed as if
            # the episode ended cleanly.
            self._tap.on_disconnect(id(conn))

    def _on_protocol_error(self, conn: netio.Connection, exc) -> None:
        # Framing is per-connection state: after a malformed frame the
        # stream is unrecoverable, so this is a connection-fatal ERROR
        # (req_id 0) — the loop flush-closes the connection after this
        # returns. Pipelined siblings on OTHER connections are untouched.
        self.stats.inc("protocol_errors")
        conn.send(protocol.ERROR, 0, str(exc).encode())

    def _reply(
        self, conn: netio.Connection, msg_type: int, req_id: int,
        payload: bytes = b"",
    ) -> None:
        if not conn.send(msg_type, req_id, payload):
            # Client gone before its reply (the disconnect-mid-request
            # fault path) or evicted for stalling: the batch already
            # computed its action; count it.
            self.stats.inc("dropped_replies")

    def _serve_conn(
        self, conn: netio.Connection, msg_type: int, req_id: int,
        payload: bytes,
    ) -> None:
        """One complete frame, on the loop thread. Must not block: the
        only slow work — inference — is handed to the policy's batcher
        and replied from its done-callback via the thread-safe
        ``Connection.send``. Raising :class:`ProtocolError` routes to
        ``_on_protocol_error`` (connection-fatal), exactly like a framing
        error from the byte stream itself."""
        reply = self._reply
        if self._chaos is not None:
            e = self._chaos.tick("sock_reset")
            if e is not None:
                # Abortive close (RST on real stacks): the peer — and any
                # reply in flight — sees a reset, exactly the
                # disconnect-mid-request fault class. The server must
                # keep serving every other connection.
                conn.abort()
                return
        if msg_type == protocol.HEALTHZ:
            reply(
                conn,
                protocol.HEALTHZ_OK,
                req_id,
                json.dumps(self.healthz()).encode(),
            )
            return
        if msg_type == protocol.ACT:
            # v1 path: an old client negotiates down to the
            # DEFAULT policy implicitly — reply bytes (version
            # byte included, via the per-type frame floor) are
            # identical to the PR-8 server's.
            pol = self._default
            obs, deadline_us = protocol.decode_act(
                payload, pol.bundle.obs_dim
            )
        elif msg_type == protocol.ACT2:
            obs, deadline_us, policy_id, _qos, _tenant = (
                protocol.decode_act2(payload)
            )
            # QoS/tenant ride the frame for the ROUTER's admission
            # tier; the replica itself routes on policy only.
            pol = self._policies.get(policy_id)
            if pol is None:
                # well-formed frame, wrong policy: a per-request
                # ERROR, not a ProtocolError — the connection
                # (and its pipelined siblings) survives
                self.stats.inc("unknown_policy")
                reply(
                    conn, protocol.ERROR, req_id,
                    f"unknown policy {policy_id!r} (resident: "
                    f"{sorted(self._policies)})".encode(),
                )
                return
            if obs.shape[0] != pol.bundle.obs_dim:
                reply(
                    conn, protocol.ERROR, req_id,
                    f"obs is {obs.shape[0]}-dim, policy "
                    f"{policy_id!r} wants {pol.bundle.obs_dim}".encode(),
                )
                return
        elif msg_type == protocol.FEEDBACK:
            # Reward echo for THIS connection's previous ACT (the
            # flywheel's closed loop). Malformed frames are
            # per-request ERRORs — the connection survives; the
            # frame is ALWAYS acked so clients need not know
            # whether a tap is attached.
            fb = protocol.decode_feedback(payload)
            fpol = self._policies.get(fb["policy_id"])
            if fpol is None:
                self.stats.inc("unknown_policy")
                reply(
                    conn, protocol.ERROR, req_id,
                    f"unknown policy {fb['policy_id']!r} (resident: "
                    f"{sorted(self._policies)})".encode(),
                )
                return
            if (
                fb["action"].shape[0] != fpol.bundle.action_dim
                or fb["next_obs"].shape[0] != fpol.bundle.obs_dim
            ):
                reply(
                    conn, protocol.ERROR, req_id,
                    f"feedback dims ({fb['action'].shape[0]} act, "
                    f"{fb['next_obs'].shape[0]} obs) do not match "
                    f"policy {fb['policy_id']!r} "
                    f"({fpol.bundle.action_dim} act, "
                    f"{fpol.bundle.obs_dim} obs)".encode(),
                )
                return
            self.stats.inc("feedback_frames")
            if self._tap is not None and fpol is self._default:
                self._tap.on_feedback(id(conn), fb)
            reply(conn, protocol.FEEDBACK_OK, req_id)
            return
        else:
            raise ProtocolError(f"unexpected message type {msg_type}")
        if self._tap is not None and pol is self._default:
            # Remember this connection's latest request obs; the
            # client's next FEEDBACK frame completes the pair.
            self._tap.on_request(id(conn), obs)
        deadline_s = (
            deadline_us / 1e6 if deadline_us else self.default_deadline_s
        )
        try:
            fut = pol.batcher.submit(obs, deadline_s)
        except ShedError as e:
            reply(conn, protocol.OVERLOADED, req_id, e.reason.encode())
            return

        def deliver(f, conn=conn, req_id=req_id):
            exc = f.exception()
            if exc is None:
                reply(
                    conn,
                    protocol.ACT_OK,
                    req_id,
                    # inside f's own done-callback: resolved by
                    # definition, result() cannot block
                    protocol.encode_action(f.result()),  # d4pglint: disable=thread-lifecycle  -- done-callback, future resolved
                )
            elif isinstance(exc, ShedError):
                reply(conn, protocol.OVERLOADED, req_id, exc.reason.encode())
            else:
                reply(conn, protocol.ERROR, req_id, str(exc).encode())

        fut.add_done_callback(deliver)

    # ----------------------------------------------------------------- status
    def healthz(self) -> dict:
        snap = self.stats.snapshot()
        # Degraded-state contract: "draining" once shutdown is requested;
        # "degraded" while healthy-but-stale (ANY policy's last hot-reload
        # attempt failed, so its traffic is served on older params); "ok"
        # otherwise. (No quarantined-worker field: serving has no worker
        # pool — the device threads either live or the process is down.)
        rows = {pid: p.healthz_row() for pid, p in self._policies.items()}
        if self._shutdown.is_set():
            status = "draining"
        elif any(r["status"] == "degraded" for r in rows.values()):
            status = "degraded"
        else:
            status = "ok"
        snap["status"] = status
        snap["draining"] = self._shutdown.is_set()
        snap["last_reload"] = rows[protocol.DEFAULT_POLICY]["last_reload"]
        if self._chaos is not None:
            snap["chaos_injections"] = self._chaos.injections_total
        snap["queue_depth"] = self.batcher.queue_depth
        # Aggregates across EVERY resident policy: compile_count is the
        # whole process's compiled-program count (the soak's flat-count
        # assertion must see a stray retrace on ANY policy), inflight is
        # the dispatch-weight gauge the router/autoscaler read.
        snap["compile_count"] = sum(
            p.batcher.compile_count for p in self._policies.values()
        )
        snap["inflight"] = sum(r["inflight"] for r in rows.values())
        snap["params_reloads"] = sum(
            r["params_reloads"] for r in rows.values()
        )
        snap["buckets"] = list(self.batcher.buckets)
        snap["obs_dim"] = self.bundle.obs_dim
        snap["action_dim"] = self.bundle.action_dim
        # Prober surface (docs/serving.md schema): the serving-bundle
        # version vector (advances ONLY on successful reload), process
        # identity for fleet attribution / chaos targeting, and the
        # uptime_s gauge already in the stats snapshot. Top-level
        # bundle_mtime stays the DEFAULT policy's (the PR-8 field old
        # routers key on); per-policy vectors ride the ``policies`` rows.
        snap["bundle_mtime"] = rows[protocol.DEFAULT_POLICY]["bundle_mtime"]
        snap["policies"] = rows
        snap["replica_id"] = self.replica_id
        snap["pid"] = os.getpid()
        if self._tap is not None:
            # Mirror-tap accounting (ISSUE 18): every counter the tap's
            # windows_built == acked + stale + shed + dropped_* identity
            # is recomputed from by the smoke/soak checks.
            snap["mirror"] = self._tap.counters()
        snap["stage_ms"] = {
            k: round(v, 4)
            for k, v in self.batcher.timers.summary_ms().items()
        }
        # Event-loop I/O core counters (docs/serving.md): connection
        # census plus the attack-eviction/shed books — slowloris and
        # zero-window evictions, EMFILE accept sheds.
        snap["netio"] = self._loop.stats()
        return snap
