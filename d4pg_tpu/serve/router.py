"""Replicated serving front-end: least-loaded dispatch, health-driven
ejection, rolling canary rollout with auto-rollback.

One ``serve/`` process saturates one device thread (1,437 rps on the
committed artifact); production traffic needs the tier above it. This
module is that tier — the serving-side mirror of the collection fleet
(``d4pg_tpu/fleet``): a stdlib front-end speaking the SAME length-prefixed
frame protocol on both sides, dispatching each request to the least-loaded
of M backend replicas.

Three jobs:

- **Dispatch** — per-replica inflight accounting (the router's own gauge,
  not a healthz round-trip per request); least-loaded admitted replica
  wins, ties broken by index. A replica that sheds (``OVERLOADED``) or
  dies mid-stream (``ConnectionClosed`` — its pipelined dispatch link
  sweeps every in-flight future) triggers ONE bounded re-dispatch on a
  different replica under a seeded :class:`~d4pg_tpu.utils.retry.Backoff`
  budget; only when every replica is ejected does the router itself
  answer ``OVERLOADED(no_replicas)``. The accounting identity the chaos
  soak pins: every request is answered ok, answered OVERLOADED, or
  failed-after-bounded-retry — never silently lost.

- **Health-driven ejection** — a prober thread polls each replica's
  healthz (``protocol.probe_healthz``, one-shot so a dead backend cannot
  wedge it). ``degraded`` / ``draining`` / timeout / connect-failure
  ejects the replica (its dispatch link is closed, failing its in-flight
  requests over to survivors); re-admission takes K CONSECUTIVE healthy
  probes (``readmit_after``) — one lucky probe must not flap a sick
  replica back in.

- **Rolling canary rollout** — ``--canary-bundle`` names a bundle
  directory the router watches (its ``bundle.json`` mtime is the version
  vector, exactly the attestation the exporter's params-first/json-second
  write ordering provides). A new version deploys onto a deterministic
  subset of replicas (the canaries), then ``--canary-fraction`` of
  requests (a deterministic counter fraction, not RNG) routes to them
  while the router compares canary vs baseline reply-error rate and p99
  over sliding windows. Better-or-equal → auto-promote (roll the
  remaining replicas forward one at a time, each attested via healthz
  ``bundle_mtime`` before the next). Worse — or a canary that fails to
  load / gets ejected — → auto-rollback: restore the saved old bundle
  and RE-EJECT the canaries until their healthz attests the old version
  again. Every decision is a structured ``[router-event]`` JSON line.

The multi-tenant tier (ISSUE 12) adds three admission/routing axes on
top:

- **Policy-id routing** — an ``ACT2`` request names a resident policy;
  dispatch is restricted to replicas hosting it (learned from each
  replica's healthz ``policies`` rows), and the canary machinery runs
  ONE rollout state machine PER policy (``--canary-bundle policy=dir``,
  repeatable): a rollout for policy A never touches policy B's replicas,
  bundle dirs, or traffic split. v1 ``ACT`` requests negotiate down to
  the default policy.

- **QoS classes + per-tenant quotas** — every request carries a class
  (interactive | bulk) and a tenant id; admission runs BEFORE dispatch:
  first the tenant's token bucket (``--tenant-quota``/``--default-quota``,
  shed reason ``quota``), then the class-aware capacity check
  (``--replica-capacity`` × admitted replicas): bulk is admitted only
  up to ``--bulk-fraction`` of fleet capacity (shed reason
  ``bulk_capacity``) so under overload the bulk tier sheds FIRST and
  interactive p99 stays inside its SLO; interactive sheds only at full
  capacity (``capacity``). The accounting identity generalizes: answered
  == ok + overloaded + error, exact in aggregate AND per (tenant, class)
  on the healthz ``tenants`` rows.

- **Elastic capacity** — ``add_backend``/``remove_backend`` let the
  autoscaler (``serve/autoscaler.py``) grow and drain the fleet at
  runtime; a replica removed mid-rollout is handled by the rollout
  state machine (abort → restore every touched bundle dir), never left
  half-deployed.

The router is a HOST-ONLY module (d4pglint manifest): it moves bytes and
stats files, never tensors — the one numpy touch is decoding the obs to
re-encode it for the backend link. Deliberately no JAX import anywhere
near it: M replicas own the devices; the router must restart in
milliseconds.

Run it::

    python -m d4pg_tpu.serve.router --backends 127.0.0.1:7431,127.0.0.1:7432 \\
        --backend-bundles runs/p1/bundle_a,runs/p1/bundle_b \\
        --canary-bundle runs/p1/canary --canary-fraction 0.25

docs/serving.md ("Replication & rollout") has the dispatch rules, the
ejection/re-admission state machine, and the canary decision table.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import signal
import socket
import tempfile
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from d4pg_tpu import netio
from d4pg_tpu.netio import attack as netio_attack
from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.client import ConnectionClosed, Overloaded, PolicyClient
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.serve.stats import LatencyReservoir
from d4pg_tpu.utils.retry import Backoff
from d4pg_tpu.analysis import flowledger, lockwitness

# Bundle file names, duplicated from serve/bundle.py ON PURPOSE: that
# module imports the agent config (and with it JAX) at module top, and the
# router is a host-only process that must never pay — or crash on — a JAX
# import. The names are a stable on-disk contract (docs/serving.md).
_PARAMS_FILE = "actor_params.npz"
_META_FILE = "bundle.json"


def _bundle_json_mtime(bundle_dir: str) -> Optional[float]:
    try:
        return os.stat(os.path.join(bundle_dir, _META_FILE)).st_mtime
    except (OSError, TypeError):
        return None


# Per-(tenant, class) accounting rows are bounded: past this many distinct
# tenants new ones aggregate into "__other__" (the identity stays exact —
# the overflow row is still a row) so a tenant-id flood cannot grow router
# memory without bound.
MAX_TENANT_ROWS = 512


class TokenBucket:
    """Per-tenant admission quota: ``rate`` tokens/s refill up to
    ``burst``. No lock of its own — every touch happens under the ROUTER
    lock on the admission path (one lock hop per request, same discipline
    as the dispatch bookkeeping), and no allocation per take (the quota
    check is in HOT_PATH_FUNCTIONS)."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.t_last = now

    def take(self, now: float) -> bool:
        tokens = self.tokens + (now - self.t_last) * self.rate
        if tokens > self.burst:
            tokens = self.burst
        self.t_last = now
        if tokens < 1.0:
            self.tokens = tokens
            return False
        self.tokens = tokens - 1.0
        return True


class RouterStats:
    """Router-level counters + client-observed latency. One lock, O(1)
    per request; the identity surface is replies_ok + replies_overloaded
    + replies_error == answered requests — in aggregate, and per
    (tenant, QoS class) on the bounded ``tenants`` rows. Latency is also
    kept per class: the isolation claim ("a flooding bulk tenant cannot
    move interactive p99") needs the interactive reservoir separable."""

    def __init__(self):
        self._lock = lockwitness.named_lock("RouterStats._lock")
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.replies_ok = 0
        self.replies_overloaded = 0
        self.replies_error = 0
        self.retries = 0
        self.ejections = 0
        self.admissions = 0
        self.dropped_replies = 0
        self.protocol_errors = 0
        self.canary_rollbacks = 0
        self.canary_promotions = 0
        # flywheel (ISSUE 18): reward echoes handled at the router tap,
        # and the off-policy promotion gate's verdict tallies
        # (evaluations == pass + block + stalls at quiesce)
        self.feedback_frames = 0
        self.gate_evaluations = 0
        self.gate_pass = 0
        self.gate_block = 0
        self.gate_stalls = 0
        # admission sheds (each also counted in replies_overloaded — they
        # ARE overloaded answers; these break the reason down)
        self.shed_quota = 0
        self.shed_bulk_capacity = 0
        self.shed_capacity = 0
        self.latency = LatencyReservoir()
        self.latency_interactive = LatencyReservoir()
        self.latency_bulk = LatencyReservoir()
        # (tenant, qos) -> [requests, ok, overloaded, error]
        self._tenants: dict = {}

    def inc(self, field: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def _tenant_row(self, tenant: str, qos: int) -> list:
        """Caller holds ``self._lock``."""
        key = (tenant, qos)
        row = self._tenants.get(key)
        if row is None:
            if len(self._tenants) >= MAX_TENANT_ROWS:
                key = ("__other__", qos)
                row = self._tenants.get(key)
                if row is None:
                    row = self._tenants[key] = [0, 0, 0, 0]
            else:
                row = self._tenants[key] = [0, 0, 0, 0]
        return row

    def tenant_request(self, tenant: str, qos: int) -> None:
        with self._lock:
            self._tenant_row(tenant, qos)[0] += 1

    def tenant_outcome(self, tenant: str, qos: int, outcome: int) -> None:
        """``outcome``: 1 = ok, 2 = overloaded, 3 = error (row offsets)."""
        with self._lock:
            self._tenant_row(tenant, qos)[outcome] += 1

    def add_latency(self, seconds: float, qos: int) -> None:
        self.latency.add(seconds)
        (self.latency_bulk if qos else self.latency_interactive).add(seconds)

    def tenants_snapshot(self) -> dict:
        """``"tenant/class" -> {requests, ok, overloaded, error, answered}``
        rows; the per-row identity (requests == answered at quiesce) is
        the machine-checked multi-tenant accounting surface."""
        with self._lock:
            items = list(self._tenants.items())
        out = {}
        for (tenant, qos), row in sorted(items):
            out[f"{tenant}/{protocol.QOS_NAMES.get(qos, qos)}"] = {
                "requests": row[0],
                "ok": row[1],
                "overloaded": row[2],
                "error": row[3],
                "answered": row[1] + row[2] + row[3],
            }
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "requests_total": self.requests_total,
                "replies_ok": self.replies_ok,
                "replies_overloaded": self.replies_overloaded,
                "replies_error": self.replies_error,
                "retries": self.retries,
                "ejections": self.ejections,
                "admissions": self.admissions,
                "dropped_replies": self.dropped_replies,
                "protocol_errors": self.protocol_errors,
                "canary_rollbacks": self.canary_rollbacks,
                "canary_promotions": self.canary_promotions,
                "feedback_frames": self.feedback_frames,
                "gate_evaluations": self.gate_evaluations,
                "gate_pass": self.gate_pass,
                "gate_block": self.gate_block,
                "gate_stalls": self.gate_stalls,
                "shed_quota": self.shed_quota,
                "shed_bulk_capacity": self.shed_bulk_capacity,
                "shed_capacity": self.shed_capacity,
            }
        out["answered_total"] = (
            out["replies_ok"] + out["replies_overloaded"] + out["replies_error"]
        )
        out.update(self.latency.percentiles_ms())
        out["interactive"] = self.latency_interactive.percentiles_ms()
        out["bulk"] = self.latency_bulk.percentiles_ms()
        return out


class Replica:
    """Router-side bookkeeping for one backend ``serve/`` process.

    No threads of its own and no locks: every mutable field is guarded by
    the ROUTER's lock — dispatch picks, inflight accounting, and ejection
    flips must be mutually consistent, and a per-replica lock would just
    invite ordering bugs between two.
    """

    def __init__(self, index: int, host: str, port: int,
                 bundle_dirs=None):
        self.index = index
        self.host = host
        self.port = port
        # policy id -> this replica's live bundle dir for that policy
        # ({} = canary rollouts cannot target it). A bare str means the
        # default policy (the PR-8 single-policy calling convention).
        if bundle_dirs is None:
            self.bundle_dirs: dict = {}
        elif isinstance(bundle_dirs, str):
            self.bundle_dirs = {protocol.DEFAULT_POLICY: bundle_dirs}
        else:
            self.bundle_dirs = dict(bundle_dirs)
        self.client: Optional[PolicyClient] = None  # dispatch link
        self.inflight = 0                 # router-side, not healthz
        self.admitted = False
        self.ejected_reason: Optional[str] = "startup"
        self.healthy_streak = 0
        self.health: dict = {}            # last successful probe snapshot
        self.pid: Optional[int] = None
        self.bundle_mtime: Optional[float] = None   # default policy's vector
        # per-policy serving-version vectors from the healthz ``policies``
        # rows (an old single-policy replica reports only the top-level
        # bundle_mtime — mapped to the default policy)
        self.policy_mtimes: dict = {}
        # policies this replica HOSTS (healthz-learned); dispatch for a
        # policy only considers replicas hosting it
        self.policies: tuple = (protocol.DEFAULT_POLICY,)
        self.canary_for: set = set()      # policies it is canary for
        # Scale-down lifecycle: a removed replica stays in the list (index
        # stability — rollout state and events reference indices) but is
        # invisible to dispatch, probing, and capacity.
        self.removed = False
        self.ok = 0                       # lifetime final outcomes served
        self.errors = 0
        # Dispatch-progress watermark: refreshed when inflight leaves 0 at
        # a pick and on EVERY future resolution. While inflight > 0 a
        # stale watermark means nothing is coming back — the stuck-replica
        # signal healthz can't carry (a wedged device thread still answers
        # healthz "ok").
        self.last_progress = time.monotonic()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class _Rollout:
    """Per-policy canary rollout state. The control thread is the only
    writer (the state machine runs there); ``state`` and the traffic
    counters are additionally written/read under the router lock because
    ``_pick`` routes on them. One instance per ``--canary-bundle``
    policy=dir spec — rollouts for different policies advance
    independently and never touch each other's replicas or traffic.

    d4pglint shared-mutable-state: control-thread-only fields (the
    PR-8 single-rollout contract, now per instance); readers take atomic
    snapshots and tolerate one-tick staleness."""

    _THREAD_SAFE = (
        "seen_mtime", "version", "deadline", "rollback_deadline",
        "deploys", "promote_done", "rollback_dir", "backed_up", "state",
        # off-policy gate handshake: gate_started/gate_token are
        # control-thread-only; gate_result is a single None→dict
        # transition by the gate worker, read by the control thread
        # (one-tick staleness tolerated, token-fenced against stragglers)
        "gate_started", "gate_result", "gate_token",
    )

    def __init__(self, policy: str, src_dir: str, window: int):
        self.policy = policy
        self.src_dir = src_dir
        self.state = "idle"  # idle|deploying|observing|promoting|rolling_back
        self.seen_mtime: Optional[float] = None
        self.version: Optional[float] = None
        self.deadline: Optional[float] = None
        self.rollback_deadline: Optional[float] = None
        self.deploys: dict = {}       # replica index -> awaited json mtime
        self.promote_done: set = set()
        self.rollback_dir: Optional[str] = None
        self.backed_up: set = set()
        # off-policy promotion gate (ISSUE 18): one evaluation per
        # observation phase, run off the control thread (the spool read +
        # policy load may block; a stalled gate must not freeze every
        # OTHER rollout's state machine). gate_token fences late writes
        # from a stalled worker of a PREVIOUS observation phase.
        self.gate_started = False
        self.gate_result: Optional[dict] = None
        self.gate_token = 0
        # per-rollout stripe counter (under the router lock): the
        # Bresenham fraction must be exact over THIS policy's requests,
        # not the global sequence mixed across policies
        self.seq = 0
        self.windows = {
            "baseline": deque(maxlen=int(window)),
            "canary": deque(maxlen=int(window)),
        }

    def snapshot_row(self, permille: int) -> dict:
        return {
            "policy": self.policy,
            "state": self.state,
            "fraction": permille / 1000.0,
            "version": self.version,
            "window_baseline": len(self.windows["baseline"]),
            "window_canary": len(self.windows["canary"]),
        }


class Router:
    """The replicated front-end. ``start()`` binds and spawns the accept /
    control threads; ``drain()`` is the graceful stop (answer in-flight,
    shed new with ``draining``)."""

    # d4pglint shared-mutable-state: per-rollout cursor state moved onto
    # _Rollout (control thread only — declared there); _obs_dim is
    # written under _lock (prober) after the first successful probe and
    # only ever goes None -> int; _obs_dims entries likewise.
    _THREAD_SAFE = ()
    # d4pglint thread-lifecycle: router-gate workers are bounded by the
    # gate evaluation itself (spool read + one NumPy policy forward); a
    # wedged one (gate_stall chaos, hung filesystem) is exactly the
    # fault the observe-deadline rollback covers, and its late verdict
    # is token-fenced out — joining would hand the control thread the
    # very stall the design isolates it from. (Client connections live
    # on the netio event loop — no per-connection threads to account.)
    _DETACHED_THREADS = ("router-gate",)

    def __init__(
        self,
        backends,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        bundle_dirs=None,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        readmit_after: int = 2,
        dispatch_retries: int = 1,
        dispatch_timeout_s: float = 10.0,
        stuck_after_s: float = 30.0,
        retry_seed: int = 0,
        canary_bundle: Optional[str] = None,
        canary_fraction: float = 0.25,
        canary_window: int = 256,
        canary_min_samples: int = 40,
        canary_max_err_increase: float = 0.05,
        canary_p99_ratio: float = 3.0,
        canary_attest_timeout_s: float = 30.0,
        canary_observe_timeout_s: float = 600.0,
        mirror_tap=None,
        gate_spool: Optional[str] = None,
        gate_sigma: float = 0.3,
        gate_min_windows: int = 16,
        gate_min_ess: float = 4.0,
        gate_band: float = 1.0,
        gate_max_windows: int = 512,
        log_dir: Optional[str] = None,
        metrics_interval_s: float = 30.0,
        chaos=None,
        tenant_quotas=None,
        default_quota=None,
        replica_capacity: int = 0,
        bulk_fraction: float = 0.5,
        flood_burst: int = 200,
        io_read_stall_s: float = netio.loop.DEFAULT_READ_STALL_S,
        io_write_stall_s: float = netio.loop.DEFAULT_WRITE_STALL_S,
        io_write_buffer_limit: int = netio.loop.DEFAULT_WRITE_BUFFER_LIMIT,
    ):
        if not backends:
            raise ValueError("router needs at least one backend replica")
        bundle_dirs = list(bundle_dirs) if bundle_dirs else [None] * len(backends)
        if len(bundle_dirs) != len(backends):
            raise ValueError(
                f"{len(backends)} backends but {len(bundle_dirs)} bundle "
                "dirs — the canary controller needs a 1:1 mapping"
            )
        self._replicas = []
        for i, spec in enumerate(backends):
            if isinstance(spec, (tuple, list)):
                h, p = spec
            else:
                h, _, p = str(spec).rpartition(":")
            self._replicas.append(Replica(i, h or "127.0.0.1", int(p),
                                          bundle_dirs[i]))
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.stats = RouterStats()
        # Witnessed under --debug-guards (static node ids, see lockwitness)
        self._lock = lockwitness.named_lock("Router._lock")
        self._seq = 0
        self._obs_dim: Optional[int] = None
        # policy -> obs_dim learned from replica healthz ``policies`` rows
        # (the default policy also mirrors into _obs_dim for the v1 path)
        self._obs_dims: dict = {}

        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._readmit_after = int(readmit_after)
        self._dispatch_retries = int(dispatch_retries)
        self._dispatch_timeout_s = float(dispatch_timeout_s)
        self._stuck_after_s = float(stuck_after_s)
        # Seeded: the failover Backoff budget and its jitter replay
        # deterministically under --chaos, like every retry in this repo.
        self._retry_rng = random.Random(retry_seed)

        # ---- per-policy canary rollout state machines (control thread) ----
        # ``canary_bundle``: a bare dir (the PR-8 convention — a rollout
        # for the DEFAULT policy) or a {policy: dir} mapping; one
        # _Rollout per entry, fully independent.
        self._canary_permille = int(round(float(canary_fraction) * 1000))
        if canary_bundle is not None and not (
            0 < self._canary_permille < 1000
        ):
            raise ValueError(
                "--canary-fraction must be strictly between 0 and 1: the "
                "verdict compares a canary window AGAINST a baseline "
                "window, so both groups must receive traffic (0 routes "
                "nothing to the canary, 1 starves the baseline — either "
                "way the rollout would observe forever)"
            )
        if canary_bundle is None:
            canary_specs = {}
        elif isinstance(canary_bundle, str):
            canary_specs = {protocol.DEFAULT_POLICY: canary_bundle}
        else:
            canary_specs = dict(canary_bundle)
        self._rollouts: dict = {
            pol: _Rollout(pol, src, canary_window)
            for pol, src in sorted(canary_specs.items())
        }
        for pol in self._rollouts:
            if not any(
                pol in r.bundle_dirs for r in self._replicas
            ):
                raise ValueError(
                    f"--canary-bundle for policy {pol!r} needs "
                    "--backend-bundles hosting that policy: the router "
                    "rolls a replica forward by writing into ITS bundle "
                    "directory for the policy"
                )
        self._attest_timeout_s = float(canary_attest_timeout_s)
        self._observe_timeout_s = float(canary_observe_timeout_s)
        self._min_samples = int(canary_min_samples)
        self._max_err_increase = float(canary_max_err_increase)
        self._p99_ratio = float(canary_p99_ratio)
        # (replica index, policy) -> bundle_mtime it must attest before
        # probes count as healthy again (the re-eject-until-old-bundle
        # rollback contract, per policy)
        self._readmit_gate: dict = {}

        # ---- flywheel (ISSUE 18): router-position mirror tap + IS gate ----
        # The tap is externally owned (main() builds/closes it); the gate
        # reads the mirror SPOOL — candidate return is estimated from
        # logged behavior traffic, never from live requests.
        self._tap = mirror_tap
        self._gate_spool = gate_spool
        self._gate_sigma = float(gate_sigma)
        self._gate_min_windows = int(gate_min_windows)
        self._gate_min_ess = float(gate_min_ess)
        self._gate_band = float(gate_band)
        self._gate_max_windows = int(gate_max_windows)

        # ---- QoS + per-tenant admission (the multi-tenant tier) ----
        # tenant -> TokenBucket, built from the configured quotas and
        # lazily for unknown tenants under the default quota; everything
        # guarded by self._lock (one hop per request on the hot path).
        now = time.monotonic()
        self._tenant_buckets: dict = {}
        self._tenant_quota_conf = {
            str(t): (float(r), float(b))
            for t, (r, b) in (tenant_quotas or {}).items()
        }
        for t, (rate, burst) in self._tenant_quota_conf.items():
            self._tenant_buckets[t] = TokenBucket(rate, burst, now)
        self._default_quota = (
            (float(default_quota[0]), float(default_quota[1]))
            if default_quota else None
        )
        # Class-aware capacity: fleet capacity = admitted replicas ×
        # replica_capacity; bulk is admitted only below bulk_fraction of
        # it, interactive up to all of it — so overload sheds bulk FIRST.
        # replica_capacity 0 disables the class-aware admission tier
        # (quotas still apply), which is the PR-8 behavior.
        self._replica_capacity = int(replica_capacity)
        if not (0.0 < float(bulk_fraction) <= 1.0):
            raise ValueError(
                f"bulk_fraction must be in (0, 1], got {bulk_fraction}"
            )
        self._bulk_fraction = float(bulk_fraction)
        self._flood_burst = int(flood_burst)

        self._events: deque = deque(maxlen=1000)
        self._events_total = 0
        self._events_lock = lockwitness.named_lock("Router._events_lock")

        self._chaos = chaos
        self._log_dir = log_dir
        self._metrics_interval_s = metrics_interval_s
        self._metrics = None

        self._listen_sock: Optional[socket.socket] = None
        # ONE event-loop thread owns every client connection (reads,
        # frame reassembly, buffered writes, progress deadlines, bounded
        # accept) — the C10k front: thread count is O(1) in connections.
        self._loop = netio.FrameLoop(
            name="router-io",
            read_stall_s=io_read_stall_s,
            write_stall_s=io_write_stall_s,
            write_buffer_limit=io_write_buffer_limit,
        )
        self._control_thread: Optional[threading.Thread] = None
        self._metrics_thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        self._started = False

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            raise RuntimeError("router already started")
        self._started = True
        self._listen_sock = socket.create_server(
            (self.host, self._requested_port)
        )
        self.port = self._listen_sock.getsockname()[1]
        self._loop.serve(
            self._listen_sock,
            on_frame=self._serve_conn,
            on_open=self._on_conn_open,
            on_close=self._on_conn_close,
            on_protocol_error=self._on_protocol_error,
        )
        self._loop.start()
        self._control_thread = threading.Thread(
            target=self._control_loop, name="router-control", daemon=True
        )
        self._control_thread.start()
        if self._log_dir:
            from d4pg_tpu.runtime.metrics import MetricsLogger

            self._metrics = MetricsLogger(self._log_dir, use_tensorboard=False)
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="router-metrics", daemon=True
            )
            self._metrics_thread.start()

    def wait_for_replicas(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until ``n`` replicas are admitted (bounded, monotonic).
        Returns the admitted count; raises ``TimeoutError`` when the fleet
        never materializes — a router fronting zero replicas should fail
        its orchestrator's readiness check loudly, not serve OVERLOADED."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                admitted = sum(1 for r in self._replicas if r.admitted)
            if admitted >= n:
                return admitted
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"only {admitted}/{n} replicas admitted after {timeout_s}s"
                )
            time.sleep(0.05)

    def request_shutdown(self) -> None:
        """Signal-handler-safe: set the event; drain happens on the waiter."""
        self._shutdown.set()

    # ------------------------------------------------- elastic fleet (autoscaler)
    def add_backend(self, host: str, port: int, bundle_dirs=None) -> int:
        """Register a new replica at runtime (the autoscaler's scale-up
        seam). Returns its index. The replica starts un-admitted —
        admission flows through the normal K-consecutive-healthy-probes
        path, so a half-started process never takes traffic."""
        with self._lock:
            idx = len(self._replicas)
            r = Replica(idx, host, int(port), bundle_dirs)
            self._replicas.append(r)
        self._record_event("backend_added", replica=idx, addr=r.addr)
        return idx

    def remove_backend(self, index: int) -> None:
        """Deregister a replica (the autoscaler's scale-down seam, called
        BEFORE the SIGTERM so no new request lands on a draining process
        and sheds). Ejection closes the dispatch link — in-flight
        dispatches fail over via the bounded retry; the replica still
        answers what it had admitted. The replica keeps its index slot
        (rollout state and events reference indices) but becomes
        invisible to dispatch, probing, and capacity. If an active
        rollout touched it, the rollout's own control tick aborts via the
        normal rollback — which restores every touched bundle dir, so a
        scale-down can never strand a half-deployed replica."""
        with self._lock:
            r = self._replicas[index]
            if r.removed:
                return
            r.removed = True
            # a removed replica can never attest a restored bundle: any
            # readmit gate on it (a rollback that raced the drain) is
            # dead weight — drop it so no rollout waits on a ghost
            for key in [k for k in self._readmit_gate if k[0] == index]:
                del self._readmit_gate[key]
            to_close = self._eject_locked(r, "removed (scale-down)") \
                if r.admitted else None
        if to_close is not None:
            try:
                to_close.close()
            except OSError:
                pass
        self._record_event("backend_removed", replica=index, addr=r.addr)

    def pick_scaledown_candidate(self) -> Optional[int]:
        """The replica an autoscaler should drain next: prefer one no
        active rollout touched (draining a canary mid-rollout forces an
        abort — legal but wasteful), highest index first (LIFO — the
        autoscaler's own spawns go before the operator's seed fleet).
        None when nothing is admitted."""
        with self._lock:
            in_rollout = set()
            for ro in self._rollouts.values():
                if ro.state != "idle":
                    in_rollout |= set(ro.backed_up) | set(ro.deploys)
                    in_rollout |= {
                        r.index for r in self._replicas
                        if ro.policy in r.canary_for
                    }
            pool = [r for r in self._replicas if r.admitted and not r.removed]
            if not pool:
                return None
            clean = [r for r in pool if r.index not in in_rollout]
            return max(clean or pool, key=lambda r: r.index).index

    def serve_until_shutdown(self) -> None:
        # Park-until-signal is the design (same contract as PolicyServer).
        self._shutdown.wait()  # d4pglint: disable=thread-lifecycle  -- blocking forever is the serve loop
        self.drain()

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful stop: no new connections, shed new requests with
        ``draining``, let every in-flight dispatch come back, tear down."""
        self._shutdown.set()
        # No new connections; the loop keeps running so in-flight
        # dispatch replies (and ``draining`` sheds for frames that race
        # the drain) still reach their clients.
        self._loop.stop_accepting()
        self._listen_sock = None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                inflight = sum(r.inflight for r in self._replicas)
            if inflight == 0:
                break
            time.sleep(0.05)
        if self._control_thread is not None:
            self._control_thread.join(timeout=self._probe_interval_s + 10)
        with self._lock:
            clients = [r.client for r in self._replicas if r.client is not None]
            for r in self._replicas:
                r.client = None
                r.admitted = False
                r.ejected_reason = "router draining"
        for c in clients:
            try:
                c.close()
            except OSError:
                pass
        if self._metrics_thread is not None:
            self._metrics_thread.join(timeout=self._metrics_interval_s + 5)
        if self._metrics is not None:
            self._metrics.log(self.stats.requests_total, self._metrics_row())
            self._metrics.close()
            self._metrics = None
        # Flush every connection's queued replies (bounded by the write-
        # progress deadline), close them, join the one I/O thread.
        self._loop.close(flush_timeout_s=5.0)
        # --debug-guards: admission/terminal accounting, the promotion
        # gate's poll accounting, and every tenant row must balance now
        # that in-flight dispatches resolved and the readers are gone
        snap = self.stats.snapshot()
        flowledger.check("router", snap, where="router drain")
        flowledger.check("router-gate", snap, where="router drain")
        flowledger.check_rows(
            "router-tenant", self.stats.tenants_snapshot(),
            where="router drain",
        )

    # ------------------------------------------------------------ event log
    def _record_event(self, kind: str, **fields) -> None:
        """Structured decision log: one JSON line per event on stdout
        (greppable by the soak) + a bounded in-memory tail for healthz."""
        event = {"event": kind, "t": round(time.monotonic(), 3), **fields}
        with self._events_lock:
            self._events.append(event)
            self._events_total += 1
        print(f"[router-event] {json.dumps(event, sort_keys=True)}", flush=True)

    # ------------------------------------------------------- control thread
    def _control_loop(self) -> None:
        """Probe → eject/re-admit → canary step, every probe interval.
        ONE control thread on purpose: ejection flips and rollout
        transitions observe each other, and two timers would race."""
        while not self._shutdown.is_set():
            try:
                self._probe_all()
                self._canary_step()
            except Exception as e:  # control must never die silently
                print(f"[router] control loop error: {e!r}", flush=True)
                self._record_event("control_error", error=repr(e))
            if self._shutdown.wait(self._probe_interval_s):
                return

    def _probe_all(self) -> None:
        # Probes run CONCURRENTLY: sequentially, every unreachable replica
        # would stall the whole control loop by its full connect timeout
        # per round (M-1 dead backends → the survivor's ejection and the
        # canary attestation deadlines slip by seconds while the
        # wall-parallel monotonic deadlines keep ticking). Each probe is a
        # self-contained one-shot socket, so a thread per replica per
        # round is safe; a wedged probe past the join bound is treated as
        # failed and its daemon thread dies with its socket timeout.
        with self._lock:
            live = [r for r in self._replicas if not r.removed]
        results: list = [None] * len(live)

        def probe_one(i: int, r: Replica) -> None:
            try:
                results[i] = (protocol.probe_healthz(
                    r.host, r.port, timeout_s=self._probe_timeout_s
                ), None)
            except (OSError, ProtocolError) as e:
                results[i] = (None, e)

        threads = [
            threading.Thread(
                target=probe_one, args=(i, r),
                name=f"router-probe-{r.index}", daemon=True,
            )
            for i, r in enumerate(live)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self._probe_timeout_s + 2.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for r, res in zip(live, results):
            if res is None:
                res = (None, TimeoutError("probe thread did not finish"))
            self._apply_probe(r, res[0], res[1])
        self._check_stuck()

    def _check_stuck(self) -> None:
        """Eject a replica whose dispatches stopped resolving. A backend
        with a wedged device thread still answers healthz ``ok`` (status
        only reflects drain/reload state), so the prober alone would keep
        it admitted while its unanswered futures break the accounting
        identity and its leaked inflight biases least-loaded dispatch.
        Closing the dispatch link fails every in-flight future with
        ``ConnectionClosed`` — the normal bounded-failover trigger — so
        stuck requests are rescued onto other replicas, not abandoned."""
        if not self._stuck_after_s:
            return
        now = time.monotonic()
        to_close, ejected = [], []
        with self._lock:
            for r in self._replicas:
                if (
                    r.admitted and r.inflight > 0
                    and now - r.last_progress > self._stuck_after_s
                ):
                    to_close.append(self._eject_locked(
                        r, f"stuck: no dispatch resolved in "
                           f"{self._stuck_after_s:g}s"
                    ))
                    ejected.append(r)
        for c in to_close:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        for r in ejected:
            self._record_event("eject", replica=r.index, addr=r.addr,
                               reason="stuck")

    def _apply_probe(self, r: Replica, h: Optional[dict], err) -> None:
        to_close = None
        eject_reason = None
        dial = False
        with self._lock:
            if h is not None:
                r.health = h
                r.pid = h.get("pid")
                r.bundle_mtime = h.get("bundle_mtime")
                pol_rows = h.get("policies")
                if isinstance(pol_rows, dict) and pol_rows:
                    r.policies = tuple(sorted(pol_rows))
                    r.policy_mtimes = {
                        pid: row.get("bundle_mtime")
                        for pid, row in pol_rows.items()
                    }
                    for pid, row in pol_rows.items():
                        if pid not in self._obs_dims and row.get("obs_dim"):
                            self._obs_dims[pid] = int(row["obs_dim"])
                else:
                    # old single-policy replica: its one bundle IS the
                    # default policy's
                    r.policies = (protocol.DEFAULT_POLICY,)
                    r.policy_mtimes = {
                        protocol.DEFAULT_POLICY: h.get("bundle_mtime")
                    }
                if self._obs_dim is None and h.get("obs_dim"):
                    self._obs_dim = int(h["obs_dim"])
                    self._obs_dims.setdefault(
                        protocol.DEFAULT_POLICY, self._obs_dim
                    )
            if h is None or h.get("status") != "ok":
                r.healthy_streak = 0
                if r.admitted:
                    eject_reason = (
                        f"probe failed: {err!r}" if err is not None
                        else f"status: {h.get('status')}"
                    )
                    to_close = self._eject_locked(r, eject_reason)
            else:
                # rolled-back canary: healthy probes do not count until it
                # attests the RESTORED bundle version for EVERY gated
                # policy (gates are per (replica, policy) — a rollback of
                # policy A never gates on policy B's vector)
                gates = [
                    (key, mt) for key, mt in self._readmit_gate.items()
                    if key[0] == r.index
                ]
                unmet = [
                    key for key, mt in gates
                    if r.policy_mtimes.get(key[1]) != mt
                ]
                if unmet:
                    r.healthy_streak = 0
                else:
                    for key, _mt in gates:
                        del self._readmit_gate[key]
                    r.healthy_streak += 1
                    if (
                        not r.admitted and not r.removed
                        and r.healthy_streak >= self._readmit_after
                    ):
                        dial = True
        if to_close is not None:
            try:
                to_close.close()
            except OSError:
                pass
        if eject_reason is not None:
            self._record_event("eject", replica=r.index, addr=r.addr,
                               reason=eject_reason)
        if dial:
            self._admit(r)

    def _eject_locked(self, r: Replica, reason: str):
        """Caller holds ``self._lock``. Returns the dispatch link to close
        OUTSIDE the lock. Closing it fails every in-flight request on this
        replica with ``ConnectionClosed`` — which is exactly the bounded
        failover trigger, so ejection actively rescues in-flight work from
        a sick replica instead of letting it ride out a timeout."""
        r.admitted = False
        r.ejected_reason = reason
        r.healthy_streak = 0
        client, r.client = r.client, None
        self.stats.inc("ejections")
        return client

    def _admit(self, r: Replica) -> None:
        """Dial the dispatch link OUTSIDE the lock, then publish. The link
        is a pipelined PolicyClient at retries=0: the router's recovery is
        failover to a DIFFERENT replica, never a hammer on the same one."""
        try:
            client = PolicyClient(
                r.host, r.port, timeout=self._dispatch_timeout_s
            )
        except OSError as e:
            with self._lock:
                r.healthy_streak = 0
            self._record_event("admit_failed", replica=r.index, addr=r.addr,
                               error=str(e))
            return
        stale = None
        with self._lock:
            # r.removed: a probe round snapshotted before remove_backend
            # may still be applying — a removed replica must never
            # re-admit (its process is drained/gone)
            if r.admitted or r.removed or self._shutdown.is_set():
                stale = client
            else:
                r.client = client
                r.admitted = True
                r.ejected_reason = None
                r.last_progress = time.monotonic()
        if stale is not None:
            try:
                stale.close()
            except OSError:
                pass
            return
        self.stats.inc("admissions")
        self._record_event("admit", replica=r.index, addr=r.addr,
                           streak=r.healthy_streak)

    # -------------------------------------------------------------- dispatch
    def _pick(self, exclude, policy: str):
        """Least-loaded admitted replica HOSTING ``policy`` (ties →
        lowest index), honoring that policy's deterministic canary
        traffic split while ITS rollout is observing. Returns
        ``(replica, client)`` or ``(None, None)`` — the all-ejected case
        the router answers OVERLOADED itself."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            pool = [
                r for r in self._replicas
                if r.admitted and r.client is not None
                and not r.removed
                and r.index not in exclude
                and policy in r.policies
            ]
            if not pool:
                return None, None
            ro = self._rollouts.get(policy)
            if (
                ro is not None and ro.state == "observing"
                and self._canary_permille
            ):
                # Bresenham-style striping on THIS policy's own request
                # counter: request i is canary iff (i·permille) mod 1000 <
                # permille — the fraction is exact over any 1000-request
                # window of this policy's traffic AND interleaved, so both
                # comparison windows fill together (seq%1000 < permille
                # would send a contiguous block of 1000·fraction requests
                # to the canary first, starving the baseline window).
                ro.seq += 1
                want_canary = (
                    ro.seq * self._canary_permille
                ) % 1000 < self._canary_permille
                group = [
                    r for r in pool if (policy in r.canary_for) == want_canary
                ] or pool
            else:
                group = [r for r in pool if policy not in r.canary_for] or pool
            # least-loaded wins; ties rotate with the dispatch counter so
            # sequential (inflight-0) traffic round-robins instead of
            # pinning the lowest index
            n = len(self._replicas)
            best = group[0]
            best_key = (best.inflight, (best.index - seq) % n)
            for r in group[1:]:
                key = (r.inflight, (r.index - seq) % n)
                if key < best_key:
                    best, best_key = r, key
            if best.inflight == 0:
                # arm the stuck watermark: from idle, the clock starts at
                # this dispatch (while inflight stays >0 only resolutions
                # refresh it — see _check_stuck)
                best.last_progress = time.monotonic()
            best.inflight += 1
            return best, best.client

    def _admit_tenant(self, tenant: str, qos: int) -> Optional[bytes]:
        """Admission control, BEFORE dispatch: the tenant's token bucket,
        then the class-aware capacity check. Returns the shed reason
        (wire bytes) or None when admitted. One lock hop, no allocation
        per request (HOT_PATH_FUNCTIONS) — the lazy bucket creation for a
        never-seen tenant is the one cold-path exception.

        The shed ORDERING contract (docs/serving.md): fleet capacity is
        admitted-replicas × replica_capacity; bulk is admitted only while
        total inflight is under bulk_fraction × capacity, interactive up
        to full capacity — so under overload the bulk tier sheds FIRST
        and the interactive tier keeps its p99 inside the SLO."""
        now = time.monotonic()
        with self._lock:
            bucket = self._tenant_buckets.get(tenant)
            if bucket is None and self._default_quota is not None:
                if len(self._tenant_buckets) >= MAX_TENANT_ROWS:
                    bucket = self._tenant_buckets.get("__other__")
                    if bucket is None:
                        bucket = self._tenant_buckets["__other__"] = (
                            TokenBucket(*self._default_quota, now)
                        )
                else:
                    bucket = self._tenant_buckets[tenant] = TokenBucket(
                        *self._default_quota, now
                    )
            if bucket is not None and not bucket.take(now):
                # stats.inc nests RouterStats._lock under Router._lock —
                # the same order _eject_locked already established
                self.stats.inc("shed_quota")
                return b"quota"
            if self._replica_capacity:
                admitted = 0
                inflight = 0
                for r in self._replicas:
                    if r.admitted and not r.removed:
                        admitted += 1
                        inflight += r.inflight
                capacity = admitted * self._replica_capacity
                if qos == protocol.QOS_BULK:
                    if inflight >= int(capacity * self._bulk_fraction):
                        self.stats.inc("shed_bulk_capacity")
                        return b"bulk_capacity"
                elif inflight >= capacity:
                    self.stats.inc("shed_capacity")
                    return b"capacity"
        return None

    def _route(
        self,
        obs,
        deadline_us: int,
        req_id: int,
        reply,
        policy: str = protocol.DEFAULT_POLICY,
        qos: int = protocol.QOS_INTERACTIVE,
        tenant: str = "",
    ) -> None:
        """Dispatch one decoded request; ``reply`` is the per-connection
        frame writer. Exactly one reply per request, on every path — the
        accounting identity (aggregate AND per tenant/class) depends on
        it."""
        t0 = time.perf_counter()
        deadline_ms = deadline_us / 1e3 if deadline_us else None
        state = {"backoff": None, "exclude": []}

        def answered(outcome: int) -> None:
            # one call per request, on exactly one path — outcome offsets:
            # 1 = ok, 2 = overloaded, 3 = error (RouterStats row layout)
            self.stats.tenant_outcome(tenant, qos, outcome)

        def attempt():
            remaining_ms = None
            if deadline_ms is not None:
                # the client's deadline is a budget for the whole request,
                # not per attempt: a failover re-dispatch gets what's LEFT
                # (a first replica that burned the budget before shedding
                # must yield an honest OVERLOADED, not a reply at 2x the
                # declared deadline)
                remaining_ms = (
                    deadline_ms - (time.perf_counter() - t0) * 1e3
                )
                if remaining_ms <= 0:
                    self.stats.inc("replies_overloaded")
                    answered(2)
                    reply(protocol.OVERLOADED, req_id, b"deadline")
                    return
            replica, client = self._pick(state["exclude"], policy)
            if replica is None:
                self.stats.inc("replies_overloaded")
                answered(2)
                reply(protocol.OVERLOADED, req_id, b"no_replicas")
                return
            kill_pid = None
            if self._chaos is not None:
                e = self._chaos.tick("replica_kill")
                if e is not None:
                    kill_pid = replica.pid
            # Forward at the lowest frame version the request needs: any
            # DEFAULT-policy request rides the v1 ACT frame — qos and
            # tenant are ROUTER-admission concerns the replica discards,
            # so forwarding them would only break old (v1-only) replicas
            # behind this router (a v2 frame tears down the shared
            # pipelined link with a version error, failing every request
            # in flight on it). Only a non-default policy needs ACT2.
            fut = client.act_async(
                obs, remaining_ms,
                policy_id=(
                    None if policy == protocol.DEFAULT_POLICY else policy
                ),
            )
            if kill_pid:
                # AFTER the send: the request is on the wire — this is the
                # mid-stream replica death the failover contract covers.
                try:
                    os.kill(int(kill_pid), signal.SIGKILL)
                except (OSError, ValueError) as e:
                    print(f"[router] chaos replica_kill failed: {e}",
                          flush=True)

            def done(f, replica=replica):
                with self._lock:
                    replica.inflight -= 1
                    replica.last_progress = time.monotonic()
                exc = f.exception()
                lat = time.perf_counter() - t0
                ro = self._rollouts.get(policy)
                if exc is None:
                    with self._lock:
                        replica.ok += 1
                        if ro is not None:
                            ro.windows[
                                "canary" if policy in replica.canary_for
                                else "baseline"
                            ].append((True, lat))
                    self.stats.inc("replies_ok")
                    self.stats.add_latency(lat, qos)
                    answered(1)
                    reply(protocol.ACT_OK, req_id,
                          # inside f's own done-callback: resolved by
                          # definition, result() cannot block
                          protocol.encode_action(f.result()))  # d4pglint: disable=thread-lifecycle  -- done-callback, future resolved
                    return
                if isinstance(exc, (Overloaded, ConnectionClosed)):
                    bo = state["backoff"]
                    if bo is None:
                        # base_s=0: with another replica available the
                        # failover is immediate; the Backoff's job here is
                        # the bounded ATTEMPT budget (and determinism under
                        # --chaos via the seeded rng).
                        bo = state["backoff"] = Backoff(
                            base_s=0.0, jitter=0.0,
                            max_attempts=self._dispatch_retries,
                            rng=self._retry_rng,
                        )
                    delay = bo.next_delay()
                    if delay is not None:
                        state["exclude"].append(replica.index)
                        self.stats.inc("retries")
                        if delay:
                            time.sleep(delay)
                        attempt()
                        return
                with self._lock:
                    replica.errors += 1
                    if ro is not None and not isinstance(exc, Overloaded):
                        ro.windows[
                            "canary" if policy in replica.canary_for
                            else "baseline"
                        ].append((False, lat))
                if isinstance(exc, Overloaded):
                    self.stats.inc("replies_overloaded")
                    answered(2)
                    reply(protocol.OVERLOADED, req_id,
                          str(exc).encode() or b"overloaded")
                else:
                    self.stats.inc("replies_error")
                    answered(3)
                    reply(protocol.ERROR, req_id,
                          f"failed after bounded retry: {exc}".encode())

            fut.add_done_callback(done)

        attempt()

    # -------------------------------------------------- synthetic chaos load
    @staticmethod
    def _sink(_msg_type, _req_id, _payload=b"") -> None:
        """Reply writer for synthetic chaos requests: the outcome counters
        tally through the normal _route path; there is no socket to
        answer."""

    def _inject_synthetic(self, policy: str, qos: int, tenant: str,
                          n: int) -> int:
        """Push ``n`` synthetic requests for ``policy`` through the REAL
        admission + dispatch path (counted in requests_total and every
        per-tenant/class identity row — the identity stays exact because
        synthetic traffic is accounted exactly like real traffic).
        Returns how many were injected (0 when the policy's obs_dim is
        still unknown)."""
        dim = self._obs_dims.get(policy)
        if dim is None and policy == protocol.DEFAULT_POLICY:
            dim = self._obs_dim
        if dim is None:
            return 0
        obs = np.zeros(dim, np.float32)
        for _ in range(n):
            self.stats.inc("requests_total")
            self.stats.tenant_request(tenant, qos)
            shed = self._admit_tenant(tenant, qos)
            if shed is not None:
                self.stats.inc("replies_overloaded")
                self.stats.tenant_outcome(tenant, qos, 2)
                continue
            self._route(obs, 0, 0, self._sink,
                        policy=policy, qos=qos, tenant=tenant)
        return n

    def _inject_flood(self, tenant: str, n: int) -> None:
        """The ``tenant_flood`` chaos site: a burst of BULK-class requests
        from one named tenant. Under the admission contracts most of it
        sheds at the tenant's quota / the bulk capacity line — which is
        the point: the soak asserts interactive p99 holds through it."""
        self._record_event("chaos_tenant_flood", tenant=tenant, n=n)
        self._inject_synthetic(
            protocol.DEFAULT_POLICY, protocol.QOS_BULK, tenant, n
        )

    def _inject_skew(self, n: int) -> None:
        """The ``policy_skew`` chaos site: 95% of a synthetic burst hits
        the default policy, 5% spreads over the other known policies —
        the cold policies' real traffic must still meet its deadlines
        (their batchers are independent; the shared resource is the
        host/device, which is what the soak measures)."""
        with self._lock:
            cold = sorted(
                p for p in self._obs_dims if p != protocol.DEFAULT_POLICY
            )
        self._record_event("chaos_policy_skew", n=n, cold_policies=cold)
        hot = int(n * 0.95) if cold else n
        self._inject_synthetic(
            protocol.DEFAULT_POLICY, protocol.QOS_BULK, "skew_tenant", hot
        )
        if cold:
            per = max(1, (n - hot) // len(cold))
            for p in cold:
                self._inject_synthetic(
                    p, protocol.QOS_BULK, "skew_tenant", per
                )

    # ------------------------------------------------------------ client side
    def _on_conn_open(self, conn) -> None:
        # Connection-level chaos sites fire at accept: each launches a
        # loop-timer-driven attacker against this router's own listener
        # (slowloris trickle / zero-window staller / fd hoard), proving
        # the eviction machinery on live traffic.
        if self._chaos is not None:
            netio_attack.tick_attacks(
                self._chaos, self._loop, self.host, self.port
            )

    def _on_conn_close(self, conn) -> None:
        if self._tap is not None:
            # vanished client: drop its half-built mirror window whole
            self._tap.on_disconnect(id(conn))

    def _on_protocol_error(self, conn, exc) -> None:
        # Framing is per-connection state: connection-fatal ERROR (req_id
        # 0), then the loop flush-closes. Other connections are untouched.
        self.stats.inc("protocol_errors")
        conn.send(protocol.ERROR, 0, str(exc).encode())

    def _reply(self, conn, msg_type: int, req_id: int,
               payload: bytes = b"") -> None:
        if not conn.send(msg_type, req_id, payload):
            # Client gone before its reply (disconnect-mid-request) or
            # evicted for stalling: count the computed-but-undeliverable
            # reply, same as the thread path's OSError branch did.
            self.stats.inc("dropped_replies")

    def _serve_conn(self, conn, msg_type: int, req_id: int,
                    payload: bytes) -> None:
        """One complete frame, on the loop thread. Must not block — the
        dispatch tier (``_route``) is already asynchronous (replica link
        done-callbacks), so the only stall the thread path tolerated
        here, the ``replica_slow`` chaos sleep, becomes a loop timer.
        Raising :class:`ProtocolError` routes to ``_on_protocol_error``
        (connection-fatal), like a framing error from the byte stream."""

        def reply(msg_type: int, req_id: int, payload: bytes = b"") -> None:
            self._reply(conn, msg_type, req_id, payload)

        if msg_type == protocol.HEALTHZ:
            reply(protocol.HEALTHZ_OK, req_id,
                  json.dumps(self.healthz()).encode())
            return
        if msg_type == protocol.ACT:
            # v1: default policy, interactive class, anonymous
            # tenant — old clients negotiate down implicitly
            policy = protocol.DEFAULT_POLICY
            qos = protocol.QOS_INTERACTIVE
            tenant = ""
            obs_dim = self._obs_dim
            if obs_dim is None:
                # no replica has ever answered a probe: obs_dim
                # (and the fleet) is unknown — shed honestly
                self.stats.inc("requests_total")
                self.stats.inc("replies_overloaded")
                reply(protocol.OVERLOADED, req_id, b"no_replicas")
                return
            obs, deadline_us = protocol.decode_act(payload, obs_dim)
        elif msg_type == protocol.ACT2:
            obs, deadline_us, policy, qos, tenant = (
                protocol.decode_act2(payload)
            )
            known = self._obs_dims.get(policy)
            if known is not None and obs.shape[0] != known:
                self.stats.inc("requests_total")
                self.stats.tenant_request(tenant, qos)
                self.stats.inc("replies_error")
                self.stats.tenant_outcome(tenant, qos, 3)
                reply(
                    protocol.ERROR, req_id,
                    f"obs is {obs.shape[0]}-dim, policy "
                    f"{policy!r} wants {known}".encode(),
                )
                return
        elif msg_type == protocol.FEEDBACK:
            # Reward echo for THIS connection's previous request —
            # handled LOCALLY (the router decoded the obs, so it
            # can pair the feedback itself; forwarding would need
            # replica-sticky feedback routing for no benefit).
            # Always acked: clients need not know whether a tap
            # rides this router.
            fb = protocol.decode_feedback(payload)
            self.stats.inc("feedback_frames")
            if (
                self._tap is not None
                and fb["policy_id"] == protocol.DEFAULT_POLICY
            ):
                self._tap.on_feedback(id(conn), fb)
            reply(protocol.FEEDBACK_OK, req_id)
            return
        else:
            raise ProtocolError(f"unexpected message type {msg_type}")
        if (
            self._tap is not None
            and policy == protocol.DEFAULT_POLICY
        ):
            # remember the obs this connection's next FEEDBACK
            # pairs with
            self._tap.on_request(id(conn), obs)
        self.stats.inc("requests_total")
        self.stats.tenant_request(tenant, qos)
        if self._shutdown.is_set():
            self.stats.inc("replies_overloaded")
            self.stats.tenant_outcome(tenant, qos, 2)
            reply(protocol.OVERLOADED, req_id, b"draining")
            return
        if self._chaos is not None:
            e = self._chaos.tick("replica_slow")
            if e is not None:
                # stall THIS request's dispatch (a slow replica as seen
                # by one request): p99 must account it, other connections
                # must not feel it — so the stall is a loop TIMER, never
                # a sleep on the loop thread
                self._loop.call_later(
                    (e.arg if e.arg is not None else 100.0) / 1e3,
                    self._admit_and_route,
                    conn, req_id, obs, deadline_us, policy, qos, tenant,
                )
                return
            e = self._chaos.tick("tenant_flood")
            if e is not None:
                # synthetic bulk flood from the named tenant: real
                # load through the real admission + dispatch path
                # (counted in every identity surface) — proves
                # interactive isolation under a misbehaving tenant
                self._inject_flood(
                    e.label or "flood_tenant", self._flood_burst
                )
            e = self._chaos.tick("policy_skew")
            if e is not None:
                # 95% of a synthetic burst hits the default
                # policy; the cold policies' requests ride along
                # and must still meet their deadlines
                self._inject_skew(self._flood_burst)
        self._admit_and_route(
            conn, req_id, obs, deadline_us, policy, qos, tenant
        )

    def _admit_and_route(self, conn, req_id, obs, deadline_us, policy,
                         qos, tenant) -> None:
        """Admission (quota first, then the class-aware capacity check —
        sheds here never reach a replica) and dispatch for one already-
        counted request. Split out of ``_serve_conn`` so a
        ``replica_slow`` stall can defer it on a loop timer."""

        def reply(msg_type: int, req_id: int, payload: bytes = b"") -> None:
            self._reply(conn, msg_type, req_id, payload)

        shed = self._admit_tenant(tenant, qos)
        if shed is not None:
            self.stats.inc("replies_overloaded")
            self.stats.tenant_outcome(tenant, qos, 2)
            reply(protocol.OVERLOADED, req_id, shed)
            return
        self._route(obs, deadline_us, req_id, reply,
                    policy=policy, qos=qos, tenant=tenant)

    # ------------------------------------------------------- canary rollout
    def _canary_step(self) -> None:
        """One control tick for EVERY per-policy rollout. The machines
        are independent: policy A deploying while policy B observes is
        normal, and no step of one ever touches another's replicas,
        bundle dirs, windows, or readmit gates."""
        for ro in self._rollouts.values():
            state = ro.state
            if state == "idle":
                self._canary_idle(ro)
            elif state == "deploying":
                self._canary_check_deploys(ro)
            elif state == "observing":
                self._canary_observe(ro)
            elif state == "promoting":
                self._canary_promote(ro)
            elif state == "rolling_back":
                self._canary_check_rollback(ro)

    def _set_canary_state(self, ro: _Rollout, state: str) -> None:
        with self._lock:
            ro.state = state

    def _clear_windows(self, ro: _Rollout) -> None:
        with self._lock:
            ro.windows["baseline"].clear()
            ro.windows["canary"].clear()

    def _removed_mid_rollout(self, ro: _Rollout) -> Optional[list]:
        """Replica indices the rollout touched that were REMOVED
        (scale-down) — an active rollout must abort rather than wait on a
        replica that no longer exists, and the abort's restore is what
        un-strands the removed replica's half-deployed bundle dir."""
        with self._lock:
            touched = (
                set(ro.backed_up)
                | set(ro.deploys)
                | {
                    r.index for r in self._replicas
                    if ro.policy in r.canary_for
                }
            )
            removed = sorted(
                i for i in touched if self._replicas[i].removed
            )
        return removed or None

    def _canary_idle(self, ro: _Rollout) -> None:
        m = _bundle_json_mtime(ro.src_dir)
        if m is None or m == ro.seen_mtime:
            return
        with self._lock:
            eligible = [
                r for r in self._replicas
                if r.admitted and not r.removed
                and ro.policy in r.bundle_dirs
            ]
            total = len([r for r in self._replicas if not r.removed])
        if len(eligible) < 2:
            # a canary needs at least one baseline to compare against;
            # keep waiting (the bookmark does NOT advance — the rollout
            # starts as soon as the fleet is healthy enough)
            return
        n_canary = min(max(1, round(self._canary_permille / 1000 * total)),
                       len(eligible) - 1)
        # deterministic choice: the highest-index eligible replicas
        canaries = sorted(eligible, key=lambda r: -r.index)[:n_canary]
        ro.seen_mtime = m
        ro.version = m
        ro.rollback_dir = tempfile.mkdtemp(
            prefix=f"d4pg-router-rollback-{ro.policy}-"
        )
        ro.backed_up = set()
        deploys = {}
        try:
            for r in canaries:
                self._backup_bundle(ro, r)
                corrupt = False
                if self._chaos is not None:
                    corrupt = self._chaos.tick("canary_corrupt") is not None
                deploys[r.index] = self._deploy_bundle(
                    ro.src_dir, r.bundle_dirs[ro.policy], corrupt=corrupt
                )
        except OSError as e:
            # Mid-deploy I/O failure (ENOSPC, unreadable canary source, a
            # missing replica bundle file): any canary ALREADY rolled
            # forward must not be left serving the new bundle as a phantom
            # baseline. Route through the normal rollback — it restores
            # every replica in backed_up and re-ejects until the old
            # version attests; the bookmark stays advanced so a broken
            # rollout is reported once, not retried every probe tick.
            self._canary_rollback(ro, f"deploy I/O error: {e!r}")
            return
        with self._lock:
            for r in canaries:
                r.canary_for.add(ro.policy)
            ro.state = "deploying"
        ro.deploys = deploys
        ro.deadline = time.monotonic() + self._attest_timeout_s
        self._clear_windows(ro)
        self._record_event(
            "canary_start", policy=ro.policy, version=m,
            canaries=[r.index for r in canaries],
            fraction=self._canary_permille / 1000.0,
        )

    def _canary_check_deploys(self, ro: _Rollout) -> None:
        removed = self._removed_mid_rollout(ro)
        if removed:
            self._canary_rollback(
                ro, f"replicas {removed} removed (scale-down) mid-deploy"
            )
            return
        with self._lock:
            canaries = [
                r for r in self._replicas if ro.policy in r.canary_for
            ]
            attested = all(
                r.policy_mtimes.get(ro.policy) == ro.deploys.get(r.index)
                and r.admitted
                for r in canaries
            )
            failed = [
                r.index for r in canaries
                if not r.admitted or r.health.get("status") == "degraded"
            ]
        if attested:
            self._set_canary_state(ro, "observing")
            # observing gets its own deadline: every other rollout state
            # is bounded, and a fleet with too little traffic to fill the
            # comparison windows must eventually roll back (frozen canary
            # traffic + a rollout that blocks every newer version forever
            # is worse than retrying later under real load)
            ro.deadline = time.monotonic() + self._observe_timeout_s
            self._clear_windows(ro)
            # fresh observation phase → fresh gate: the token fences any
            # still-running gate worker from a previous phase out of
            # writing into this one
            ro.gate_started = False
            ro.gate_result = None
            ro.gate_token += 1
            self._record_event("canary_observing", policy=ro.policy,
                               version=ro.version)
        elif failed or time.monotonic() > ro.deadline:
            self._canary_rollback(
                ro,
                f"deploy failed on replicas {failed}" if failed
                else "deploy attestation timed out"
            )

    def _canary_observe(self, ro: _Rollout) -> None:
        removed = self._removed_mid_rollout(ro)
        if removed:
            self._canary_rollback(
                ro,
                f"replicas {removed} removed (scale-down) mid-observation"
            )
            return
        with self._lock:
            dead = [r.index for r in self._replicas
                    if ro.policy in r.canary_for and not r.admitted]
            base = list(ro.windows["baseline"])
            can = list(ro.windows["canary"])
        if dead:
            self._canary_rollback(ro, f"canary replicas {dead} ejected "
                                  "mid-observation")
            return
        if len(base) < self._min_samples or len(can) < self._min_samples:
            if time.monotonic() > ro.deadline:
                self._canary_rollback(
                    ro,
                    f"observation starved: windows never filled "
                    f"({len(base)} baseline / {len(can)} canary of "
                    f"{self._min_samples} required)"
                )
            return
        base_err = 1.0 - sum(ok for ok, _ in base) / len(base)
        can_err = 1.0 - sum(ok for ok, _ in can) / len(can)
        base_p99 = _p99([lat for ok, lat in base if ok])
        can_p99 = _p99([lat for ok, lat in can if ok])
        verdict = {
            "baseline_error_rate": round(base_err, 4),
            "canary_error_rate": round(can_err, 4),
            "baseline_p99_ms": _ms(base_p99),
            "canary_p99_ms": _ms(can_p99),
            "samples": [len(base), len(can)],
        }
        if can_err > base_err + self._max_err_increase:
            self._canary_rollback(
                ro,
                f"error-rate regression {can_err:.4f} vs {base_err:.4f}",
                **verdict,
            )
        elif (
            base_p99 is not None and can_p99 is not None
            and can_p99 > base_p99 * self._p99_ratio + 0.010
        ):
            self._canary_rollback(
                ro,
                f"p99 regression {_ms(can_p99)} ms vs {_ms(base_p99)} ms",
                **verdict,
            )
        else:
            # The live verdict (errors + p99) passed. A bad-but-valid
            # bundle shows NEITHER signal — it serves cleanly while
            # steering the plant wrong — so when an off-policy gate is
            # configured, promotion additionally needs its IS-estimate
            # verdict over the MIRRORED windows (flywheel/gate.py).
            if self._gate_spool is not None:
                if not ro.gate_started:
                    # kick the evaluation off-thread (spool read + policy
                    # load may block; gate_stall chaos sleeps in there)
                    # and keep observing until it resolves
                    ro.gate_started = True
                    ro.gate_result = None
                    token = ro.gate_token
                    self.stats.inc("gate_evaluations")
                    self._record_event("gate_evaluating", policy=ro.policy,
                                       version=ro.version)
                    threading.Thread(
                        target=self._gate_worker, args=(ro, token),
                        name="router-gate", daemon=True,
                    ).start()
                    return
                gate = ro.gate_result
                if gate is None:
                    if time.monotonic() > ro.deadline:
                        # the observe deadline bounds the gate too: a
                        # wedged evaluation must not hold the rollout —
                        # and every newer version behind it — forever
                        self.stats.inc("gate_stalls")
                        self._canary_rollback(
                            ro,
                            "promotion gate stalled past observe deadline",
                            **verdict,
                        )
                    return
                if not gate.get("passed"):
                    self.stats.inc("gate_block")
                    self._canary_rollback(
                        ro,
                        f"off-policy gate: {gate.get('reason')}",
                        gate=gate, **verdict,
                    )
                    return
                self.stats.inc("gate_pass")
                verdict["gate"] = gate
            # canary_promotions ticks at COMPLETION (the canary_promoted
            # terminal in _canary_promote), not here at the verdict: a
            # promote that later fails (deploy I/O, attestation timeout)
            # ends in a rollback, and one rollout must never book both
            ro.promote_done = set()
            ro.deploys = {}
            self._set_canary_state(ro, "promoting")
            self._record_event("canary_promote", policy=ro.policy,
                               version=ro.version, **verdict)

    def _gate_worker(self, ro: _Rollout, token: int) -> None:
        """One off-policy gate evaluation (its own thread): estimate the
        CANDIDATE bundle's return on the mirror spool's logged behavior
        windows. Any failure becomes a refusing verdict — a gate that
        dies must block the promotion loudly, never wedge or wave it
        through."""
        try:
            if self._chaos is not None:
                e = self._chaos.tick("gate_stall")
                if e is not None:
                    # stall INSIDE the evaluation (a wedged spool read /
                    # slow shared filesystem): the control thread must
                    # roll back at the observe deadline, not wait forever
                    time.sleep(e.arg if e.arg is not None else 3600.0)
            from d4pg_tpu.fleet.policy import load_numpy_policy
            from d4pg_tpu.flywheel.gate import evaluate_is_gate
            from d4pg_tpu.flywheel.spool import read_windows

            pol = load_numpy_policy(ro.src_dir)
            cols, _n = read_windows(
                self._gate_spool, pol.obs_dim, pol.action_dim,
                max_windows=self._gate_max_windows,
            )
            verdict = evaluate_is_gate(
                cols, pol,
                sigma=self._gate_sigma,
                min_windows=self._gate_min_windows,
                min_ess=self._gate_min_ess,
                band=self._gate_band,
            )
        except Exception as e:  # d4pglint: disable=broad-except  -- every failure class (missing spool, unreadable bundle, bad dims) becomes a REFUSING verdict carrying the repr: logged via the canary_rollback event, never swallowed
            verdict = {
                "samples": 0, "passed": False,
                "reason": f"gate evaluation failed: {e!r}",
            }
        if ro.gate_token == token:
            ro.gate_result = verdict

    def _canary_promote(self, ro: _Rollout) -> None:
        """Roll the remaining baselines forward ONE at a time, each
        attested before the next — a bad surprise mid-promote strands one
        replica, not the fleet."""
        removed = self._removed_mid_rollout(ro)
        if removed:
            self._canary_rollback(
                ro, f"replicas {removed} removed (scale-down) mid-promote"
            )
            return
        with self._lock:
            baselines = [r for r in self._replicas
                         if ro.policy in r.bundle_dirs
                         and ro.policy not in r.canary_for
                         and not r.removed]
            pending = [r for r in baselines if r.index in ro.deploys]
            for r in pending:
                if (
                    r.policy_mtimes.get(ro.policy) == ro.deploys[r.index]
                    and r.admitted
                ):
                    ro.promote_done.add(r.index)
                    del ro.deploys[r.index]
        for r in pending:
            if r.index in ro.promote_done:
                self._record_event("promoted_replica", policy=ro.policy,
                                   replica=r.index)
        if ro.deploys:
            if time.monotonic() > ro.deadline:
                self._canary_rollback(
                    ro,
                    f"promote attestation timed out on "
                    f"{sorted(ro.deploys)}"
                )
            return
        nxt = next(
            (r for r in baselines if r.index not in ro.promote_done), None
        )
        if nxt is not None:
            try:
                self._backup_bundle(ro, nxt)
                mt = self._deploy_bundle(
                    ro.src_dir, nxt.bundle_dirs[ro.policy]
                )
            except OSError as e:
                # same contract as the idle-path deploy guard: a promote
                # whose source vanished or whose disk filled must roll the
                # whole rollout back, not spin in "promoting" re-raising
                # into the control loop's catch-all every tick
                self._canary_rollback(
                    ro, f"deploy I/O error during promote: {e!r}"
                )
                return
            ro.deploys = {nxt.index: mt}
            ro.deadline = time.monotonic() + self._attest_timeout_s
            self._record_event("promote_replica", policy=ro.policy,
                               replica=nxt.index)
            return
        # nxt is None: every baseline rolled forward — terminal event
        # BEFORE the state flip: a healthz reader that polls for
        # state=="idle" must find the terminal event already in
        # events_tail (the soak and tests do exactly that)
        self.stats.inc("canary_promotions")
        self._record_event("canary_promoted", policy=ro.policy,
                           version=ro.version)
        with self._lock:
            for r in self._replicas:
                r.canary_for.discard(ro.policy)
            ro.state = "idle"
        self._cleanup_rollback_dir(ro)

    def _canary_rollback(self, ro: _Rollout, reason: str, **verdict) -> None:
        """Restore every replica the rollout touched to the saved old
        bundle for THIS policy and RE-EJECT it until its healthz attests
        that old version (then the normal K-consecutive-probes
        re-admission applies). Baselines that were never deployed to —
        and every other policy's bundles — are never touched. A REMOVED
        replica still gets its bundle dir restored (nothing half-deployed
        may remain on disk) but is never gated or ejected: it has already
        left the fleet."""
        # State flips FIRST: once canary_rollbacks ticks (next line), a
        # healthz reader must never see the rollout still "idle"/
        # "observing" — a rollback entered from idle (deploy I/O error)
        # does file restores below before the gates land, and that window
        # read as a settled fleet.
        with self._lock:
            ro.state = "rolling_back"
        # deadline BEFORE the restores: if one raises below, the next
        # _canary_check_rollback tick must compare against a real deadline,
        # not a stale/None one (TypeError every control tick = a
        # permanently wedged rollout machine)
        ro.rollback_deadline = time.monotonic() + 4 * self._attest_timeout_s
        self.stats.inc("canary_rollbacks")
        self._record_event("canary_rollback", policy=ro.policy,
                           reason=reason, version=ro.version, **verdict)
        gates = {}
        restore_failed = []
        for i in sorted(ro.backed_up):
            r = self._replicas[i]
            try:
                gates[i] = self._deploy_bundle(
                    os.path.join(ro.rollback_dir, str(i)),
                    r.bundle_dirs[ro.policy],
                )
            except OSError as e:
                # the restore itself failed (ENOSPC again, backup dir
                # damaged): no version to gate re-admission on — eject the
                # replica below anyway (its probes decide re-admission) and
                # say so loudly; the rollback deadline bounds the wait
                restore_failed.append((i, e))
        to_close = []
        ejected = []
        with self._lock:
            for i in sorted(ro.backed_up):
                r = self._replicas[i]
                if r.removed:
                    continue  # restored above; no gate, no eject
                if i in gates:
                    self._readmit_gate[(i, ro.policy)] = gates[i]
                if r.admitted:
                    to_close.append(self._eject_locked(r, "rollback"))
                    ejected.append(i)
                else:
                    r.healthy_streak = 0
        ro.deploys = {}
        for c in to_close:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        for i, e in restore_failed:
            self._record_event("rollback_restore_failed", policy=ro.policy,
                               replica=i, error=repr(e))
        for i in ejected:
            self._record_event("eject", replica=i,
                               addr=self._replicas[i].addr, reason="rollback")

    def _canary_check_rollback(self, ro: _Rollout) -> None:
        with self._lock:
            # every replica the rollout DEPLOYED to (canaries, plus any
            # baseline a failed promote already rolled forward) must attest
            # the restored bundle and re-admit before the rollback is done
            # — except removed replicas, which left the fleet (their dirs
            # were restored; there is no process to wait for)
            waiting = [
                r.index for r in self._replicas
                if r.index in ro.backed_up and not r.removed
                and ((r.index, ro.policy) in self._readmit_gate
                     or not r.admitted)
            ]
        if not waiting:
            # terminal event BEFORE the state flip (see _canary_promote)
            self._record_event("canary_rolled_back", policy=ro.policy,
                               version=ro.version)
            with self._lock:
                for r in self._replicas:
                    r.canary_for.discard(ro.policy)
                ro.state = "idle"
            self._cleanup_rollback_dir(ro)
            return
        if time.monotonic() > ro.rollback_deadline:
            # the replica never came back (killed and not restarted?) —
            # stop gating on it so a fresh process serving the restored
            # bundle can re-admit normally, and say so loudly
            self._record_event("canary_rollback_timeout", policy=ro.policy,
                               version=ro.version, waiting=waiting)
            with self._lock:
                for r in self._replicas:
                    r.canary_for.discard(ro.policy)
                for key in [
                    k for k in self._readmit_gate if k[1] == ro.policy
                ]:
                    del self._readmit_gate[key]
                ro.state = "idle"
            self._cleanup_rollback_dir(ro)

    def _backup_bundle(self, ro: _Rollout, r: Replica) -> None:
        if r.index in ro.backed_up:
            # never overwrite the pristine pre-rollout copy: a re-entered
            # promote step after a partial deploy would otherwise save the
            # half-deployed dir (new params + old json) AS the backup, and
            # a later rollback would restore that corrupt mixture
            return
        dst = os.path.join(ro.rollback_dir, str(r.index))
        os.makedirs(dst, exist_ok=True)
        for fname in (_PARAMS_FILE, _META_FILE):
            shutil.copyfile(
                os.path.join(r.bundle_dirs[ro.policy], fname),
                os.path.join(dst, fname),
            )
        ro.backed_up.add(r.index)

    def _deploy_bundle(self, src_dir: str, dst_dir: str,
                       corrupt: bool = False) -> float:
        """Roll ``dst_dir`` (a replica's live bundle) onto ``src_dir``'s
        content: params FIRST, json second, each tmp+rename — the
        exporter's atomic attestation ordering, reproduced because the
        router IS an exporter when it rolls a replica forward. Returns the
        new json mtime (the version the replica must attest via healthz).
        ``corrupt`` is the ``canary_corrupt`` chaos fault: truncate the
        params copy so the replica's reload fails AFTER the attestation
        moved — the degraded-not-promoted path."""
        os.makedirs(dst_dir, exist_ok=True)
        for fname in (_PARAMS_FILE, _META_FILE):
            src = os.path.join(src_dir, fname)
            fd, tmp = tempfile.mkstemp(dir=dst_dir, suffix=".tmp")
            os.close(fd)
            try:
                shutil.copyfile(src, tmp)
                if corrupt and fname == _PARAMS_FILE:
                    size = os.path.getsize(tmp)
                    with open(tmp, "rb+") as f:
                        f.truncate(max(1, size // 2))
                    print(f"[router] chaos canary_corrupt: truncated "
                          f"{fname} for {dst_dir}", flush=True)
                os.replace(tmp, os.path.join(dst_dir, fname))
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        return os.stat(os.path.join(dst_dir, _META_FILE)).st_mtime

    def _cleanup_rollback_dir(self, ro: _Rollout) -> None:
        if ro.rollback_dir is not None:
            shutil.rmtree(ro.rollback_dir, ignore_errors=True)
            ro.rollback_dir = None
        ro.backed_up = set()

    # ----------------------------------------------------------------- status
    # healthz keeps at most this many REMOVED replica rows (newest first
    # by index): scale-down tombstones stay in _replicas forever for
    # index stability, and without a bound a long-lived autoscaled
    # router would serialize every dead row into every probe reply.
    _HEALTHZ_REMOVED_ROWS = 16

    def healthz(self) -> dict:
        with self._lock:
            removed_idx = [r.index for r in self._replicas if r.removed]
            drop = set(removed_idx[:-self._HEALTHZ_REMOVED_ROWS]) \
                if len(removed_idx) > self._HEALTHZ_REMOVED_ROWS else set()
            replicas = [
                {
                    "index": r.index,
                    "addr": r.addr,
                    "admitted": r.admitted,
                    "removed": r.removed,
                    "ejected_reason": r.ejected_reason,
                    "canary": sorted(r.canary_for),
                    "policies": list(r.policies),
                    "inflight": r.inflight,
                    "healthy_streak": r.healthy_streak,
                    "bundle_mtime": r.bundle_mtime,
                    "policy_mtimes": dict(r.policy_mtimes),
                    "pid": r.pid,
                    "replica_id": r.health.get("replica_id"),
                    "status": r.health.get("status"),
                    "compile_count": r.health.get("compile_count"),
                    "params_reloads": r.health.get("params_reloads"),
                    "ok": r.ok,
                    "errors": r.errors,
                }
                for r in self._replicas
                if r.index not in drop
            ]
            admitted = sum(
                1 for r in self._replicas if r.admitted and not r.removed
            )
            inflight = sum(
                r.inflight for r in self._replicas if not r.removed
            )
            rollouts = {
                pol: ro.snapshot_row(self._canary_permille)
                for pol, ro in self._rollouts.items()
            }
            obs_dim = self._obs_dim
            obs_dims = dict(self._obs_dims)
            capacity = admitted * self._replica_capacity
        snap = self.stats.snapshot()
        snap["router"] = True
        snap["status"] = "draining" if self._shutdown.is_set() else (
            "ok" if admitted else "degraded"
        )
        snap["draining"] = self._shutdown.is_set()
        snap["admitted"] = admitted
        snap["inflight"] = inflight
        snap["obs_dim"] = obs_dim
        snap["obs_dims"] = obs_dims
        snap["replicas"] = replicas
        # Back-compat: ``canary`` stays the DEFAULT policy's rollout view
        # (the PR-8 single-rollout schema); every rollout — default
        # included — also appears under ``rollouts`` keyed by policy.
        default_ro = rollouts.get(protocol.DEFAULT_POLICY)
        snap["canary"] = default_ro if default_ro is not None else {
            "state": "idle",
            "fraction": self._canary_permille / 1000.0,
            "version": None,
            "window_baseline": 0,
            "window_canary": 0,
        }
        snap["rollouts"] = rollouts
        # The multi-tenant admission surface: capacity model + exact
        # per-(tenant, class) accounting rows. answered == requests on
        # every row at quiesce — the machine-checked identity.
        snap["capacity"] = {
            "replica_capacity": self._replica_capacity,
            "bulk_fraction": self._bulk_fraction,
            "total": capacity,
            "bulk_limit": int(capacity * self._bulk_fraction),
        }
        snap["tenants"] = self.stats.tenants_snapshot()
        if self._tap is not None:
            # router-position mirror tap books (ISSUE 18): the smoke/soak
            # recompute the windows_built identity from this block
            snap["mirror"] = self._tap.counters()
        if self._gate_spool is not None:
            snap["gate"] = {
                "spool": self._gate_spool,
                "sigma": self._gate_sigma,
                "min_windows": self._gate_min_windows,
                "min_ess": self._gate_min_ess,
                "band": self._gate_band,
            }
        with self._events_lock:
            snap["events_total"] = self._events_total
            snap["events_tail"] = list(self._events)[-20:]
        if self._chaos is not None:
            snap["chaos_injections"] = self._chaos.injections_total
        # Event-loop I/O core counters (docs/serving.md): connection
        # census plus the attack-eviction/shed books — slowloris and
        # zero-window evictions, EMFILE accept sheds.
        snap["netio"] = self._loop.stats()
        return snap

    def _metrics_row(self) -> dict:
        """Numeric-only flat row (MetricsLogger contract)."""
        snap = self.stats.snapshot()
        for cls in ("interactive", "bulk"):
            sub = snap.pop(cls, None) or {}
            for k, v in sub.items():
                if v is not None:
                    snap[f"{cls}_{k}"] = v
        with self._lock:
            snap["admitted"] = sum(
                1 for r in self._replicas if r.admitted and not r.removed
            )
            snap["inflight"] = sum(
                r.inflight for r in self._replicas if not r.removed
            )
            snap["canary_active"] = float(any(
                ro.state != "idle" for ro in self._rollouts.values()
            ))
        return {
            k: float(v) for k, v in snap.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }

    def _metrics_loop(self) -> None:
        while not self._shutdown.wait(self._metrics_interval_s):
            self._metrics.log(self.stats.requests_total, self._metrics_row())


def _p99(lats) -> Optional[float]:
    if not lats:
        return None
    s = sorted(lats)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def _ms(v: Optional[float]):
    return None if v is None else round(v * 1e3, 4)


# --------------------------------------------------------------------- CLI
def parse_bundle_spec(spec: str):
    """One --backend-bundles entry: '' -> None, bare DIR -> default
    policy, 'name=dir+name2=dir2' -> multi-policy mapping."""
    if not spec:
        return None
    if "=" not in spec:
        return spec
    out = {}
    for part in spec.split("+"):
        name, sep, path = part.partition("=")
        if not sep or not name or not path:
            raise SystemExit(
                f"--backend-bundles spec wants name=dir[+name=dir...], "
                f"got {part!r}"
            )
        out[name] = path
    return out


def parse_quota(q: str):
    """'RPS[:BURST]' -> (rate, burst); burst defaults to 2×rate."""
    rate_s, _, burst_s = q.partition(":")
    try:
        rate = float(rate_s)
        burst = float(burst_s) if burst_s else 2.0 * rate
    except ValueError:
        raise SystemExit(f"quota wants RPS[:BURST], got {q!r}") from None
    if rate <= 0 or burst < 1:
        raise SystemExit(f"quota must have RPS > 0 and BURST >= 1, got {q!r}")
    return rate, burst


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.serve.router",
        description="Replicated serving front-end: least-loaded dispatch, "
                    "health-driven ejection, rolling canary rollout.",
    )
    p.add_argument("--backends", required=True,
                   help="comma-separated host:port of the serve/ replicas")
    p.add_argument("--backend-bundles", default=None,
                   help="comma-separated bundle-dir specs, 1:1 with "
                        "--backends (required for canary rollout: the "
                        "router rolls a replica forward by writing into "
                        "its bundle dir). Each spec is a bare DIR (the "
                        "default policy) or 'name=dir+name2=dir2' for a "
                        "multi-policy replica")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7430,
                   help="0 = ephemeral (printed on startup)")
    p.add_argument("--probe-interval", type=float, default=0.5,
                   help="seconds between healthz probe rounds")
    p.add_argument("--probe-timeout", type=float, default=2.0,
                   help="per-probe budget; past it the replica is ejected")
    p.add_argument("--readmit-after", type=int, default=2,
                   help="consecutive healthy probes before (re-)admission")
    p.add_argument("--dispatch-retries", type=int, default=1,
                   help="bounded re-dispatches on a different replica when "
                        "one sheds or dies mid-stream")
    p.add_argument("--stuck-after", type=float, default=30.0,
                   help="eject a replica whose in-flight dispatches stop "
                        "resolving for this many seconds even though its "
                        "healthz still answers ok (a wedged device thread); "
                        "ejection fails the stuck requests over. 0 disables")
    p.add_argument("--retry-seed", type=int, default=0)
    p.add_argument("--wait-replicas", type=int, default=None,
                   help="block startup until N replicas admitted "
                        "(default: all backends)")
    p.add_argument("--wait-timeout", type=float, default=120.0)
    p.add_argument("--canary-bundle", action="append", default=None,
                   metavar="[POLICY=]DIR",
                   help="bundle dir to watch for rollouts: each new "
                        "bundle.json mtime there starts a canary rollout. "
                        "Bare DIR rolls the default policy; POLICY=DIR "
                        "rolls that policy only (repeatable — one "
                        "independent rollout state machine per policy)")
    p.add_argument("--tenant-quota", action="append", default=[],
                   metavar="TENANT=RPS[:BURST]",
                   help="per-tenant token-bucket admission quota "
                        "(repeatable); requests past it shed OVERLOADED "
                        "'quota' before dispatch. BURST defaults to 2×RPS")
    p.add_argument("--default-quota", default=None, metavar="RPS[:BURST]",
                   help="quota applied to tenants without an explicit "
                        "--tenant-quota (unset = unlimited)")
    p.add_argument("--replica-capacity", type=int, default=0,
                   help="per-replica inflight capacity for the "
                        "class-aware shed: fleet capacity = admitted "
                        "replicas × this. Bulk requests shed past "
                        "--bulk-fraction of it, interactive past all of "
                        "it — bulk sheds FIRST under overload. 0 "
                        "disables the class tier (quotas still apply)")
    p.add_argument("--bulk-fraction", type=float, default=0.5,
                   help="fraction of fleet capacity the bulk class may "
                        "occupy before it sheds (the interactive-p99 "
                        "protection knob)")
    p.add_argument("--flood-burst", type=int, default=200,
                   help="synthetic request count per tenant_flood / "
                        "policy_skew chaos injection")
    p.add_argument("--canary-fraction", type=float, default=0.25,
                   help="deterministic request fraction routed to canary "
                        "replicas while observing")
    p.add_argument("--canary-window", type=int, default=256,
                   help="sliding comparison window per group (requests)")
    p.add_argument("--canary-min-samples", type=int, default=40,
                   help="per-group samples required before a verdict")
    p.add_argument("--canary-max-error-increase", type=float, default=0.05,
                   help="canary error rate above baseline+this rolls back")
    p.add_argument("--canary-p99-ratio", type=float, default=3.0,
                   help="canary p99 above baseline*this (+10ms) rolls back")
    p.add_argument("--canary-attest-timeout", type=float, default=30.0,
                   help="seconds a deployed replica gets to attest the new "
                        "bundle_mtime before the rollout rolls back")
    p.add_argument("--canary-observe-timeout", type=float, default=600.0,
                   help="seconds the observation windows get to reach "
                        "--canary-min-samples before the rollout rolls "
                        "back (too little traffic must not wedge a "
                        "rollout in 'observing' forever)")
    g = p.add_argument_group("flywheel (d4pg_tpu/flywheel)")
    g.add_argument("--mirror-fraction", type=float, default=0.0,
                   help="mirror tap at the ROUTER: fraction of served "
                        "episodes (per client connection, Bresenham-"
                        "striped) whose obs/action/reward traffic becomes "
                        "training windows; needs clients that echo reward "
                        "via FEEDBACK frames (flywheel/sim_client.py)")
    g.add_argument("--mirror-bundle", default=None, metavar="DIR",
                   help="bundle dir giving the tap its obs/action dims, "
                        "n-step/gamma, and generation tags (default: the "
                        "first --backend-bundles default-policy dir)")
    g.add_argument("--mirror-ingest", default=None, metavar="HOST:PORT",
                   help="fleet ingest to stream mirrored WINDOWS2 frames "
                        "to (the learner's --fleet-listen port)")
    g.add_argument("--mirror-spool", default=None, metavar="DIR",
                   help="on-disk spool of mirrored frames; also the "
                        "default --gate-spool")
    g.add_argument("--gate-spool", default=None, metavar="DIR",
                   help="arm the off-policy promotion gate on this mirror "
                        "spool: a canary additionally needs its "
                        "importance-weighted return estimate over the "
                        "mirrored windows to clear the gate before it "
                        "promotes (defaults to --mirror-spool when set)")
    g.add_argument("--gate-sigma", type=float, default=0.3,
                   help="exploration σ the behavior propensities were "
                        "logged under (must match the clients' "
                        "--noise-sigma); the candidate is scored as "
                        "N(μ_cand(s), σ²)")
    g.add_argument("--gate-min-windows", type=int, default=16,
                   help="mirrored windows required for a verdict: a "
                        "starved gate refuses, it never guesses")
    g.add_argument("--gate-min-ess", type=float, default=4.0,
                   help="minimum effective sample size: below it the "
                        "candidate is too far off the serving "
                        "distribution to estimate, and is refused")
    g.add_argument("--gate-band", type=float, default=1.0,
                   help="tolerated estimated-return shortfall vs the "
                        "behavior policy before the gate refuses")
    g.add_argument("--gate-max-windows", type=int, default=512,
                   help="newest spool windows the gate evaluates over")
    p.add_argument("--log-dir", default=None,
                   help="append router metrics rows (metrics.jsonl) here")
    p.add_argument("--metrics-interval", type=float, default=30.0)
    p.add_argument("--io-read-stall-s", type=float,
                   default=netio.loop.DEFAULT_READ_STALL_S,
                   help="event loop: evict a connection whose partial "
                        "frame makes no completion progress for this long "
                        "(the slowloris bound)")
    p.add_argument("--io-write-stall-s", type=float,
                   default=netio.loop.DEFAULT_WRITE_STALL_S,
                   help="event loop: evict a connection that drains none "
                        "of its buffered replies for this long (the "
                        "zero-window bound)")
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="deterministic fault injection (d4pg_tpu/chaos.py): "
                        "replica_kill@N / replica_slow@N:ms / "
                        "canary_corrupt@N / tenant_flood@N:tenant / "
                        "policy_skew@N / mirror_drop@N / gate_stall@N:s / "
                        "slowloris@N:bps / zero_window@N:ms / "
                        "fd_exhaust@N:ms "
                        "(scaledown_during_canary@N ticks in the "
                        "autoscaler)")
    p.add_argument("--debug-guards", action="store_true",
                   help="arm the runtime witnesses (lock-order, flow "
                        "conservation): drain checks the recorded lock "
                        "nesting and the admission/gate/tenant accounting "
                        "identities, raising on any imbalance")
    g = p.add_argument_group("autoscaler (serve/autoscaler.py)")
    g.add_argument("--autoscale", action="store_true",
                   help="run the healthz-driven autoscaler in-process: "
                        "spawn/drain replicas via scripts/spawnlib.py "
                        "between --autoscale-min and --autoscale-max")
    g.add_argument("--autoscale-bundle", default=None,
                   help="source bundle dir for spawned replicas (each "
                        "spawn gets its OWN copy under "
                        "--autoscale-workdir; default: the first "
                        "--backend-bundles default-policy dir)")
    g.add_argument("--autoscale-workdir", default=None,
                   help="where spawned replicas' bundle copies and "
                        "the pool bookkeeping live (default: a mkdtemp)")
    g.add_argument("--autoscale-min", type=int, default=1)
    g.add_argument("--autoscale-max", type=int, default=4)
    g.add_argument("--autoscale-interval", type=float, default=2.0,
                   help="seconds between control samples")
    g.add_argument("--autoscale-samples", type=int, default=3,
                   help="CONSECUTIVE breaching samples before any action "
                        "(never scale on one sample)")
    g.add_argument("--autoscale-cooldown", type=float, default=30.0,
                   help="hold after any action: new capacity needs warmup "
                        "+ K-probe admission before its effect is "
                        "measurable")
    g.add_argument("--autoscale-up-load", type=float, default=0.8,
                   help="inflight/capacity above this breaches toward "
                        "scale-up")
    g.add_argument("--autoscale-down-load", type=float, default=0.3,
                   help="inflight/capacity below this breaches toward "
                        "scale-down (hysteresis: well under the up "
                        "threshold)")
    g.add_argument("--autoscale-p99-slo", type=float, default=None,
                   help="interactive-tier p99 SLO in ms: violating it "
                        "breaches toward scale-up regardless of load")
    g.add_argument("--autoscale-shed", type=float, default=0.05,
                   help="shed rate (since last sample) above this "
                        "breaches toward scale-up")
    g.add_argument("--replica-args", default="",
                   help="extra args for spawned serve replicas, e.g. "
                        "'--max-batch 8 --max-wait-us 500'")
    return p


def _load_spawnlib():
    """Import ``scripts/spawnlib.py`` (the shared CLI subprocess harness)
    from the repo checkout this package runs out of."""
    from d4pg_tpu.utils.procs import load_spawnlib

    try:
        return load_spawnlib()
    except RuntimeError as e:
        raise SystemExit(f"--autoscale: {e}")


def main(argv=None) -> None:
    import sys

    from d4pg_tpu.utils.signals import install_graceful_signals

    args = build_parser().parse_args(argv)
    if args.debug_guards:
        # BEFORE the Router/tap build their locks (named_lock wraps only
        # while enabled); drain() then checks nesting + identities.
        lockwitness.enable()
        flowledger.enable()
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    bundles = None
    if args.backend_bundles:
        bundles = [
            parse_bundle_spec(b.strip())
            for b in args.backend_bundles.split(",")
        ]
    canary = None
    if args.canary_bundle:
        canary = {}
        for spec in args.canary_bundle:
            name, sep, path = spec.partition("=")
            pol, src = (name, path) if sep and path else (
                protocol.DEFAULT_POLICY, spec
            )
            if pol in canary:
                raise SystemExit(f"--canary-bundle for {pol!r} given twice")
            canary[pol] = src
    quotas = {}
    for spec in args.tenant_quota:
        name, sep, q = spec.partition("=")
        if not sep or not name:
            raise SystemExit(f"--tenant-quota wants TENANT=RPS[:BURST], got {spec!r}")
        quotas[name] = parse_quota(q)
    chaos = None
    if args.chaos:
        from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

        chaos = ChaosInjector(ChaosPlan.parse(args.chaos))
    tap = None
    if args.mirror_fraction > 0:
        from d4pg_tpu.fleet.policy import load_numpy_policy
        from d4pg_tpu.flywheel.spool import MirrorSpool
        from d4pg_tpu.flywheel.tap import MirrorTap

        mirror_bundle = args.mirror_bundle
        if mirror_bundle is None:
            for b in bundles or []:
                if isinstance(b, str):
                    mirror_bundle = b
                    break
                if isinstance(b, dict) and protocol.DEFAULT_POLICY in b:
                    mirror_bundle = b[protocol.DEFAULT_POLICY]
                    break
        if mirror_bundle is None:
            raise SystemExit(
                "--mirror-fraction needs --mirror-bundle (or a "
                "--backend-bundles default-policy dir) for the tap's "
                "dims, n-step/gamma, and generation tags"
            )
        np_pol = load_numpy_policy(mirror_bundle)
        ingest_addr = None
        if args.mirror_ingest:
            ih, _, ip = args.mirror_ingest.rpartition(":")
            ingest_addr = (ih, int(ip))
        spool = MirrorSpool(args.mirror_spool) if args.mirror_spool else None
        tap = MirrorTap(
            obs_dim=np_pol.obs_dim,
            action_dim=np_pol.action_dim,
            n_step=np_pol.n_step,
            gamma=np_pol.gamma,
            fraction=args.mirror_fraction,
            ingest_addr=ingest_addr,
            spool=spool,
            bundle_dir=mirror_bundle,
            env="router",
            tap_id="mirror-router",
            chaos=chaos,
        )
    gate_spool = args.gate_spool or args.mirror_spool
    router = Router(
        backends,
        host=args.host,
        port=args.port,
        bundle_dirs=bundles,
        tenant_quotas=quotas or None,
        default_quota=(
            parse_quota(args.default_quota) if args.default_quota else None
        ),
        replica_capacity=args.replica_capacity,
        bulk_fraction=args.bulk_fraction,
        flood_burst=args.flood_burst,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        readmit_after=args.readmit_after,
        dispatch_retries=args.dispatch_retries,
        stuck_after_s=args.stuck_after,
        retry_seed=args.retry_seed,
        canary_bundle=canary,
        canary_fraction=args.canary_fraction,
        canary_window=args.canary_window,
        canary_min_samples=args.canary_min_samples,
        canary_max_err_increase=args.canary_max_error_increase,
        canary_p99_ratio=args.canary_p99_ratio,
        canary_attest_timeout_s=args.canary_attest_timeout,
        canary_observe_timeout_s=args.canary_observe_timeout,
        mirror_tap=tap,
        gate_spool=gate_spool,
        gate_sigma=args.gate_sigma,
        gate_min_windows=args.gate_min_windows,
        gate_min_ess=args.gate_min_ess,
        gate_band=args.gate_band,
        gate_max_windows=args.gate_max_windows,
        log_dir=args.log_dir,
        metrics_interval_s=args.metrics_interval,
        chaos=chaos,
        io_read_stall_s=args.io_read_stall_s,
        io_write_stall_s=args.io_write_stall_s,
    )
    install_graceful_signals(
        router.request_shutdown,
        "[router] {sig}: draining (second signal hard-kills)",
    )
    router.start()
    print(
        f"[router] listening on {router.host}:{router.port} "
        f"backends={','.join(backends)}",
        flush=True,
    )
    want = args.wait_replicas if args.wait_replicas is not None else len(backends)
    if want:
        admitted = router.wait_for_replicas(want, timeout_s=args.wait_timeout)
        print(f"[router] admitted {admitted}/{len(backends)} replicas",
              flush=True)
    scaler = pool = None
    if args.autoscale:
        import shlex
        import tempfile as _tempfile

        from d4pg_tpu.serve.autoscaler import (
            Autoscaler,
            RouterReplicaPool,
            ServingSignalSource,
        )

        src = args.autoscale_bundle
        if src is None:
            for b in bundles or []:
                if isinstance(b, str):
                    src = b
                    break
                if isinstance(b, dict) and protocol.DEFAULT_POLICY in b:
                    src = b[protocol.DEFAULT_POLICY]
                    break
        if src is None:
            raise SystemExit(
                "--autoscale needs --autoscale-bundle (or a "
                "--backend-bundles default-policy dir to clone)"
            )
        workdir = args.autoscale_workdir or _tempfile.mkdtemp(
            prefix="d4pg-autoscale-"
        )
        pool = RouterReplicaPool(
            router, src, workdir, _load_spawnlib().spawn,
            replica_args=shlex.split(args.replica_args),
        )
        scaler = Autoscaler(
            ServingSignalSource(router.healthz),
            pool.scale_up,
            pool.scale_down,
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max,
            interval_s=args.autoscale_interval,
            up_load=args.autoscale_up_load,
            down_load=args.autoscale_down_load,
            p99_slo_ms=args.autoscale_p99_slo,
            shed_threshold=args.autoscale_shed,
            samples=args.autoscale_samples,
            cooldown_s=args.autoscale_cooldown,
            chaos=chaos,
            on_event=lambda kind, **f: router._record_event(kind, **f),
        )
        scaler.start()
        print(
            f"[router] autoscaler on: {args.autoscale_min}.."
            f"{args.autoscale_max} replicas, bundle={src}",
            flush=True,
        )
    router.serve_until_shutdown()
    if tap is not None:
        # after the router's drain: every connection is closed, so the
        # mirror books are final
        tap.close()
        mc = tap.counters()
        print(
            "[router] mirror: "
            + " ".join(f"{k}={mc[k]}" for k in sorted(mc)),
            flush=True,
        )
    if scaler is not None:
        scaler.close()
        print(f"[router] autoscaler: {scaler.snapshot()}", flush=True)
    if pool is not None:
        pool.close()
    snap = router.healthz()
    print(
        f"[router] drained: {snap['replies_ok']} ok, "
        f"{snap['replies_overloaded']} overloaded, "
        f"{snap['replies_error']} failed, "
        f"retries={snap['retries']} ejections={snap['ejections']} "
        f"p99={snap.get('p99_ms')} ms",
        flush=True,
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
