"""Replicated serving front-end: least-loaded dispatch, health-driven
ejection, rolling canary rollout with auto-rollback.

One ``serve/`` process saturates one device thread (1,437 rps on the
committed artifact); production traffic needs the tier above it. This
module is that tier — the serving-side mirror of the collection fleet
(``d4pg_tpu/fleet``): a stdlib front-end speaking the SAME length-prefixed
frame protocol on both sides, dispatching each request to the least-loaded
of M backend replicas.

Three jobs:

- **Dispatch** — per-replica inflight accounting (the router's own gauge,
  not a healthz round-trip per request); least-loaded admitted replica
  wins, ties broken by index. A replica that sheds (``OVERLOADED``) or
  dies mid-stream (``ConnectionClosed`` — its pipelined dispatch link
  sweeps every in-flight future) triggers ONE bounded re-dispatch on a
  different replica under a seeded :class:`~d4pg_tpu.utils.retry.Backoff`
  budget; only when every replica is ejected does the router itself
  answer ``OVERLOADED(no_replicas)``. The accounting identity the chaos
  soak pins: every request is answered ok, answered OVERLOADED, or
  failed-after-bounded-retry — never silently lost.

- **Health-driven ejection** — a prober thread polls each replica's
  healthz (``protocol.probe_healthz``, one-shot so a dead backend cannot
  wedge it). ``degraded`` / ``draining`` / timeout / connect-failure
  ejects the replica (its dispatch link is closed, failing its in-flight
  requests over to survivors); re-admission takes K CONSECUTIVE healthy
  probes (``readmit_after``) — one lucky probe must not flap a sick
  replica back in.

- **Rolling canary rollout** — ``--canary-bundle`` names a bundle
  directory the router watches (its ``bundle.json`` mtime is the version
  vector, exactly the attestation the exporter's params-first/json-second
  write ordering provides). A new version deploys onto a deterministic
  subset of replicas (the canaries), then ``--canary-fraction`` of
  requests (a deterministic counter fraction, not RNG) routes to them
  while the router compares canary vs baseline reply-error rate and p99
  over sliding windows. Better-or-equal → auto-promote (roll the
  remaining replicas forward one at a time, each attested via healthz
  ``bundle_mtime`` before the next). Worse — or a canary that fails to
  load / gets ejected — → auto-rollback: restore the saved old bundle
  and RE-EJECT the canaries until their healthz attests the old version
  again. Every decision is a structured ``[router-event]`` JSON line.

The router is a HOST-ONLY module (d4pglint manifest): it moves bytes and
stats files, never tensors — the one numpy touch is decoding the obs to
re-encode it for the backend link. Deliberately no JAX import anywhere
near it: M replicas own the devices; the router must restart in
milliseconds.

Run it::

    python -m d4pg_tpu.serve.router --backends 127.0.0.1:7431,127.0.0.1:7432 \\
        --backend-bundles runs/p1/bundle_a,runs/p1/bundle_b \\
        --canary-bundle runs/p1/canary --canary-fraction 0.25

docs/serving.md ("Replication & rollout") has the dispatch rules, the
ejection/re-admission state machine, and the canary decision table.
"""

from __future__ import annotations

import errno
import json
import os
import random
import shutil
import signal
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Optional

from d4pg_tpu.serve import protocol
from d4pg_tpu.serve.client import ConnectionClosed, Overloaded, PolicyClient
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.serve.stats import LatencyReservoir
from d4pg_tpu.utils.retry import Backoff
from d4pg_tpu.analysis import lockwitness

# Bundle file names, duplicated from serve/bundle.py ON PURPOSE: that
# module imports the agent config (and with it JAX) at module top, and the
# router is a host-only process that must never pay — or crash on — a JAX
# import. The names are a stable on-disk contract (docs/serving.md).
_PARAMS_FILE = "actor_params.npz"
_META_FILE = "bundle.json"


def _bundle_json_mtime(bundle_dir: str) -> Optional[float]:
    try:
        return os.stat(os.path.join(bundle_dir, _META_FILE)).st_mtime
    except (OSError, TypeError):
        return None


class RouterStats:
    """Router-level counters + client-observed latency. One lock, O(1)
    per request; the identity surface is replies_ok + replies_overloaded
    + replies_error == answered requests."""

    def __init__(self):
        self._lock = lockwitness.named_lock("RouterStats._lock")
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.replies_ok = 0
        self.replies_overloaded = 0
        self.replies_error = 0
        self.retries = 0
        self.ejections = 0
        self.admissions = 0
        self.dropped_replies = 0
        self.protocol_errors = 0
        self.canary_rollbacks = 0
        self.canary_promotions = 0
        self.latency = LatencyReservoir()

    def inc(self, field: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "requests_total": self.requests_total,
                "replies_ok": self.replies_ok,
                "replies_overloaded": self.replies_overloaded,
                "replies_error": self.replies_error,
                "retries": self.retries,
                "ejections": self.ejections,
                "admissions": self.admissions,
                "dropped_replies": self.dropped_replies,
                "protocol_errors": self.protocol_errors,
                "canary_rollbacks": self.canary_rollbacks,
                "canary_promotions": self.canary_promotions,
            }
        out["answered_total"] = (
            out["replies_ok"] + out["replies_overloaded"] + out["replies_error"]
        )
        out.update(self.latency.percentiles_ms())
        return out


class Replica:
    """Router-side bookkeeping for one backend ``serve/`` process.

    No threads of its own and no locks: every mutable field is guarded by
    the ROUTER's lock — dispatch picks, inflight accounting, and ejection
    flips must be mutually consistent, and a per-replica lock would just
    invite ordering bugs between two.
    """

    def __init__(self, index: int, host: str, port: int,
                 bundle_dir: Optional[str] = None):
        self.index = index
        self.host = host
        self.port = port
        self.bundle_dir = bundle_dir      # None = canary cannot target it
        self.client: Optional[PolicyClient] = None  # dispatch link
        self.inflight = 0                 # router-side, not healthz
        self.admitted = False
        self.ejected_reason: Optional[str] = "startup"
        self.healthy_streak = 0
        self.health: dict = {}            # last successful probe snapshot
        self.pid: Optional[int] = None
        self.bundle_mtime: Optional[float] = None
        self.canary = False
        self.ok = 0                       # lifetime final outcomes served
        self.errors = 0
        # Dispatch-progress watermark: refreshed when inflight leaves 0 at
        # a pick and on EVERY future resolution. While inflight > 0 a
        # stale watermark means nothing is coming back — the stuck-replica
        # signal healthz can't carry (a wedged device thread still answers
        # healthz "ok").
        self.last_progress = time.monotonic()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class Router:
    """The replicated front-end. ``start()`` binds and spawns the accept /
    control threads; ``drain()`` is the graceful stop (answer in-flight,
    shed new with ``draining``)."""

    # d4pglint shared-mutable-state: written by exactly one thread each,
    # read as atomic snapshots —
    #   _canary_* cursor fields: control thread only (the state machine
    #   runs there); _canary_state itself is written under _lock because
    #   _pick routes on it;
    #   _rollback_dir/_backed_up: control thread only (file staging);
    #   _obs_dim is also written under _lock (prober) after the first
    #   successful probe and only ever goes None -> int.
    _THREAD_SAFE = (
        "_canary_seen_mtime", "_canary_version", "_canary_deadline",
        "_rollback_deadline", "_deploys", "_promote_done",
        "_rollback_dir", "_backed_up",
    )
    # d4pglint thread-lifecycle: per-connection reader threads are not
    # joined — drain() closes every socket in _conns, which unblocks the
    # blocking read_frame immediately (same contract as PolicyServer).
    _DETACHED_THREADS = ("router-conn",)

    def __init__(
        self,
        backends,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        bundle_dirs=None,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        readmit_after: int = 2,
        dispatch_retries: int = 1,
        dispatch_timeout_s: float = 10.0,
        stuck_after_s: float = 30.0,
        retry_seed: int = 0,
        canary_bundle: Optional[str] = None,
        canary_fraction: float = 0.25,
        canary_window: int = 256,
        canary_min_samples: int = 40,
        canary_max_err_increase: float = 0.05,
        canary_p99_ratio: float = 3.0,
        canary_attest_timeout_s: float = 30.0,
        canary_observe_timeout_s: float = 600.0,
        log_dir: Optional[str] = None,
        metrics_interval_s: float = 30.0,
        chaos=None,
    ):
        if not backends:
            raise ValueError("router needs at least one backend replica")
        bundle_dirs = list(bundle_dirs) if bundle_dirs else [None] * len(backends)
        if len(bundle_dirs) != len(backends):
            raise ValueError(
                f"{len(backends)} backends but {len(bundle_dirs)} bundle "
                "dirs — the canary controller needs a 1:1 mapping"
            )
        self._replicas = []
        for i, spec in enumerate(backends):
            if isinstance(spec, (tuple, list)):
                h, p = spec
            else:
                h, _, p = str(spec).rpartition(":")
            self._replicas.append(Replica(i, h or "127.0.0.1", int(p),
                                          bundle_dirs[i]))
        if canary_bundle is not None and not any(
            r.bundle_dir for r in self._replicas
        ):
            raise ValueError(
                "--canary-bundle needs --backend-bundles: the router rolls "
                "a replica forward by writing into ITS bundle directory"
            )
        self.host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self.stats = RouterStats()
        # Witnessed under --debug-guards (static node ids, see lockwitness)
        self._lock = lockwitness.named_lock("Router._lock")
        self._seq = 0
        self._obs_dim: Optional[int] = None

        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._readmit_after = int(readmit_after)
        self._dispatch_retries = int(dispatch_retries)
        self._dispatch_timeout_s = float(dispatch_timeout_s)
        self._stuck_after_s = float(stuck_after_s)
        # Seeded: the failover Backoff budget and its jitter replay
        # deterministically under --chaos, like every retry in this repo.
        self._retry_rng = random.Random(retry_seed)

        # ---- canary rollout state machine (control thread) ----
        self._canary_dir = canary_bundle
        self._canary_permille = int(round(float(canary_fraction) * 1000))
        if canary_bundle is not None and not (
            0 < self._canary_permille < 1000
        ):
            raise ValueError(
                "--canary-fraction must be strictly between 0 and 1: the "
                "verdict compares a canary window AGAINST a baseline "
                "window, so both groups must receive traffic (0 routes "
                "nothing to the canary, 1 starves the baseline — either "
                "way the rollout would observe forever)"
            )
        self._canary_state = "idle"   # idle|deploying|observing|promoting|rolling_back
        self._canary_seen_mtime: Optional[float] = None
        self._canary_version: Optional[float] = None
        self._canary_deadline: Optional[float] = None
        self._rollback_deadline: Optional[float] = None
        self._attest_timeout_s = float(canary_attest_timeout_s)
        self._observe_timeout_s = float(canary_observe_timeout_s)
        self._min_samples = int(canary_min_samples)
        self._max_err_increase = float(canary_max_err_increase)
        self._p99_ratio = float(canary_p99_ratio)
        self._deploys: dict = {}        # replica index -> awaited json mtime
        self._promote_done: set = set()
        self._rollback_dir: Optional[str] = None
        self._backed_up: set = set()
        # replica index -> bundle_mtime it must attest before probes count
        # as healthy again (the re-eject-until-old-bundle rollback contract)
        self._readmit_gate: dict = {}
        self._windows = {
            "baseline": deque(maxlen=int(canary_window)),
            "canary": deque(maxlen=int(canary_window)),
        }

        self._events: deque = deque(maxlen=1000)
        self._events_total = 0
        self._events_lock = lockwitness.named_lock("Router._events_lock")

        self._chaos = chaos
        self._log_dir = log_dir
        self._metrics_interval_s = metrics_interval_s
        self._metrics = None

        self._listen_sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._control_thread: Optional[threading.Thread] = None
        self._metrics_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = lockwitness.named_lock("Router._conns_lock")
        self._shutdown = threading.Event()
        self._started = False

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._started:
            raise RuntimeError("router already started")
        self._started = True
        self._listen_sock = socket.create_server(
            (self.host, self._requested_port)
        )
        self.port = self._listen_sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True
        )
        self._accept_thread.start()
        self._control_thread = threading.Thread(
            target=self._control_loop, name="router-control", daemon=True
        )
        self._control_thread.start()
        if self._log_dir:
            from d4pg_tpu.runtime.metrics import MetricsLogger

            self._metrics = MetricsLogger(self._log_dir, use_tensorboard=False)
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="router-metrics", daemon=True
            )
            self._metrics_thread.start()

    def wait_for_replicas(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until ``n`` replicas are admitted (bounded, monotonic).
        Returns the admitted count; raises ``TimeoutError`` when the fleet
        never materializes — a router fronting zero replicas should fail
        its orchestrator's readiness check loudly, not serve OVERLOADED."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                admitted = sum(1 for r in self._replicas if r.admitted)
            if admitted >= n:
                return admitted
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"only {admitted}/{n} replicas admitted after {timeout_s}s"
                )
            time.sleep(0.05)

    def request_shutdown(self) -> None:
        """Signal-handler-safe: set the event; drain happens on the waiter."""
        self._shutdown.set()

    def serve_until_shutdown(self) -> None:
        # Park-until-signal is the design (same contract as PolicyServer).
        self._shutdown.wait()  # d4pglint: disable=thread-lifecycle  -- blocking forever is the serve loop
        self.drain()

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful stop: no new connections, shed new requests with
        ``draining``, let every in-flight dispatch come back, tear down."""
        self._shutdown.set()
        if self._listen_sock is not None:
            try:
                self._listen_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:  # wake a stack where shutdown() on a listener is a no-op
                with socket.create_connection((self.host, self.port), timeout=1):
                    pass
            except OSError:
                pass
            try:
                self._listen_sock.close()
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                inflight = sum(r.inflight for r in self._replicas)
            if inflight == 0:
                break
            time.sleep(0.05)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        if self._control_thread is not None:
            self._control_thread.join(timeout=self._probe_interval_s + 10)
        with self._lock:
            clients = [r.client for r in self._replicas if r.client is not None]
            for r in self._replicas:
                r.client = None
                r.admitted = False
                r.ejected_reason = "router draining"
        for c in clients:
            try:
                c.close()
            except OSError:
                pass
        if self._metrics_thread is not None:
            self._metrics_thread.join(timeout=self._metrics_interval_s + 5)
        if self._metrics is not None:
            self._metrics.log(self.stats.requests_total, self._metrics_row())
            self._metrics.close()
            self._metrics = None
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # ------------------------------------------------------------ event log
    def _record_event(self, kind: str, **fields) -> None:
        """Structured decision log: one JSON line per event on stdout
        (greppable by the soak) + a bounded in-memory tail for healthz."""
        event = {"event": kind, "t": round(time.monotonic(), 3), **fields}
        with self._events_lock:
            self._events.append(event)
            self._events_total += 1
        print(f"[router-event] {json.dumps(event, sort_keys=True)}", flush=True)

    # ------------------------------------------------------- control thread
    def _control_loop(self) -> None:
        """Probe → eject/re-admit → canary step, every probe interval.
        ONE control thread on purpose: ejection flips and rollout
        transitions observe each other, and two timers would race."""
        while not self._shutdown.is_set():
            try:
                self._probe_all()
                self._canary_step()
            except Exception as e:  # control must never die silently
                print(f"[router] control loop error: {e!r}", flush=True)
                self._record_event("control_error", error=repr(e))
            if self._shutdown.wait(self._probe_interval_s):
                return

    def _probe_all(self) -> None:
        # Probes run CONCURRENTLY: sequentially, every unreachable replica
        # would stall the whole control loop by its full connect timeout
        # per round (M-1 dead backends → the survivor's ejection and the
        # canary attestation deadlines slip by seconds while the
        # wall-parallel monotonic deadlines keep ticking). Each probe is a
        # self-contained one-shot socket, so a thread per replica per
        # round is safe; a wedged probe past the join bound is treated as
        # failed and its daemon thread dies with its socket timeout.
        results: list = [None] * len(self._replicas)

        def probe_one(i: int, r: Replica) -> None:
            try:
                results[i] = (protocol.probe_healthz(
                    r.host, r.port, timeout_s=self._probe_timeout_s
                ), None)
            except (OSError, ProtocolError) as e:
                results[i] = (None, e)

        threads = [
            threading.Thread(
                target=probe_one, args=(i, r),
                name=f"router-probe-{i}", daemon=True,
            )
            for i, r in enumerate(self._replicas)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self._probe_timeout_s + 2.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        for r, res in zip(self._replicas, results):
            if res is None:
                res = (None, TimeoutError("probe thread did not finish"))
            self._apply_probe(r, res[0], res[1])
        self._check_stuck()

    def _check_stuck(self) -> None:
        """Eject a replica whose dispatches stopped resolving. A backend
        with a wedged device thread still answers healthz ``ok`` (status
        only reflects drain/reload state), so the prober alone would keep
        it admitted while its unanswered futures break the accounting
        identity and its leaked inflight biases least-loaded dispatch.
        Closing the dispatch link fails every in-flight future with
        ``ConnectionClosed`` — the normal bounded-failover trigger — so
        stuck requests are rescued onto other replicas, not abandoned."""
        if not self._stuck_after_s:
            return
        now = time.monotonic()
        to_close, ejected = [], []
        with self._lock:
            for r in self._replicas:
                if (
                    r.admitted and r.inflight > 0
                    and now - r.last_progress > self._stuck_after_s
                ):
                    to_close.append(self._eject_locked(
                        r, f"stuck: no dispatch resolved in "
                           f"{self._stuck_after_s:g}s"
                    ))
                    ejected.append(r)
        for c in to_close:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        for r in ejected:
            self._record_event("eject", replica=r.index, addr=r.addr,
                               reason="stuck")

    def _apply_probe(self, r: Replica, h: Optional[dict], err) -> None:
        to_close = None
        eject_reason = None
        dial = False
        with self._lock:
            if h is not None:
                r.health = h
                r.pid = h.get("pid")
                r.bundle_mtime = h.get("bundle_mtime")
                if self._obs_dim is None and h.get("obs_dim"):
                    self._obs_dim = int(h["obs_dim"])
            if h is None or h.get("status") != "ok":
                r.healthy_streak = 0
                if r.admitted:
                    eject_reason = (
                        f"probe failed: {err!r}" if err is not None
                        else f"status: {h.get('status')}"
                    )
                    to_close = self._eject_locked(r, eject_reason)
            else:
                gate = self._readmit_gate.get(r.index)
                if gate is not None and r.bundle_mtime != gate:
                    # rolled-back canary: healthy probes do not count until
                    # it attests the RESTORED bundle version
                    r.healthy_streak = 0
                else:
                    if gate is not None:
                        del self._readmit_gate[r.index]
                    r.healthy_streak += 1
                    if (
                        not r.admitted
                        and r.healthy_streak >= self._readmit_after
                    ):
                        dial = True
        if to_close is not None:
            try:
                to_close.close()
            except OSError:
                pass
        if eject_reason is not None:
            self._record_event("eject", replica=r.index, addr=r.addr,
                               reason=eject_reason)
        if dial:
            self._admit(r)

    def _eject_locked(self, r: Replica, reason: str):
        """Caller holds ``self._lock``. Returns the dispatch link to close
        OUTSIDE the lock. Closing it fails every in-flight request on this
        replica with ``ConnectionClosed`` — which is exactly the bounded
        failover trigger, so ejection actively rescues in-flight work from
        a sick replica instead of letting it ride out a timeout."""
        r.admitted = False
        r.ejected_reason = reason
        r.healthy_streak = 0
        client, r.client = r.client, None
        self.stats.inc("ejections")
        return client

    def _admit(self, r: Replica) -> None:
        """Dial the dispatch link OUTSIDE the lock, then publish. The link
        is a pipelined PolicyClient at retries=0: the router's recovery is
        failover to a DIFFERENT replica, never a hammer on the same one."""
        try:
            client = PolicyClient(
                r.host, r.port, timeout=self._dispatch_timeout_s
            )
        except OSError as e:
            with self._lock:
                r.healthy_streak = 0
            self._record_event("admit_failed", replica=r.index, addr=r.addr,
                               error=str(e))
            return
        stale = None
        with self._lock:
            if r.admitted or self._shutdown.is_set():
                stale = client
            else:
                r.client = client
                r.admitted = True
                r.ejected_reason = None
                r.last_progress = time.monotonic()
        if stale is not None:
            try:
                stale.close()
            except OSError:
                pass
            return
        self.stats.inc("admissions")
        self._record_event("admit", replica=r.index, addr=r.addr,
                           streak=r.healthy_streak)

    # -------------------------------------------------------------- dispatch
    def _pick(self, exclude):
        """Least-loaded admitted replica (ties → lowest index), honoring
        the deterministic canary traffic split while a rollout is
        observing. Returns ``(replica, client)`` or ``(None, None)`` —
        the all-ejected case the router answers OVERLOADED itself."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            pool = [
                r for r in self._replicas
                if r.admitted and r.client is not None
                and r.index not in exclude
            ]
            if not pool:
                return None, None
            if self._canary_state == "observing" and self._canary_permille:
                # Bresenham-style striping: request i is canary iff
                # (i·permille) mod 1000 < permille — the fraction is exact
                # over any 1000-request window AND interleaved, so both
                # comparison windows fill together (seq%1000 < permille
                # would send a contiguous block of 1000·fraction requests
                # to the canary first, starving the baseline window).
                want_canary = (
                    seq * self._canary_permille
                ) % 1000 < self._canary_permille
                group = [r for r in pool if r.canary == want_canary] or pool
            else:
                group = [r for r in pool if not r.canary] or pool
            # least-loaded wins; ties rotate with the dispatch counter so
            # sequential (inflight-0) traffic round-robins instead of
            # pinning the lowest index
            n = len(self._replicas)
            best = group[0]
            best_key = (best.inflight, (best.index - seq) % n)
            for r in group[1:]:
                key = (r.inflight, (r.index - seq) % n)
                if key < best_key:
                    best, best_key = r, key
            if best.inflight == 0:
                # arm the stuck watermark: from idle, the clock starts at
                # this dispatch (while inflight stays >0 only resolutions
                # refresh it — see _check_stuck)
                best.last_progress = time.monotonic()
            best.inflight += 1
            return best, best.client

    def _route(self, obs, deadline_us: int, req_id: int, reply) -> None:
        """Dispatch one decoded request; ``reply`` is the per-connection
        frame writer. Exactly one reply per request, on every path — the
        accounting identity depends on it."""
        t0 = time.perf_counter()
        deadline_ms = deadline_us / 1e3 if deadline_us else None
        state = {"backoff": None, "exclude": []}

        def attempt():
            remaining_ms = None
            if deadline_ms is not None:
                # the client's deadline is a budget for the whole request,
                # not per attempt: a failover re-dispatch gets what's LEFT
                # (a first replica that burned the budget before shedding
                # must yield an honest OVERLOADED, not a reply at 2x the
                # declared deadline)
                remaining_ms = (
                    deadline_ms - (time.perf_counter() - t0) * 1e3
                )
                if remaining_ms <= 0:
                    self.stats.inc("replies_overloaded")
                    reply(protocol.OVERLOADED, req_id, b"deadline")
                    return
            replica, client = self._pick(state["exclude"])
            if replica is None:
                self.stats.inc("replies_overloaded")
                reply(protocol.OVERLOADED, req_id, b"no_replicas")
                return
            kill_pid = None
            if self._chaos is not None:
                e = self._chaos.tick("replica_kill")
                if e is not None:
                    kill_pid = replica.pid
            fut = client.act_async(obs, remaining_ms)
            if kill_pid:
                # AFTER the send: the request is on the wire — this is the
                # mid-stream replica death the failover contract covers.
                try:
                    os.kill(int(kill_pid), signal.SIGKILL)
                except (OSError, ValueError) as e:
                    print(f"[router] chaos replica_kill failed: {e}",
                          flush=True)

            def done(f, replica=replica):
                with self._lock:
                    replica.inflight -= 1
                    replica.last_progress = time.monotonic()
                exc = f.exception()
                lat = time.perf_counter() - t0
                if exc is None:
                    with self._lock:
                        replica.ok += 1
                        self._windows[
                            "canary" if replica.canary else "baseline"
                        ].append((True, lat))
                    self.stats.inc("replies_ok")
                    self.stats.latency.add(lat)
                    reply(protocol.ACT_OK, req_id,
                          # inside f's own done-callback: resolved by
                          # definition, result() cannot block
                          protocol.encode_action(f.result()))  # d4pglint: disable=thread-lifecycle  -- done-callback, future resolved
                    return
                if isinstance(exc, (Overloaded, ConnectionClosed)):
                    bo = state["backoff"]
                    if bo is None:
                        # base_s=0: with another replica available the
                        # failover is immediate; the Backoff's job here is
                        # the bounded ATTEMPT budget (and determinism under
                        # --chaos via the seeded rng).
                        bo = state["backoff"] = Backoff(
                            base_s=0.0, jitter=0.0,
                            max_attempts=self._dispatch_retries,
                            rng=self._retry_rng,
                        )
                    delay = bo.next_delay()
                    if delay is not None:
                        state["exclude"].append(replica.index)
                        self.stats.inc("retries")
                        if delay:
                            time.sleep(delay)
                        attempt()
                        return
                with self._lock:
                    replica.errors += 1
                    if not isinstance(exc, Overloaded):
                        self._windows[
                            "canary" if replica.canary else "baseline"
                        ].append((False, lat))
                if isinstance(exc, Overloaded):
                    self.stats.inc("replies_overloaded")
                    reply(protocol.OVERLOADED, req_id,
                          str(exc).encode() or b"overloaded")
                else:
                    self.stats.inc("replies_error")
                    reply(protocol.ERROR, req_id,
                          f"failed after bounded retry: {exc}".encode())

            fut.add_done_callback(done)

        attempt()

    # ------------------------------------------------------------ client side
    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._listen_sock.accept()
            except OSError as e:
                if self._shutdown.is_set():
                    return  # listener closed: draining
                if e.errno in (errno.EBADF, errno.EINVAL):
                    # the listen socket died under us WITHOUT a drain:
                    # say so loudly instead of silently never accepting
                    # again while the fleet keeps answering probes
                    print(f"[router] accept loop dead: {e!r}", flush=True)
                    self._record_event("accept_error", error=repr(e))
                    return
                # transient (ECONNABORTED from a client RST between SYN
                # and accept — exactly the failover/chaos traffic shape —
                # or a brief EMFILE): keep accepting (the ingest server's
                # accept loop learned this in PR 7)
                time.sleep(0.05)
                continue
            if self._shutdown.is_set():
                try:
                    conn.close()  # the drain's own wake-up connection
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                # Same rationale as PolicyServer: replies are written from
                # the replica links' reader threads — one zero-window
                # client must not head-of-line-block a replica's whole
                # reply pump behind an unbounded sendall.
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                    struct.pack("ll", 10, 0),
                )
            except OSError:
                pass
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="router-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = lockwitness.named_lock("Router._serve_conn.send_lock")
        rfile = conn.makefile("rb")

        def reply(msg_type: int, req_id: int, payload: bytes = b"") -> None:
            try:
                with send_lock:
                    protocol.write_frame(conn, msg_type, req_id, payload)
            except OSError:
                # Client gone before its reply, or wedged past the send
                # timeout: a partial frame is unrecoverable — close (which
                # also unblocks this connection's reader).
                self.stats.inc("dropped_replies")
                try:
                    conn.close()
                except OSError:
                    pass

        try:
            while True:
                frame = protocol.read_frame(rfile)
                if frame is None:
                    return  # clean EOF
                msg_type, req_id, payload = frame
                if msg_type == protocol.HEALTHZ:
                    reply(protocol.HEALTHZ_OK, req_id,
                          json.dumps(self.healthz()).encode())
                    continue
                if msg_type != protocol.ACT:
                    raise ProtocolError(f"unexpected message type {msg_type}")
                obs_dim = self._obs_dim
                if obs_dim is None:
                    # no replica has ever answered a probe: obs_dim (and
                    # the fleet) is unknown — shed honestly
                    self.stats.inc("requests_total")
                    self.stats.inc("replies_overloaded")
                    reply(protocol.OVERLOADED, req_id, b"no_replicas")
                    continue
                obs, deadline_us = protocol.decode_act(payload, obs_dim)
                self.stats.inc("requests_total")
                if self._shutdown.is_set():
                    self.stats.inc("replies_overloaded")
                    reply(protocol.OVERLOADED, req_id, b"draining")
                    continue
                if self._chaos is not None:
                    e = self._chaos.tick("replica_slow")
                    if e is not None:
                        # stall THIS request's dispatch (a slow replica as
                        # seen by one request): p99 must account it, other
                        # connections must not feel it
                        time.sleep(
                            (e.arg if e.arg is not None else 100.0) / 1e3
                        )
                self._route(obs, deadline_us, req_id, reply)
        except ProtocolError as e:
            self.stats.inc("protocol_errors")
            try:
                with send_lock:
                    protocol.write_frame(
                        conn, protocol.ERROR, 0, str(e).encode()
                    )
            except OSError:
                pass
        except OSError:
            pass  # peer reset / socket closed by drain
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                rfile.close()
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------- canary rollout
    def _canary_step(self) -> None:
        if self._canary_dir is None:
            return
        state = self._canary_state
        if state == "idle":
            self._canary_idle()
        elif state == "deploying":
            self._canary_check_deploys()
        elif state == "observing":
            self._canary_observe()
        elif state == "promoting":
            self._canary_promote()
        elif state == "rolling_back":
            self._canary_check_rollback()

    def _set_canary_state(self, state: str) -> None:
        with self._lock:
            self._canary_state = state

    def _clear_windows(self) -> None:
        with self._lock:
            self._windows["baseline"].clear()
            self._windows["canary"].clear()

    def _canary_replicas(self):
        return [r for r in self._replicas if r.canary]

    def _canary_idle(self) -> None:
        m = _bundle_json_mtime(self._canary_dir)
        if m is None or m == self._canary_seen_mtime:
            return
        with self._lock:
            eligible = [
                r for r in self._replicas if r.admitted and r.bundle_dir
            ]
            total = len(self._replicas)
        if len(eligible) < 2:
            # a canary needs at least one baseline to compare against;
            # keep waiting (the bookmark does NOT advance — the rollout
            # starts as soon as the fleet is healthy enough)
            return
        n_canary = min(max(1, round(self._canary_permille / 1000 * total)),
                       len(eligible) - 1)
        # deterministic choice: the highest-index eligible replicas
        canaries = sorted(eligible, key=lambda r: -r.index)[:n_canary]
        self._canary_seen_mtime = m
        self._canary_version = m
        self._rollback_dir = tempfile.mkdtemp(prefix="d4pg-router-rollback-")
        self._backed_up = set()
        deploys = {}
        try:
            for r in canaries:
                self._backup_bundle(r)
                corrupt = False
                if self._chaos is not None:
                    corrupt = self._chaos.tick("canary_corrupt") is not None
                deploys[r.index] = self._deploy_bundle(
                    self._canary_dir, r.bundle_dir, corrupt=corrupt
                )
        except OSError as e:
            # Mid-deploy I/O failure (ENOSPC, unreadable canary source, a
            # missing replica bundle file): any canary ALREADY rolled
            # forward must not be left serving the new bundle as a phantom
            # baseline. Route through the normal rollback — it restores
            # every replica in _backed_up and re-ejects until the old
            # version attests; the bookmark stays advanced so a broken
            # rollout is reported once, not retried every probe tick.
            self._canary_rollback(f"deploy I/O error: {e!r}")
            return
        with self._lock:
            for r in canaries:
                r.canary = True
            self._canary_state = "deploying"
        self._deploys = deploys
        self._canary_deadline = time.monotonic() + self._attest_timeout_s
        self._clear_windows()
        self._record_event(
            "canary_start", version=m,
            canaries=[r.index for r in canaries],
            fraction=self._canary_permille / 1000.0,
        )

    def _canary_check_deploys(self) -> None:
        with self._lock:
            canaries = [r for r in self._replicas if r.canary]
            attested = all(
                r.bundle_mtime == self._deploys.get(r.index) and r.admitted
                for r in canaries
            )
            failed = [
                r.index for r in canaries
                if not r.admitted or r.health.get("status") == "degraded"
            ]
        if attested:
            self._set_canary_state("observing")
            # observing gets its own deadline: every other rollout state
            # is bounded, and a fleet with too little traffic to fill the
            # comparison windows must eventually roll back (frozen canary
            # traffic + a rollout that blocks every newer version forever
            # is worse than retrying later under real load)
            self._canary_deadline = (
                time.monotonic() + self._observe_timeout_s
            )
            self._clear_windows()
            self._record_event("canary_observing",
                               version=self._canary_version)
        elif failed or time.monotonic() > self._canary_deadline:
            self._canary_rollback(
                f"deploy failed on replicas {failed}" if failed
                else "deploy attestation timed out"
            )

    def _canary_observe(self) -> None:
        with self._lock:
            dead = [r.index for r in self._replicas
                    if r.canary and not r.admitted]
            base = list(self._windows["baseline"])
            can = list(self._windows["canary"])
        if dead:
            self._canary_rollback(f"canary replicas {dead} ejected "
                                  "mid-observation")
            return
        if len(base) < self._min_samples or len(can) < self._min_samples:
            if time.monotonic() > self._canary_deadline:
                self._canary_rollback(
                    f"observation starved: windows never filled "
                    f"({len(base)} baseline / {len(can)} canary of "
                    f"{self._min_samples} required)"
                )
            return
        base_err = 1.0 - sum(ok for ok, _ in base) / len(base)
        can_err = 1.0 - sum(ok for ok, _ in can) / len(can)
        base_p99 = _p99([lat for ok, lat in base if ok])
        can_p99 = _p99([lat for ok, lat in can if ok])
        verdict = {
            "baseline_error_rate": round(base_err, 4),
            "canary_error_rate": round(can_err, 4),
            "baseline_p99_ms": _ms(base_p99),
            "canary_p99_ms": _ms(can_p99),
            "samples": [len(base), len(can)],
        }
        if can_err > base_err + self._max_err_increase:
            self._canary_rollback(
                f"error-rate regression {can_err:.4f} vs {base_err:.4f}",
                **verdict,
            )
        elif (
            base_p99 is not None and can_p99 is not None
            and can_p99 > base_p99 * self._p99_ratio + 0.010
        ):
            self._canary_rollback(
                f"p99 regression {_ms(can_p99)} ms vs {_ms(base_p99)} ms",
                **verdict,
            )
        else:
            # canary_promotions ticks at COMPLETION (the canary_promoted
            # terminal in _canary_promote), not here at the verdict: a
            # promote that later fails (deploy I/O, attestation timeout)
            # ends in a rollback, and one rollout must never book both
            self._promote_done = set()
            self._deploys = {}
            self._set_canary_state("promoting")
            self._record_event("canary_promote",
                               version=self._canary_version, **verdict)

    def _canary_promote(self) -> None:
        """Roll the remaining baselines forward ONE at a time, each
        attested before the next — a bad surprise mid-promote strands one
        replica, not the fleet."""
        with self._lock:
            baselines = [r for r in self._replicas
                         if r.bundle_dir and not r.canary]
            pending = [r for r in baselines if r.index in self._deploys]
            for r in pending:
                if r.bundle_mtime == self._deploys[r.index] and r.admitted:
                    self._promote_done.add(r.index)
                    del self._deploys[r.index]
        for r in pending:
            if r.index in self._promote_done:
                self._record_event("promoted_replica", replica=r.index)
        if self._deploys:
            if time.monotonic() > self._canary_deadline:
                self._canary_rollback(
                    f"promote attestation timed out on "
                    f"{sorted(self._deploys)}"
                )
            return
        nxt = next(
            (r for r in baselines if r.index not in self._promote_done), None
        )
        if nxt is not None:
            try:
                self._backup_bundle(nxt)
                mt = self._deploy_bundle(self._canary_dir, nxt.bundle_dir)
            except OSError as e:
                # same contract as the idle-path deploy guard: a promote
                # whose source vanished or whose disk filled must roll the
                # whole rollout back, not spin in "promoting" re-raising
                # into the control loop's catch-all every tick
                self._canary_rollback(
                    f"deploy I/O error during promote: {e!r}"
                )
                return
            self._deploys = {nxt.index: mt}
            self._canary_deadline = time.monotonic() + self._attest_timeout_s
            self._record_event("promote_replica", replica=nxt.index)
            return
        # nxt is None: every baseline rolled forward — terminal event
        # BEFORE the state flip: a healthz reader that polls for
        # state=="idle" must find the terminal event already in
        # events_tail (the soak and tests do exactly that)
        self.stats.inc("canary_promotions")
        self._record_event("canary_promoted",
                           version=self._canary_version)
        with self._lock:
            for r in self._replicas:
                r.canary = False
            self._canary_state = "idle"
        self._cleanup_rollback_dir()

    def _canary_rollback(self, reason: str, **verdict) -> None:
        """Restore every replica the rollout touched to the saved old
        bundle and RE-EJECT it until its healthz attests that old version
        (then the normal K-consecutive-probes re-admission applies).
        Baselines that were never deployed to are never touched."""
        # State flips FIRST: once canary_rollbacks ticks (next line), a
        # healthz reader must never see the rollout still "idle"/
        # "observing" — a rollback entered from idle (deploy I/O error)
        # does file restores below before the gates land, and that window
        # read as a settled fleet.
        with self._lock:
            self._canary_state = "rolling_back"
        # deadline BEFORE the restores: if one raises below, the next
        # _canary_check_rollback tick must compare against a real deadline,
        # not a stale/None one (TypeError every control tick = a
        # permanently wedged rollout machine)
        self._rollback_deadline = time.monotonic() + 4 * self._attest_timeout_s
        self.stats.inc("canary_rollbacks")
        self._record_event("canary_rollback", reason=reason,
                           version=self._canary_version, **verdict)
        gates = {}
        restore_failed = []
        for i in sorted(self._backed_up):
            r = self._replicas[i]
            try:
                gates[i] = self._deploy_bundle(
                    os.path.join(self._rollback_dir, str(i)), r.bundle_dir
                )
            except OSError as e:
                # the restore itself failed (ENOSPC again, backup dir
                # damaged): no version to gate re-admission on — eject the
                # replica below anyway (its probes decide re-admission) and
                # say so loudly; the rollback deadline bounds the wait
                restore_failed.append((i, e))
        to_close = []
        ejected = []
        with self._lock:
            for i in sorted(self._backed_up):
                r = self._replicas[i]
                if i in gates:
                    self._readmit_gate[i] = gates[i]
                if r.admitted:
                    to_close.append(self._eject_locked(r, "rollback"))
                    ejected.append(i)
                else:
                    r.healthy_streak = 0
        self._deploys = {}
        for c in to_close:
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass
        for i, e in restore_failed:
            self._record_event("rollback_restore_failed", replica=i,
                               error=repr(e))
        for i in ejected:
            self._record_event("eject", replica=i,
                               addr=self._replicas[i].addr, reason="rollback")

    def _canary_check_rollback(self) -> None:
        with self._lock:
            # every replica the rollout DEPLOYED to (canaries, plus any
            # baseline a failed promote already rolled forward) must attest
            # the restored bundle and re-admit before the rollback is done
            waiting = [
                r.index for r in self._replicas
                if r.index in self._backed_up
                and (r.index in self._readmit_gate or not r.admitted)
            ]
        if not waiting:
            # terminal event BEFORE the state flip (see _canary_promote)
            self._record_event("canary_rolled_back",
                               version=self._canary_version)
            with self._lock:
                for r in self._replicas:
                    r.canary = False
                self._canary_state = "idle"
            self._cleanup_rollback_dir()
            return
        if time.monotonic() > self._rollback_deadline:
            # the replica never came back (killed and not restarted?) —
            # stop gating on it so a fresh process serving the restored
            # bundle can re-admit normally, and say so loudly
            self._record_event("canary_rollback_timeout",
                               version=self._canary_version,
                               waiting=waiting)
            with self._lock:
                for r in self._replicas:
                    r.canary = False
                self._readmit_gate.clear()
                self._canary_state = "idle"
            self._cleanup_rollback_dir()

    def _backup_bundle(self, r: Replica) -> None:
        if r.index in self._backed_up:
            # never overwrite the pristine pre-rollout copy: a re-entered
            # promote step after a partial deploy would otherwise save the
            # half-deployed dir (new params + old json) AS the backup, and
            # a later rollback would restore that corrupt mixture
            return
        dst = os.path.join(self._rollback_dir, str(r.index))
        os.makedirs(dst, exist_ok=True)
        for fname in (_PARAMS_FILE, _META_FILE):
            shutil.copyfile(os.path.join(r.bundle_dir, fname),
                            os.path.join(dst, fname))
        self._backed_up.add(r.index)

    def _deploy_bundle(self, src_dir: str, dst_dir: str,
                       corrupt: bool = False) -> float:
        """Roll ``dst_dir`` (a replica's live bundle) onto ``src_dir``'s
        content: params FIRST, json second, each tmp+rename — the
        exporter's atomic attestation ordering, reproduced because the
        router IS an exporter when it rolls a replica forward. Returns the
        new json mtime (the version the replica must attest via healthz).
        ``corrupt`` is the ``canary_corrupt`` chaos fault: truncate the
        params copy so the replica's reload fails AFTER the attestation
        moved — the degraded-not-promoted path."""
        os.makedirs(dst_dir, exist_ok=True)
        for fname in (_PARAMS_FILE, _META_FILE):
            src = os.path.join(src_dir, fname)
            fd, tmp = tempfile.mkstemp(dir=dst_dir, suffix=".tmp")
            os.close(fd)
            try:
                shutil.copyfile(src, tmp)
                if corrupt and fname == _PARAMS_FILE:
                    size = os.path.getsize(tmp)
                    with open(tmp, "rb+") as f:
                        f.truncate(max(1, size // 2))
                    print(f"[router] chaos canary_corrupt: truncated "
                          f"{fname} for {dst_dir}", flush=True)
                os.replace(tmp, os.path.join(dst_dir, fname))
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        return os.stat(os.path.join(dst_dir, _META_FILE)).st_mtime

    def _cleanup_rollback_dir(self) -> None:
        if self._rollback_dir is not None:
            shutil.rmtree(self._rollback_dir, ignore_errors=True)
            self._rollback_dir = None
        self._backed_up = set()

    # ----------------------------------------------------------------- status
    def healthz(self) -> dict:
        with self._lock:
            replicas = [
                {
                    "index": r.index,
                    "addr": r.addr,
                    "admitted": r.admitted,
                    "ejected_reason": r.ejected_reason,
                    "canary": r.canary,
                    "inflight": r.inflight,
                    "healthy_streak": r.healthy_streak,
                    "bundle_mtime": r.bundle_mtime,
                    "pid": r.pid,
                    "replica_id": r.health.get("replica_id"),
                    "status": r.health.get("status"),
                    "compile_count": r.health.get("compile_count"),
                    "params_reloads": r.health.get("params_reloads"),
                    "ok": r.ok,
                    "errors": r.errors,
                }
                for r in self._replicas
            ]
            admitted = sum(1 for r in self._replicas if r.admitted)
            inflight = sum(r.inflight for r in self._replicas)
            canary = {
                "state": self._canary_state,
                "fraction": self._canary_permille / 1000.0,
                "version": self._canary_version,
                "window_baseline": len(self._windows["baseline"]),
                "window_canary": len(self._windows["canary"]),
            }
            obs_dim = self._obs_dim
        snap = self.stats.snapshot()
        snap["router"] = True
        snap["status"] = "draining" if self._shutdown.is_set() else (
            "ok" if admitted else "degraded"
        )
        snap["draining"] = self._shutdown.is_set()
        snap["admitted"] = admitted
        snap["inflight"] = inflight
        snap["obs_dim"] = obs_dim
        snap["replicas"] = replicas
        snap["canary"] = canary
        with self._events_lock:
            snap["events_total"] = self._events_total
            snap["events_tail"] = list(self._events)[-20:]
        if self._chaos is not None:
            snap["chaos_injections"] = self._chaos.injections_total
        return snap

    def _metrics_row(self) -> dict:
        """Numeric-only flat row (MetricsLogger contract)."""
        snap = self.stats.snapshot()
        with self._lock:
            snap["admitted"] = sum(1 for r in self._replicas if r.admitted)
            snap["inflight"] = sum(r.inflight for r in self._replicas)
            snap["canary_active"] = float(self._canary_state != "idle")
        return {
            k: float(v) for k, v in snap.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }

    def _metrics_loop(self) -> None:
        while not self._shutdown.wait(self._metrics_interval_s):
            self._metrics.log(self.stats.requests_total, self._metrics_row())


def _p99(lats) -> Optional[float]:
    if not lats:
        return None
    s = sorted(lats)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def _ms(v: Optional[float]):
    return None if v is None else round(v * 1e3, 4)


# --------------------------------------------------------------------- CLI
def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.serve.router",
        description="Replicated serving front-end: least-loaded dispatch, "
                    "health-driven ejection, rolling canary rollout.",
    )
    p.add_argument("--backends", required=True,
                   help="comma-separated host:port of the serve/ replicas")
    p.add_argument("--backend-bundles", default=None,
                   help="comma-separated bundle dirs, 1:1 with --backends "
                        "(required for canary rollout: the router rolls a "
                        "replica forward by writing into its bundle dir)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7430,
                   help="0 = ephemeral (printed on startup)")
    p.add_argument("--probe-interval", type=float, default=0.5,
                   help="seconds between healthz probe rounds")
    p.add_argument("--probe-timeout", type=float, default=2.0,
                   help="per-probe budget; past it the replica is ejected")
    p.add_argument("--readmit-after", type=int, default=2,
                   help="consecutive healthy probes before (re-)admission")
    p.add_argument("--dispatch-retries", type=int, default=1,
                   help="bounded re-dispatches on a different replica when "
                        "one sheds or dies mid-stream")
    p.add_argument("--stuck-after", type=float, default=30.0,
                   help="eject a replica whose in-flight dispatches stop "
                        "resolving for this many seconds even though its "
                        "healthz still answers ok (a wedged device thread); "
                        "ejection fails the stuck requests over. 0 disables")
    p.add_argument("--retry-seed", type=int, default=0)
    p.add_argument("--wait-replicas", type=int, default=None,
                   help="block startup until N replicas admitted "
                        "(default: all backends)")
    p.add_argument("--wait-timeout", type=float, default=120.0)
    p.add_argument("--canary-bundle", default=None,
                   help="bundle dir to watch for rollouts: each new "
                        "bundle.json mtime there starts a canary rollout")
    p.add_argument("--canary-fraction", type=float, default=0.25,
                   help="deterministic request fraction routed to canary "
                        "replicas while observing")
    p.add_argument("--canary-window", type=int, default=256,
                   help="sliding comparison window per group (requests)")
    p.add_argument("--canary-min-samples", type=int, default=40,
                   help="per-group samples required before a verdict")
    p.add_argument("--canary-max-error-increase", type=float, default=0.05,
                   help="canary error rate above baseline+this rolls back")
    p.add_argument("--canary-p99-ratio", type=float, default=3.0,
                   help="canary p99 above baseline*this (+10ms) rolls back")
    p.add_argument("--canary-attest-timeout", type=float, default=30.0,
                   help="seconds a deployed replica gets to attest the new "
                        "bundle_mtime before the rollout rolls back")
    p.add_argument("--canary-observe-timeout", type=float, default=600.0,
                   help="seconds the observation windows get to reach "
                        "--canary-min-samples before the rollout rolls "
                        "back (too little traffic must not wedge a "
                        "rollout in 'observing' forever)")
    p.add_argument("--log-dir", default=None,
                   help="append router metrics rows (metrics.jsonl) here")
    p.add_argument("--metrics-interval", type=float, default=30.0)
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="deterministic fault injection (d4pg_tpu/chaos.py): "
                        "replica_kill@N / replica_slow@N:ms / "
                        "canary_corrupt@N")
    return p


def main(argv=None) -> None:
    import sys

    from d4pg_tpu.utils.signals import install_graceful_signals

    args = build_parser().parse_args(argv)
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    bundles = None
    if args.backend_bundles:
        bundles = [
            b.strip() or None for b in args.backend_bundles.split(",")
        ]
    chaos = None
    if args.chaos:
        from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

        chaos = ChaosInjector(ChaosPlan.parse(args.chaos))
    router = Router(
        backends,
        host=args.host,
        port=args.port,
        bundle_dirs=bundles,
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        readmit_after=args.readmit_after,
        dispatch_retries=args.dispatch_retries,
        stuck_after_s=args.stuck_after,
        retry_seed=args.retry_seed,
        canary_bundle=args.canary_bundle,
        canary_fraction=args.canary_fraction,
        canary_window=args.canary_window,
        canary_min_samples=args.canary_min_samples,
        canary_max_err_increase=args.canary_max_error_increase,
        canary_p99_ratio=args.canary_p99_ratio,
        canary_attest_timeout_s=args.canary_attest_timeout,
        canary_observe_timeout_s=args.canary_observe_timeout,
        log_dir=args.log_dir,
        metrics_interval_s=args.metrics_interval,
        chaos=chaos,
    )
    install_graceful_signals(
        router.request_shutdown,
        "[router] {sig}: draining (second signal hard-kills)",
    )
    router.start()
    print(
        f"[router] listening on {router.host}:{router.port} "
        f"backends={','.join(backends)}",
        flush=True,
    )
    want = args.wait_replicas if args.wait_replicas is not None else len(backends)
    if want:
        admitted = router.wait_for_replicas(want, timeout_s=args.wait_timeout)
        print(f"[router] admitted {admitted}/{len(backends)} replicas",
              flush=True)
    router.serve_until_shutdown()
    snap = router.healthz()
    print(
        f"[router] drained: {snap['replies_ok']} ok, "
        f"{snap['replies_overloaded']} overloaded, "
        f"{snap['replies_error']} failed, "
        f"retries={snap['retries']} ejections={snap['ejections']} "
        f"p99={snap.get('p99_ms')} ms",
        flush=True,
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
