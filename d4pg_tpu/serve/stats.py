"""Serving observability: latency percentiles + batch/queue histograms.

The serving SLO surface is p50/p95/p99 request latency, the batch-size
distribution (how well the window fills), queue depth (how close to
shedding), and the shed counters themselves. All of it aggregates here and
is snapshotted by the ``healthz`` reply and the periodic
:class:`~d4pg_tpu.runtime.metrics.MetricsLogger` row — the same jsonl
pipeline training runs log through, so serve metrics plot with the same
tooling (docs/serving.md has the schema).

Everything is lock-protected and O(1) per request; percentile computation
happens only at snapshot time over a bounded reservoir.
"""

from __future__ import annotations

import threading
import time

import numpy as np
from d4pg_tpu.analysis import lockwitness


class LatencyReservoir:
    """Sliding window of the last ``size`` request latencies.

    A plain ring, not a decaying sample: serving percentiles should reflect
    the RECENT regime (the thing an operator alarms on), and a few thousand
    samples bound the snapshot cost while covering seconds of traffic at
    any realistic rate.
    """

    def __init__(self, size: int = 8192):
        self._buf = np.zeros(size, np.float64)
        self._n = 0          # total ever recorded
        self._lock = lockwitness.named_lock("LatencyReservoir._lock")

    def add(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = seconds
            self._n += 1

    def percentiles_ms(self, qs=(50, 95, 99)) -> dict:
        with self._lock:
            n = min(self._n, len(self._buf))
            if n == 0:
                return {f"p{q}_ms": None for q in qs}
            window = self._buf[:n].copy()
        vals = np.percentile(window, qs)
        return {f"p{q}_ms": round(float(v) * 1e3, 4) for q, v in zip(qs, vals)}

    @property
    def count(self) -> int:
        with self._lock:
            return self._n


class Histogram:
    """Counts per bucket over fixed upper-edge boundaries (last bucket is
    open-ended). Used for batch sizes (edges = the batcher's bucket sizes)
    and queue depth (powers of two up to the queue limit)."""

    def __init__(self, edges):
        self.edges = tuple(int(e) for e in edges)
        self._counts = [0] * (len(self.edges) + 1)
        self._lock = lockwitness.named_lock("Histogram._lock")

    def add(self, value: int) -> None:
        i = 0
        while i < len(self.edges) and value > self.edges[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
        out = {}
        for i, e in enumerate(self.edges):
            out[f"le_{e}"] = counts[i]
        out["inf"] = counts[-1]
        return out


class ServeStats:
    """One aggregation point for every serving counter.

    Shared by the connection handlers (request/shed/error counts), the
    batcher device thread (batch sizes, per-batch device time via
    StageTimers), and the reply path (latency reservoir). ``snapshot()``
    is the healthz payload and the periodic metrics row.
    """

    def __init__(self, batch_edges, queue_edges):
        self.latency = LatencyReservoir()
        self.batch_hist = Histogram(batch_edges)
        self.queue_hist = Histogram(queue_edges)
        # Witnessed under --debug-guards (static node ids, see lockwitness)
        self._lock = lockwitness.named_lock("ServeStats._lock")
        self._t0 = time.monotonic()
        self.requests_total = 0
        self.replies_ok = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.shed_draining = 0
        self.protocol_errors = 0
        self.dropped_replies = 0   # client gone before its reply
        self.unknown_policy = 0    # well-formed ACT2 naming a non-resident policy
        self.feedback_frames = 0   # reward echoes accepted (flywheel mirror)
        self.batches_total = 0
        self.padded_rows_total = 0
        self.params_version = 0
        self.params_reloads = 0
        # Admitted-but-unanswered gauge (+1 at enqueue, −1 when the request
        # future resolves — any way). The replica front-end's prober reads
        # it from healthz for least-loaded dispatch across replicas.
        self.inflight = 0

    def inc(self, field: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def observe_batch(self, n: int, bucket: int) -> None:
        self.batch_hist.add(n)
        with self._lock:
            self.batches_total += 1
            self.padded_rows_total += bucket - n

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "inflight": self.inflight,
                "requests_total": self.requests_total,
                "replies_ok": self.replies_ok,
                "shed_queue_full": self.shed_queue_full,
                "shed_deadline": self.shed_deadline,
                "shed_draining": self.shed_draining,
                "protocol_errors": self.protocol_errors,
                "dropped_replies": self.dropped_replies,
                "unknown_policy": self.unknown_policy,
                "feedback_frames": self.feedback_frames,
                "batches_total": self.batches_total,
                "padded_rows_total": self.padded_rows_total,
                "params_version": self.params_version,
                "params_reloads": self.params_reloads,
            }
        shed = out["shed_queue_full"] + out["shed_deadline"] + out["shed_draining"]
        out["shed_total"] = shed
        if out["requests_total"]:
            out["shed_rate"] = round(shed / out["requests_total"], 6)
        out.update(self.latency.percentiles_ms())
        out["batch_size_hist"] = self.batch_hist.snapshot()
        out["queue_depth_hist"] = self.queue_hist.snapshot()
        if out["batches_total"]:
            out["mean_batch"] = round(
                out["replies_ok"] / out["batches_total"], 3
            )
        return out

    def metrics_row(self) -> dict:
        """Flat scalars-only view for MetricsLogger (histograms flattened,
        None percentiles dropped — jsonl rows are float-valued)."""
        snap = self.snapshot()
        row = {}
        for k, v in snap.items():
            if isinstance(v, dict):
                for bk, bv in v.items():
                    row[f"{k}_{bk}"] = float(bv)
            elif v is not None:
                row[k] = float(v)
        return row
