"""Healthz-driven autoscaler: a control loop that spawns and DRAINS
capacity against the gauges the fleet already exports.

Capacity was a hand-picked constant through PR 8 — M replicas chosen at
router startup, N actor hosts chosen at fleet launch. This module is the
control dimension: one generic hysteresis/cooldown loop
(:class:`Autoscaler`) over a normalized :class:`ScaleSignal`, with two
signal adapters and two pools:

- **Serving** — :class:`ServingSignalSource` reads the ROUTER's healthz
  (inflight vs the capacity model, interactive p99 vs its SLO, shed
  rate); :class:`RouterReplicaPool` scales by spawning
  ``python -m d4pg_tpu.serve`` subprocesses (via ``scripts/spawnlib.py``,
  the shared CLI harness), registering them with
  :meth:`~d4pg_tpu.serve.router.Router.add_backend`, and scaling DOWN by
  SIGTERM — the graceful-drain contract: the replica answers everything
  admitted and exits 0; only after the process exits is it
  ``remove_backend``-ed. Never SIGKILL on the happy path.

- **Training** — :class:`IngestSignalSource` reads the fleet-ingest
  counters (the learner paces against ingested windows: a starved ingest
  means too FEW actor hosts; sustained queue-full shedding means too
  many); :class:`ActorHostPool` spawns/drains
  ``python -m d4pg_tpu.fleet.actor`` hosts — the same loop shape driving
  collection capacity against backpressure.

Control discipline (docs/serving.md has the knob rationale):

- **never scale on one sample** — a decision needs ``samples``
  CONSECUTIVE breaching ticks; one GC pause or probe blip must not move
  the fleet;
- **hysteresis** — the scale-up threshold (``up_load``) sits well above
  the scale-down threshold (``down_load``); between them the loop holds,
  so load hovering at one threshold cannot flap capacity;
- **cooldown** — after ANY action the loop holds ``cooldown_s``: new
  capacity needs warmup + admission (K healthy probes) before its effect
  is measurable, and reacting to the pre-action gauges again would
  over-shoot;
- **drain, don't kill** — scale-down reuses the SIGTERM graceful-drain
  contract end to end.

Chaos: the ``scaledown_during_canary`` site ticks once per control tick
and forces a scale-down regardless of the gauges — the soak drives it
mid-rollout to prove the router's rollout machinery aborts or completes
cleanly (never a stranded half-deployed replica).

This is a HOST-ONLY module (d4pglint manifest): it moves signals and
processes, never tensors — it must restart in milliseconds and run
beside a JAX-free router.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from d4pg_tpu.analysis import lockwitness
from d4pg_tpu.utils import procs


@dataclass
class ScaleSignal:
    """One normalized control sample.

    ``load`` is utilization against CURRENT capacity: > ``up_load`` means
    underprovisioned, < ``down_load`` overprovisioned (the adapters map
    their domain gauges onto this axis). ``p99_ms``/``shed_rate`` are
    breach accelerants: an SLO violation or sustained shedding counts as
    an up-breach even at moderate load."""

    load: float
    p99_ms: Optional[float] = None
    shed_rate: float = 0.0
    replicas: int = 0


class Autoscaler:
    """The generic control loop. ``signal_fn() -> ScaleSignal`` samples
    the gauges; ``scale_up()`` / ``scale_down()`` are the pool's
    actuators (return True when they acted). ``close()`` joins the
    control thread (bounded)."""

    def __init__(
        self,
        signal_fn: Callable[[], ScaleSignal],
        scale_up: Callable[[], bool],
        scale_down: Callable[[], bool],
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 2.0,
        up_load: float = 0.8,
        down_load: float = 0.3,
        p99_slo_ms: Optional[float] = None,
        shed_threshold: float = 0.05,
        samples: int = 3,
        cooldown_s: float = 30.0,
        chaos=None,
        on_event: Optional[Callable[..., None]] = None,
    ):
        if not (0.0 <= down_load < up_load):
            raise ValueError(
                f"need 0 <= down_load < up_load for hysteresis, got "
                f"down={down_load} up={up_load}"
            )
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        self._signal_fn = signal_fn
        self._scale_up = scale_up
        self._scale_down = scale_down
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._interval_s = float(interval_s)
        self._up_load = float(up_load)
        self._down_load = float(down_load)
        self._p99_slo_ms = p99_slo_ms
        self._shed_threshold = float(shed_threshold)
        self._samples = int(samples)
        self._cooldown_s = float(cooldown_s)
        self._chaos = chaos
        self._on_event = on_event

        self._lock = lockwitness.named_lock("Autoscaler._lock")
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: Optional[float] = None
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.signal_errors = 0
        self.last_signal: Optional[ScaleSignal] = None

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._thread = threading.Thread(
            target=self._control_loop, name="autoscaler-control", daemon=True
        )
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event(kind, **fields)
        else:
            import json

            print(f"[autoscaler] {json.dumps({'event': kind, **fields})}",
                  flush=True)

    # --------------------------------------------------------------- control
    def _control_loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.tick()
            except Exception as e:  # d4pglint: disable=broad-except -- logged via _event (router event log or stdout); the control loop must outlive probe/pool errors
                self._event("autoscaler_error", error=repr(e))

    def tick(self) -> Optional[str]:
        """One control step (public so tests drive it without the timer).
        Returns "up"/"down" when an action fired, else None."""
        with self._lock:
            self.ticks += 1
        if self._chaos is not None:
            e = self._chaos.tick("scaledown_during_canary")
            if e is not None:
                # forced scale-down (the chaos proof): bypasses streak +
                # cooldown but NEVER the floor — a chaos plan must not be
                # able to scale the fleet to zero
                sig = self._sample()
                if sig is not None and sig.replicas > self.min_replicas:
                    self._act("down", sig, forced=True)
                    return "down"
                self._event("scaledown_skipped_at_floor",
                            replicas=None if sig is None else sig.replicas)
                return None
        sig = self._sample()
        if sig is None:
            return None
        up_breach = sig.load > self._up_load or (
            self._p99_slo_ms is not None
            and sig.p99_ms is not None
            and sig.p99_ms > self._p99_slo_ms
        ) or sig.shed_rate > self._shed_threshold
        down_breach = not up_breach and sig.load < self._down_load and (
            self._p99_slo_ms is None
            or sig.p99_ms is None
            or sig.p99_ms <= self._p99_slo_ms
        )
        with self._lock:
            self._up_streak = self._up_streak + 1 if up_breach else 0
            self._down_streak = self._down_streak + 1 if down_breach else 0
            up_ready = self._up_streak >= self._samples
            down_ready = self._down_streak >= self._samples
            in_cooldown = (
                self._last_action_t is not None
                and time.monotonic() - self._last_action_t < self._cooldown_s
            )
        if in_cooldown:
            return None
        if up_ready and sig.replicas < self.max_replicas:
            return self._act("up", sig)
        if down_ready and sig.replicas > self.min_replicas:
            return self._act("down", sig)
        return None

    def _sample(self) -> Optional[ScaleSignal]:
        try:
            sig = self._signal_fn()
        except Exception as e:  # d4pglint: disable=broad-except -- counted in signal_errors + logged via _event; a flaky probe is a no-op sample, not a dead autoscaler
            with self._lock:
                self.signal_errors += 1
            self._event("signal_error", error=repr(e))
            return None
        with self._lock:
            self.last_signal = sig
        return sig

    def _act(self, direction: str, sig: ScaleSignal,
             forced: bool = False) -> Optional[str]:
        acted = (self._scale_up if direction == "up" else self._scale_down)()
        with self._lock:
            self._up_streak = 0
            self._down_streak = 0
            # cooldown starts at the ATTEMPT, success or not: a failed
            # spawn (crash-looping replica) must be paced by the full
            # cooldown, not retried every `samples` ticks forever
            self._last_action_t = time.monotonic()
            if acted:
                if direction == "up":
                    self.scale_ups += 1
                else:
                    self.scale_downs += 1
        self._event(
            f"scale_{direction}" if acted else f"scale_{direction}_failed",
            load=round(sig.load, 4),
            p99_ms=sig.p99_ms,
            shed_rate=round(sig.shed_rate, 4),
            replicas=sig.replicas,
            forced=forced,
        )
        return direction if acted else None

    def snapshot(self) -> dict:
        with self._lock:
            sig = self.last_signal
            return {
                "ticks": self.ticks,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "signal_errors": self.signal_errors,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "load": None if sig is None else round(sig.load, 4),
                "replicas": None if sig is None else sig.replicas,
            }


# ------------------------------------------------------- signal adapters
class ServingSignalSource:
    """Router healthz → :class:`ScaleSignal`. ``load`` = inflight over
    the capacity model (admitted × replica_capacity); ``p99_ms`` is the
    INTERACTIVE tier's p99 (the SLO the autoscaler defends — bulk p99 is
    allowed to suffer by design); ``shed_rate`` is overloaded-replies
    over requests SINCE THE LAST SAMPLE (a lifetime ratio would dilute a
    live overload under hours of healthy history)."""

    def __init__(self, healthz_fn: Callable[[], dict]):
        self._fn = healthz_fn
        self._prev = (0, 0)  # (requests_total, replies_overloaded)

    def __call__(self) -> ScaleSignal:
        h = self._fn()
        admitted = int(h.get("admitted") or 0)
        # the min/max clamp counts REGISTERED (non-removed) replicas, not
        # admitted ones: a transiently-ejected-but-alive replica still
        # owns its device/memory, and counting it out would let a load
        # breach push the fleet past --autoscale-max while it re-admits
        rows = h.get("replicas")
        registered = (
            sum(1 for r in rows if not r.get("removed"))
            if isinstance(rows, list) else admitted
        )
        cap = (h.get("capacity") or {}).get("total") or 0
        inflight = int(h.get("inflight") or 0)
        # no capacity model configured: fall back to inflight per replica
        # against an implicit 1.0 "busy" line per replica
        load = (inflight / cap) if cap else (
            float(inflight) / admitted if admitted else 0.0
        )
        req = int(h.get("requests_total") or 0)
        over = int(h.get("replies_overloaded") or 0)
        d_req = req - self._prev[0]
        d_over = over - self._prev[1]
        self._prev = (req, over)
        shed = (d_over / d_req) if d_req > 0 else 0.0
        inter = h.get("interactive") or {}
        p99 = inter.get("p99_ms")
        if p99 is None:
            p99 = h.get("p99_ms")
        return ScaleSignal(
            load=load, p99_ms=p99, shed_rate=shed, replicas=registered
        )


class IngestSignalSource:
    """Fleet-ingest counters → :class:`ScaleSignal` for ACTOR-HOST
    scaling. The learner paces against ingested windows, so demand is a
    TARGET windows/s: ``load = target / observed_rate`` — a starved
    ingest (too few actor hosts) reads load > 1 and scales UP; sustained
    queue-full shedding (too many hosts for the learner's write rate)
    zeroes the load and scales DOWN. ``replicas`` is the live connection
    count (one per actor host)."""

    def __init__(self, counters_fn: Callable[[], dict],
                 target_windows_per_s: float):
        if target_windows_per_s <= 0:
            raise ValueError("target_windows_per_s must be > 0")
        self._fn = counters_fn
        self._target = float(target_windows_per_s)
        self._prev: Optional[tuple] = None  # (t, ingested, shed)

    def __call__(self) -> ScaleSignal:
        c = self._fn()
        now = time.monotonic()
        ingested = int(c.get("windows_ingested") or 0)
        shed = int(c.get("windows_shed") or 0)
        conns = int(c.get("connections") or 0)
        if self._prev is None:
            self._prev = (now, ingested, shed)
            return ScaleSignal(load=1.0, replicas=conns)  # hold: no rate yet
        t0, i0, s0 = self._prev
        dt = max(now - t0, 1e-6)
        rate = (ingested - i0) / dt
        d_shed = shed - s0
        self._prev = (now, ingested, shed)
        total = (ingested - i0) + d_shed
        shed_frac = (d_shed / total) if total > 0 else 0.0
        if d_shed > 0 and shed_frac > 0.5:
            # the learner is the bottleneck: more actors only shed more
            return ScaleSignal(load=0.0, shed_rate=shed_frac,
                               replicas=conns)
        load = self._target / max(rate, 1e-6)
        return ScaleSignal(load=min(load, 10.0), shed_rate=shed_frac,
                           replicas=conns)


# ---------------------------------------------------------------- pools
class RouterReplicaPool:
    """Serving-side actuators over an in-process
    :class:`~d4pg_tpu.serve.router.Router`.

    Scale-up: copy the source bundle into a FRESH per-replica dir (each
    replica serves its own dir — the rollout contract), spawn
    ``python -m d4pg_tpu.serve`` via the injected ``spawn`` callable
    (``scripts/spawnlib.py:spawn`` — tagged stdout pump + port scrape),
    then ``router.add_backend`` so admission flows through the normal
    probe path. Scale-down: drain the router's candidate (SIGTERM, wait
    for the rc-0 drain), then ``remove_backend``. ``close()`` drains
    everything this pool spawned."""

    def __init__(
        self,
        router,
        bundle_src: str,
        workdir: str,
        spawn: Callable,
        *,
        replica_args=(),
        spawn_timeout_s: float = 180.0,
        drain_timeout_s: float = 120.0,
    ):
        import sys

        self._router = router
        self._bundle_src = bundle_src
        self._workdir = workdir
        self._spawn = spawn
        self._replica_args = list(replica_args)
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._python = sys.executable
        self._lock = lockwitness.named_lock("RouterReplicaPool._lock")
        self._spawned: dict = {}  # router index -> Spawned handle
        self._n = 0

    def scale_up(self) -> bool:
        import os
        import shutil

        with self._lock:
            self._n += 1
            n = self._n
        bundle_dir = os.path.join(self._workdir, f"autoscale_r{n}")
        if not os.path.isdir(bundle_dir):
            shutil.copytree(self._bundle_src, bundle_dir)
        handle = self._spawn(
            [self._python, "-m", "d4pg_tpu.serve",
             "--bundle", bundle_dir, "--port", "0",
             "--replica-id", str(1000 + n)] + self._replica_args,
            f"autoscale-r{n}",
        )
        try:
            port = handle.wait_port(self._spawn_timeout_s)
        except AssertionError:
            # the replica never came up: reap it AND its bundle copy,
            # report failure — the autoscaler's cooldown (recorded at the
            # attempt, success or not) paces a crash-looping spawn storm,
            # and the rmtree keeps it from growing disk per retry
            import signal as _signal

            procs.drain_or_kill(
                handle.proc, pgid=getattr(handle, "pgid", 0),
                sig=_signal.SIGKILL, drain_timeout_s=10.0,
                label="failed-spawn replica",
            )
            shutil.rmtree(bundle_dir, ignore_errors=True)
            return False
        idx = self._router.add_backend("127.0.0.1", port, bundle_dir)
        with self._lock:
            self._spawned[idx] = handle
        return True

    def scale_down(self) -> bool:
        import signal as _signal

        cand = self._router.pick_scaledown_candidate()
        with self._lock:
            if not self._spawned:
                return False  # nothing THIS pool owns is drainable
            idx = cand if cand in self._spawned else max(self._spawned)
            handle = self._spawned.pop(idx)
        return self._drain_one(idx, handle, _signal)

    def _drain_one(self, idx: int, handle, _signal) -> bool:
        # Deregister from dispatch FIRST: remove_backend ejects the
        # replica (in-flight dispatches fail over via the bounded retry),
        # so no NEW request can land on it and shed OVERLOADED(draining)
        # during the window before a probe would have noticed. Only then
        # SIGTERM — drain, don't kill: the replica still answers
        # everything it had admitted and exits 0. The bounded
        # drain→group-kill escalation is procs.drain_or_kill, once for
        # the whole repo (ISSUE 15 dedup).
        self._router.remove_backend(idx)
        rc = procs.drain_or_kill(
            handle.proc, pgid=getattr(handle, "pgid", 0),
            sig=_signal.SIGTERM, drain_timeout_s=self._drain_timeout_s,
            label=f"replica {idx}",
        )
        if rc not in (0, None):
            print(f"[autoscaler] replica {idx} drained rc={rc}", flush=True)
        return True

    def count(self) -> int:
        with self._lock:
            return len(self._spawned)

    def close(self) -> None:
        import signal as _signal

        with self._lock:
            spawned, self._spawned = dict(self._spawned), {}
        for idx, handle in sorted(spawned.items(), reverse=True):
            self._drain_one(idx, handle, _signal)


class ActorHostPool:
    """Training-side actuators: spawn/drain ``python -m
    d4pg_tpu.fleet.actor`` hosts against a fleet-ingest endpoint. No
    registration step — actors dial the learner themselves (the HELLO
    handshake is the admission); scale-down SIGTERMs the newest host
    (its drain flushes the spool and prints the accounting line)."""

    def __init__(self, connect: str, bundle_dir: str, spawn: Callable,
                 *, actor_args=(), drain_timeout_s: float = 60.0):
        import sys

        self._connect = connect
        self._bundle_dir = bundle_dir
        self._spawn = spawn
        self._actor_args = list(actor_args)
        self._drain_timeout_s = float(drain_timeout_s)
        self._python = sys.executable
        self._lock = lockwitness.named_lock("ActorHostPool._lock")
        self._spawned: list = []
        self._n = 0

    def scale_up(self) -> bool:
        with self._lock:
            self._n += 1
            n = self._n
        handle = self._spawn(
            [self._python, "-m", "d4pg_tpu.fleet.actor",
             "--connect", self._connect, "--bundle", self._bundle_dir,
             "--seed", str(1000 + n)] + self._actor_args,
            f"autoscale-actor{n}",
        )
        with self._lock:
            self._spawned.append(handle)
        return True

    def scale_down(self) -> bool:
        import signal as _signal

        with self._lock:
            if not self._spawned:
                return False
            handle = self._spawned.pop()
        procs.drain_or_kill(
            handle.proc, pgid=getattr(handle, "pgid", 0),
            sig=_signal.SIGTERM, drain_timeout_s=self._drain_timeout_s,
            label="actor host",
        )
        return True

    def count(self) -> int:
        with self._lock:
            return len(self._spawned)

    def close(self) -> None:
        while self.scale_down():
            pass
