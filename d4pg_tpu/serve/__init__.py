"""Policy serving: obs → action inference as a standalone subsystem.

The training side ends at a checkpoint; this package is the other half of
the ROADMAP's "serves heavy traffic" north star — the SEED-RL-shaped
deployment of a trained D4PG actor (PAPERS.md: Espeholt et al. 2019;
Barth-Maron et al. 2018 §deployment):

- :mod:`~d4pg_tpu.serve.bundle`   — self-contained export/load of params +
  config + bounds + obs-norm stats (``train.py --export-bundle``);
- :mod:`~d4pg_tpu.serve.batcher`  — dynamic micro-batching onto one device
  thread, bucket-compiled, donated inputs, explicit load shedding;
- :mod:`~d4pg_tpu.serve.server`   — stdlib socket front-end with deadlines,
  checkpoint hot-reload, graceful drain, healthz;
- :mod:`~d4pg_tpu.serve.client`   — blocking + pipelined client;
- :mod:`~d4pg_tpu.serve.protocol` — the length-prefixed binary frames;
- :mod:`~d4pg_tpu.serve.stats`    — p50/p95/p99, batch/queue histograms.

Run it: ``python -m d4pg_tpu.serve --bundle <dir>`` (docs/serving.md).
"""

from d4pg_tpu.serve.batcher import DynamicBatcher, ShedError, default_buckets
from d4pg_tpu.serve.bundle import PolicyBundle, export_bundle, load_bundle
from d4pg_tpu.serve.client import (
    ConnectionClosed,
    Overloaded,
    PolicyClient,
    ServerError,
)
from d4pg_tpu.serve.server import PolicyServer

__all__ = [
    "ConnectionClosed",
    "DynamicBatcher",
    "Overloaded",
    "PolicyBundle",
    "PolicyClient",
    "PolicyServer",
    "ServerError",
    "ShedError",
    "default_buckets",
    "export_bundle",
    "load_bundle",
]
