"""Policy serving: obs → action inference as a standalone subsystem.

The training side ends at a checkpoint; this package is the other half of
the ROADMAP's "serves heavy traffic" north star — the SEED-RL-shaped
deployment of a trained D4PG actor (PAPERS.md: Espeholt et al. 2019;
Barth-Maron et al. 2018 §deployment):

- :mod:`~d4pg_tpu.serve.bundle`   — self-contained export/load of params +
  config + bounds + obs-norm stats (``train.py --export-bundle``);
- :mod:`~d4pg_tpu.serve.batcher`  — dynamic micro-batching onto one device
  thread, bucket-compiled, donated inputs, explicit load shedding;
- :mod:`~d4pg_tpu.serve.server`   — stdlib socket front-end with deadlines,
  checkpoint hot-reload, graceful drain, healthz;
- :mod:`~d4pg_tpu.serve.client`   — blocking + pipelined client;
- :mod:`~d4pg_tpu.serve.protocol` — the length-prefixed binary frames;
- :mod:`~d4pg_tpu.serve.stats`    — p50/p95/p99, batch/queue histograms;
- :mod:`~d4pg_tpu.serve.router`   — replicated front-end: least-loaded
  dispatch across M replicas, health-driven ejection/re-admission,
  per-policy rolling canary rollouts with auto-rollback, QoS classes +
  per-tenant admission quotas (JAX-free, host-only);
- :mod:`~d4pg_tpu.serve.autoscaler` — healthz-driven control loop with
  hysteresis + cooldown: spawns/drains serve replicas (and fleet actor
  hosts) against the exported gauges (JAX-free, host-only).

Run it: ``python -m d4pg_tpu.serve --bundle <dir>`` (one replica) and
``python -m d4pg_tpu.serve.router --backends host:port,...`` (the fleet
front-end) — docs/serving.md.

Lazy re-exports (the `_lazy.py` contract): the protocol, client, and
stats submodules are host-only — thin clients and the JAX-free fleet
actor hosts (``d4pg_tpu/fleet``) import them — so an eager
``from .batcher import DynamicBatcher`` here would make ANY
``d4pg_tpu.serve.*`` import pay the full JAX import.
"""

from d4pg_tpu._lazy import lazy_exports

_EXPORTS = {
    "DynamicBatcher": "d4pg_tpu.serve.batcher",
    "ShedError": "d4pg_tpu.serve.batcher",
    "default_buckets": "d4pg_tpu.serve.batcher",
    "PolicyBundle": "d4pg_tpu.serve.bundle",
    "export_bundle": "d4pg_tpu.serve.bundle",
    "load_bundle": "d4pg_tpu.serve.bundle",
    "ConnectionClosed": "d4pg_tpu.serve.client",
    "Overloaded": "d4pg_tpu.serve.client",
    "PolicyClient": "d4pg_tpu.serve.client",
    "ServerError": "d4pg_tpu.serve.client",
    "PolicyServer": "d4pg_tpu.serve.server",
    "Router": "d4pg_tpu.serve.router",
    "Autoscaler": "d4pg_tpu.serve.autoscaler",
    "ScaleSignal": "d4pg_tpu.serve.autoscaler",
}

__getattr__, __dir__ = lazy_exports(__name__, _EXPORTS)

__all__ = sorted(_EXPORTS)
