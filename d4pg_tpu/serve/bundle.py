"""Policy bundles: everything inference needs, in one directory.

A bundle decouples SERVING from TRAINING: the exporter
(``train.py --export-bundle`` or :func:`export_bundle`) packages the actor
params, the :class:`~d4pg_tpu.agent.state.D4PGConfig` that shapes the
network, the env's action bounds, and the obs-normalizer statistics from
``trainer_meta.json`` into a self-describing directory — so the serving
process reconstructs the exact acting-time data path (normalize → actor →
clip → affine to env bounds) with no Trainer, replay, env, or Orbax import
anywhere near it.

Layout::

    <bundle>/
      bundle.json        config + bounds + obs-norm stats + provenance
      actor_params.npz   actor param leaves in tree_flatten order
                         (zero-padded ``leaf_%05d`` keys, the
                         ``best_actor.npz`` discipline — sorted(files)
                         restores the order exactly)

Writes are atomic (params first, json second, each tmp+rename): a reader —
including the server's hot-reload watcher — never sees a json attesting
params that are not fully on disk. Hot reload keys on ``bundle.json``'s
mtime for exactly this reason: it is the LAST file the exporter moves into
place.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from d4pg_tpu.agent.state import D4PGConfig
from d4pg_tpu.models.critic import DistConfig

BUNDLE_VERSION = 1
PARAMS_FILE = "actor_params.npz"
META_FILE = "bundle.json"


def config_to_json(config: D4PGConfig) -> dict:
    return dataclasses.asdict(config)


def config_from_json(d: dict) -> D4PGConfig:
    """Rebuild the frozen dataclasses from their asdict form. Unknown keys
    are a hard error: a bundle written by a newer schema must fail loudly,
    not silently drop a field that changes the network."""
    d = dict(d)
    dist_d = d.pop("dist", None)
    known = {f.name for f in dataclasses.fields(D4PGConfig)}
    unknown = set(d) - known
    if unknown:
        raise ValueError(
            f"bundle agent config has unknown fields {sorted(unknown)}; "
            "re-export with this code or upgrade it"
        )
    if "hidden_sizes" in d:
        d["hidden_sizes"] = tuple(d["hidden_sizes"])
    if d.get("pixel_shape") is not None:
        d["pixel_shape"] = tuple(d["pixel_shape"])
    dist = DistConfig(**dist_d) if dist_d is not None else DistConfig()
    return D4PGConfig(dist=dist, **d)


@dataclass
class PolicyBundle:
    """A loaded bundle: the inference-time contract."""

    config: D4PGConfig
    actor_params: Any                      # numpy pytree, tree of the actor net
    action_low: np.ndarray                 # [action_dim] env-scale bounds
    action_high: np.ndarray
    obs_norm: Optional[dict]               # {"count","mean","m2"} or None
    meta: dict                             # provenance (env, step, source, …)
    path: Optional[str] = None             # directory it was loaded from

    @property
    def obs_dim(self) -> int:
        return self.config.obs_dim

    @property
    def action_dim(self) -> int:
        return self.config.action_dim


def actor_template(config: D4PGConfig):
    """A freshly-initialized actor params pytree with the bundle's shapes —
    the unflatten target for the saved leaves (and the shape validator)."""
    import jax

    from d4pg_tpu.agent.d4pg import build_networks

    actor, _ = build_networks(config)
    return actor.init(
        jax.random.PRNGKey(0), np.zeros((1, config.obs_dim), np.float32)
    )


def _save_leaves(path: str, params) -> None:
    import jax

    leaves = jax.tree_util.tree_leaves(jax.device_get(params))
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(
                f,
                **{f"leaf_{i:05d}": np.asarray(l) for i, l in enumerate(leaves)},
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def load_params(bundle_dir: str, config: D4PGConfig):
    """Restore the actor params pytree from a bundle directory, validating
    leaf count and shapes against a template built from ``config`` (a
    silently mis-shaped load would serve garbage actions)."""
    import jax

    template = actor_template(config)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(os.path.join(bundle_dir, PARAMS_FILE)) as z:
        leaves = [z[k] for k in sorted(z.files)]
    if len(leaves) != len(t_leaves):
        raise ValueError(
            f"bundle has {len(leaves)} param leaves, config implies "
            f"{len(t_leaves)} — config/params mismatch"
        )
    for i, (saved, want) in enumerate(zip(leaves, t_leaves)):
        if tuple(saved.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"bundle param leaf {i} has shape {tuple(saved.shape)}, "
                f"config implies {tuple(np.shape(want))}"
            )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def export_bundle(
    bundle_dir: str,
    config: D4PGConfig,
    actor_params,
    *,
    action_low=None,
    action_high=None,
    obs_norm_state: Optional[dict] = None,
    meta: Optional[dict] = None,
) -> str:
    """Write a serving bundle. Bounds default to the canonical (−1, 1) box
    (pure-JAX envs act in it natively; host adapters expose their Box via
    ``NormalizeAction``)."""
    os.makedirs(bundle_dir, exist_ok=True)
    low = np.full(config.action_dim, -1.0, np.float32) if action_low is None \
        else np.asarray(action_low, np.float32).reshape(config.action_dim)
    high = np.full(config.action_dim, 1.0, np.float32) if action_high is None \
        else np.asarray(action_high, np.float32).reshape(config.action_dim)
    if not np.all(high > low):
        raise ValueError("action_high must exceed action_low elementwise")
    # params FIRST, json second (write-ordering: the json is the attestation
    # a watcher reloads on)
    _save_leaves(os.path.join(bundle_dir, PARAMS_FILE), actor_params)
    doc = {
        "bundle_version": BUNDLE_VERSION,
        "agent": config_to_json(config),
        "action_low": low.tolist(),
        "action_high": high.tolist(),
        "obs_norm": obs_norm_state,
        "meta": meta or {},
    }
    meta_path = os.path.join(bundle_dir, META_FILE)
    fd, tmp = tempfile.mkstemp(dir=bundle_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, meta_path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    return bundle_dir


def load_bundle(bundle_dir: str) -> PolicyBundle:
    meta_path = os.path.join(bundle_dir, META_FILE)
    with open(meta_path) as f:
        doc = json.load(f)
    if doc.get("bundle_version") != BUNDLE_VERSION:
        raise ValueError(
            f"bundle_version {doc.get('bundle_version')!r} unsupported "
            f"(this code reads {BUNDLE_VERSION})"
        )
    config = config_from_json(doc["agent"])
    params = load_params(bundle_dir, config)
    obs_norm = doc.get("obs_norm")
    if obs_norm is not None and len(obs_norm.get("mean", [])) != config.obs_dim:
        raise ValueError(
            f"obs_norm stats are {len(obs_norm.get('mean', []))}-dim, "
            f"config.obs_dim is {config.obs_dim}"
        )
    return PolicyBundle(
        config=config,
        actor_params=params,
        action_low=np.asarray(doc["action_low"], np.float32),
        action_high=np.asarray(doc["action_high"], np.float32),
        obs_norm=obs_norm,
        meta=doc.get("meta", {}),
        path=os.path.abspath(bundle_dir),
    )


def bundle_mtime(bundle_dir: str) -> Optional[float]:
    """mtime of the bundle's json attestation (the hot-reload watch key);
    None when absent."""
    try:
        return os.stat(os.path.join(bundle_dir, META_FILE)).st_mtime
    except FileNotFoundError:
        return None
