"""Self-targeted connection-level attackers for the chaos harness.

Three ``--chaos`` sites (serve and router front-ends tick them at every
accept, so an attack launches while real traffic is in flight):

- ``slowloris@N[:bps]`` — connect and trickle a valid frame header at
  ``bps`` bytes/second, capped one byte short of a complete frame, then
  go silent mid-frame. The victim's read-progress deadline must evict
  it; no request is ever completed, so the answered identity is
  untouched by construction.
- ``zero_window@N[:ms]`` — connect with a tiny receive buffer, pipeline
  ``HEALTHZ`` bursts, and never read a byte. The victim's replies back
  up until its write-progress deadline (or buffered-bytes watermark)
  evicts the connection. ``HEALTHZ`` is outside the answered identity,
  so the books stay exact while ``evicted_write_stall`` moves.
- ``fd_exhaust@N[:ms]`` — hoard descriptors to EMFILE and hold them for
  ``ms``, driving the victim's listener into the reserve-fd shed path
  (``OVERLOADED fd_exhausted``) mid-accept instead of killing the
  accept loop.

Every attacker runs on the victim's OWN FrameLoop as a timer chain —
zero threads, zero selector registrations — and self-bounds: it stops
when evicted, when its budget expires, or at a hard tick cap.
"""

from __future__ import annotations

import os
import socket

from d4pg_tpu.serve import protocol

__all__ = ["tick_attacks"]

_SLOWLORIS_DEFAULT_BPS = 4.0
_ZERO_WINDOW_DEFAULT_MS = 1500.0
_FD_EXHAUST_DEFAULT_MS = 150.0
_ZERO_WINDOW_INTERVAL_S = 0.05
_ATTACK_MAX_TICKS = 2000  # hard safety bound per attacker


def tick_attacks(chaos, loop, host: str, port: int) -> None:
    """Tick the three connection-attack chaos sites; each fire launches
    one attacker against ``host:port`` driven by ``loop``'s timers."""
    e = chaos.tick("slowloris")
    if e is not None:
        _start_slowloris(
            loop, host, port, float(e.arg or _SLOWLORIS_DEFAULT_BPS)
        )
    e = chaos.tick("zero_window")
    if e is not None:
        _start_zero_window(
            loop, host, port, float(e.arg or _ZERO_WINDOW_DEFAULT_MS)
        )
    e = chaos.tick("fd_exhaust")
    if e is not None:
        _start_fd_exhaust(loop, float(e.arg or _FD_EXHAUST_DEFAULT_MS))


def _quiet_close(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _attack_socket(host: str, port: int, rcvbuf: int = 0):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf:
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        except OSError:
            pass  # stack refuses tiny buffers: the attack is just slower
    sock.setblocking(False)
    try:
        sock.connect_ex((host, port))
    except OSError:
        _quiet_close(sock)
        return None
    return sock


def _start_slowloris(loop, host: str, port: int, bps: float) -> None:
    sock = _attack_socket(host, port)
    if sock is None:
        return
    interval = 1.0 / max(0.5, bps)
    # a well-formed ACT frame minus its last byte: the victim sees an
    # eternally-incomplete frame, never an answerable request
    drip = protocol.encode_frame(protocol.ACT, 1, b"\x00" * 24)[:-1]
    state = {"i": 0, "ticks": 0}

    def _tick():
        state["ticks"] += 1
        if state["ticks"] > _ATTACK_MAX_TICKS:
            _quiet_close(sock)
            return
        try:
            if state["i"] < len(drip):
                # d4pglint: disable=loop-blocking-call  -- non-blocking attacker socket; EWOULDBLOCK tolerated
                state["i"] += sock.send(drip[state["i"]:state["i"] + 1])
            else:
                # trickle spent: sit silent mid-frame until evicted
                # d4pglint: disable=loop-blocking-call  -- non-blocking attacker socket; EWOULDBLOCK tolerated
                if sock.recv(4096) == b"":
                    _quiet_close(sock)  # victim hung up: eviction landed
                    return
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            _quiet_close(sock)  # reset by the victim: eviction landed
            return
        loop.call_later(interval, _tick)

    loop.call_later(interval, _tick)


def _start_zero_window(loop, host: str, port: int, ms: float) -> None:
    sock = _attack_socket(host, port, rcvbuf=4096)
    if sock is None:
        return
    # pipelined HEALTHZ storm the attacker will never read the replies of
    burst = b"".join(
        protocol.encode_frame(protocol.HEALTHZ, i + 1) for i in range(64)
    )
    budget_ticks = max(1, int((ms / 1e3) / _ZERO_WINDOW_INTERVAL_S))
    state = {"ticks": 0}

    def _tick():
        state["ticks"] += 1
        if state["ticks"] > min(_ATTACK_MAX_TICKS, budget_ticks):
            _quiet_close(sock)  # budget spent: release the victim
            return
        try:
            # never a recv: the receive window slams shut and stays shut
            # d4pglint: disable=loop-blocking-call  -- non-blocking attacker socket; EWOULDBLOCK tolerated
            sock.send(burst)
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            _quiet_close(sock)  # reset by the victim: eviction landed
            return
        loop.call_later(_ZERO_WINDOW_INTERVAL_S, _tick)

    loop.call_later(_ZERO_WINDOW_INTERVAL_S, _tick)


def _start_fd_exhaust(loop, hold_ms: float) -> None:
    hoard = []
    try:
        while True:
            hoard.append(os.open(os.devnull, os.O_RDONLY))
    except OSError:
        pass  # EMFILE reached: the table is full

    def _release():
        for fd in hoard:
            try:
                os.close(fd)
            except OSError:
                pass
        hoard.clear()

    loop.call_later(max(0.01, hold_ms / 1e3), _release)
