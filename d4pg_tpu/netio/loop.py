"""The event loop itself: FrameLoop + Connection.

One thread (``<name>`` from the constructor, default ``netio-loop``)
owns a ``selectors.DefaultSelector`` (epoll on linux — O(ready), not
O(connections), which is what makes 10k idle connections cheap) and is
the only thread that touches socket state. Everything other threads may
do — ``Connection.send`` from a batcher callback, ``call_soon``/
``call_later``, ``close`` — either takes the connection's queue lock or
marshals onto the loop via the callback queue + a socketpair wakeup.

Handler callbacks (``on_frame``/``on_open``/``on_close``/
``on_protocol_error``) run ON the loop thread and must not block: a
``time.sleep`` or a blocking socket call in a callback stalls every
connection on the loop. The ``loop-blocking-call`` d4pglint check
enforces this over the manifest in ``tools/d4pglint/config.py``; the
intentionally non-blocking socket calls inside this module carry
justified suppressions.
"""

from __future__ import annotations

import errno
import heapq
import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Optional

from d4pg_tpu.analysis import lockwitness
from d4pg_tpu.serve import protocol

__all__ = ["Connection", "FrameLoop", "configure_reply_timeout"]

#: Write-progress deadline default. The SAME number the thread-path
#: front-ends used to pass to SO_SNDTIMEO: a peer that drains nothing
#: for this long forfeits the connection.
DEFAULT_WRITE_STALL_S = 10.0
#: Read-progress (frame-completion) deadline default: once a partial
#: frame exists the peer has this long to finish it.
DEFAULT_READ_STALL_S = 30.0
#: Per-connection buffered-reply watermark: a never-reading peer can
#: make the server hold at most this many queued bytes before eviction.
DEFAULT_WRITE_BUFFER_LIMIT = 8 << 20

_RECV_CHUNK = 1 << 17
_ACCEPTS_PER_TICK = 64
_ACCEPT_BACKOFF_S = 0.1
# errnos that mean "out of descriptors/buffers", not "this one client
# misbehaved": shed admission-controlled instead of killing the loop.
_EXHAUSTION_ERRNOS = tuple(
    getattr(errno, n) for n in ("EMFILE", "ENFILE", "ENOBUFS", "ENOMEM")
    if hasattr(errno, n)
)


def configure_reply_timeout(sock, timeout_s: float = DEFAULT_WRITE_STALL_S) -> None:
    """Thread-path half of the write-deadline contract: bound every
    blocking reply write with SO_SNDTIMEO so one zero-window client
    times out (the writer then closes the connection) instead of
    wedging its reply thread forever. Loop-path front-ends do NOT use
    this — the FrameLoop's write-progress deadline is the same contract
    without a thread to wedge. Lives here so the logic exists once for
    every thread-path endpoint that still needs it (fleet ingest)."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            struct.pack("ll", int(timeout_s), 0),
        )
    except OSError:
        # best-effort (not all stacks expose it): the write-deadline is a
        # robustness bound, not a correctness requirement
        pass


class Connection:
    """One accepted socket on a :class:`FrameLoop`.

    Socket/selector/deadline state is loop-thread-owned. The outbound
    frame queue is the one cross-thread surface: :meth:`send` (any
    thread) appends under ``_lock``; the loop flushes. Identity (``id``
    of this object) is the per-connection key front-ends hand to taps
    and logs, exactly as the thread path keyed on the socket object.
    """

    # Loop-thread-owned fields (single writer: every mutation happens on
    # the loop thread; `send` only appends to the deque under _lock and
    # reads flags written under the same lock).
    _THREAD_SAFE = (
        "_out_off", "_read_deadline", "_write_deadline",
        "_read_timer_armed", "_write_timer_armed", "closed",
    )

    __slots__ = (
        "loop", "sock", "addr",
        "assembler",
        "_lock", "_out", "_out_bytes", "_out_off",
        "closed", "_close_requested",
        "_read_deadline", "_read_timer_armed",
        "_write_deadline", "_write_timer_armed",
    )

    def __init__(self, loop: "FrameLoop", sock, addr):
        self.loop = loop
        self.sock = sock
        self.addr = addr
        self.assembler = protocol.FrameAssembler()
        self._lock = lockwitness.named_lock("Connection._lock")
        self._out: deque = deque()      # encoded frames awaiting the kernel
        self._out_bytes = 0             # total queued (watermark input)
        self._out_off = 0               # sent bytes of the head frame
        self.closed = False
        self._close_requested = False
        self._read_deadline: Optional[float] = None
        self._read_timer_armed = False
        self._write_deadline: Optional[float] = None
        self._write_timer_armed = False

    # ------------------------------------------------------------- any thread
    def send(self, msg_type: int, req_id: int, payload: bytes = b"") -> bool:
        """Queue one frame (encoded via ``protocol.encode_frame`` — the
        byte-compat anchor) and kick the flush. Returns False when the
        connection is already closed/closing, so the caller can book a
        dropped reply — same contract as the thread path's OSError on a
        dead socket."""
        buf = protocol.encode_frame(msg_type, req_id, payload)
        with self._lock:
            if self.closed or self._close_requested:
                return False
            self._out.append(buf)
            self._out_bytes += len(buf)
        if self.loop.on_loop_thread():
            self.loop._flush(self)
        else:
            self.loop.call_soon(self.loop._flush, self)
        return True

    def close(self) -> None:
        """Flush whatever is queued, then close (the graceful path:
        ERROR-then-close, drain). The write-progress deadline still
        bounds the flush — a peer that will not drain it gets evicted,
        not waited on forever."""
        with self._lock:
            if self.closed or self._close_requested:
                return
            self._close_requested = True
        if self.loop.on_loop_thread():
            self.loop._flush(self)
        else:
            self.loop.call_soon(self.loop._flush, self)

    def abort(self) -> None:
        """Abortive close NOW (RST; queued frames dropped) — the chaos
        ``sock_reset`` teardown."""
        if self.loop.on_loop_thread():
            self.loop._teardown(self, abortive=True)
        else:
            self.loop.call_soon(self.loop._teardown, self, True)

    @property
    def write_backlog(self) -> int:
        """Queued-but-unsent reply bytes (tests/observability)."""
        with self._lock:
            return self._out_bytes

    def __repr__(self) -> str:
        return f"Connection({self.addr!r}, closed={self.closed})"


class FrameLoop:
    """The selectors loop. Construct, :meth:`serve` a listening socket,
    :meth:`start`; tear down with :meth:`stop_accepting` (drain step 1)
    then :meth:`close` (bounded flush of every connection, loop-thread
    join). Thread count is O(1) in connections: this thread is the only
    one netio ever creates."""

    # Loop state below is loop-thread-owned after start() (single
    # writer); cross-thread producers go through _cb_lock'd call_soon.
    # _tid is written once by the loop thread at startup and only read
    # elsewhere; _flush_deadline/_accept_paused flip on the loop thread;
    # _timer_seq is bumped only in _call_at, which always runs on the
    # loop (call_later marshals the heap push through call_soon).
    _THREAD_SAFE = (
        "_tid", "_stats", "_flush_deadline", "_accept_paused",
        "_reserve_fd", "_listener", "_stopping", "_timer_seq",
    )

    def __init__(
        self,
        *,
        name: str = "netio-loop",
        read_stall_s: float = DEFAULT_READ_STALL_S,
        write_stall_s: float = DEFAULT_WRITE_STALL_S,
        write_buffer_limit: int = DEFAULT_WRITE_BUFFER_LIMIT,
    ):
        self.name = name
        self.read_stall_s = float(read_stall_s)
        self.write_stall_s = float(write_stall_s)
        self.write_buffer_limit = int(write_buffer_limit)
        self._selector = selectors.DefaultSelector()
        self._conns: set = set()
        self._listener = None
        self._on_frame: Optional[Callable] = None
        self._on_open: Optional[Callable] = None
        self._on_close: Optional[Callable] = None
        self._on_protocol_error: Optional[Callable] = None
        self._thread: Optional[threading.Thread] = None
        self._tid: Optional[int] = None
        self._stopping = threading.Event()
        self._flush_deadline: Optional[float] = None
        # cross-thread → loop marshalling
        self._cb_lock = lockwitness.named_lock("FrameLoop._cb_lock")
        self._callbacks: deque = deque()
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._waker_w.setblocking(False)
        self._selector.register(self._waker_r, selectors.EVENT_READ, "waker")
        # timers: (when, seq, fn) min-heap, loop-thread-owned
        self._timers: list = []
        self._timer_seq = 0
        # EMFILE shed machinery: one fd held in reserve so a full table
        # can still accept-reply-close instead of wedging the listener
        self._reserve_fd: Optional[int] = None
        self._accept_paused = False
        self._shed_reply = protocol.encode_frame(
            protocol.OVERLOADED, 0, b"fd_exhausted"
        )
        # loop counters: loop-thread single-writer; stats() copies (int
        # reads are atomic under the GIL — same contract as gauges)
        self._stats = {
            "conns_open": 0,
            "conns_total": 0,
            "frames_in": 0,
            "frames_out": 0,
            "dropped_frames": 0,
            "evicted_read_stall": 0,
            "evicted_write_stall": 0,
            "accept_shed": 0,
            "accept_backoffs": 0,
            "accept_errors": 0,
        }

    # ------------------------------------------------------------------ setup
    def serve(
        self,
        listen_sock,
        *,
        on_frame: Callable,
        on_open: Optional[Callable] = None,
        on_close: Optional[Callable] = None,
        on_protocol_error: Optional[Callable] = None,
    ) -> None:
        """Adopt a listening socket (``socket.create_server`` result) and
        the frame handler. Must be called before :meth:`start`.

        - ``on_frame(conn, msg_type, req_id, payload)`` — one complete
          frame. Raising :class:`protocol.ProtocolError` routes to the
          protocol-error path (reply-and-close), exactly like a framing
          error from the assembler.
        - ``on_open(conn)`` / ``on_close(conn)`` — connection lifecycle
          (close fires exactly once per opened connection).
        - ``on_protocol_error(conn, exc)`` — framing/decode violation;
          after it returns the loop flush-closes the connection. Default:
          reply ``ERROR`` req_id 0 and close (the thread-path contract).
        """
        if self._thread is not None:
            raise RuntimeError("serve() must precede start()")
        listen_sock.setblocking(False)
        self._listener = listen_sock
        self._on_frame = on_frame
        self._on_open = on_open
        self._on_close = on_close
        self._on_protocol_error = on_protocol_error
        self._selector.register(listen_sock, selectors.EVENT_READ, "accept")
        try:
            self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
        except OSError:
            self._reserve_fd = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()

    def on_loop_thread(self) -> bool:
        return threading.get_ident() == self._tid

    # --------------------------------------------------------- cross-thread API
    def call_soon(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the loop thread, soon. Threadsafe."""
        with self._cb_lock:
            self._callbacks.append((fn, args))
        self._wake()

    def call_later(self, delay_s: float, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the loop thread after ``delay_s``.
        Threadsafe (marshals the heap push onto the loop)."""
        when = time.monotonic() + max(0.0, delay_s)
        if self.on_loop_thread():
            self._call_at(when, fn, *args)
        else:
            self.call_soon(self._call_at, when, fn, *args)

    def connections(self) -> list:
        """Snapshot of open connections (drain/observability)."""
        return list(self._conns)

    def stats(self) -> dict:
        """Loop counters snapshot (healthz's ``netio`` section)."""
        return dict(self._stats)

    def stop_accepting(self, timeout_s: float = 2.0) -> None:
        """Close the listener (drain step 1: no new connections; every
        open connection keeps being served). Synchronous up to
        ``timeout_s``; safe to call twice."""
        if self._thread is None or not self._thread.is_alive():
            self._close_listener()
            return
        done = threading.Event()

        def _do():
            self._close_listener()
            done.set()

        self.call_soon(_do)
        done.wait(timeout_s)

    def close(self, flush_timeout_s: float = 5.0) -> None:
        """Stop the loop: no new connections, flush every connection's
        queued replies (bounded by ``flush_timeout_s`` AND the write-
        progress deadline), close them, join the loop thread. Idempotent."""
        if self._thread is None:
            # never started: tear down directly (tests, failed start)
            self._stopping.set()
            self._close_listener()
            for conn in list(self._conns):
                self._teardown(conn)
            self._final_cleanup()
            return
        self.call_soon(self._begin_shutdown, flush_timeout_s)
        self._thread.join(timeout=flush_timeout_s + 5.0)

    # ------------------------------------------------------------ loop thread
    def _run(self) -> None:
        self._tid = threading.get_ident()
        while True:
            now = time.monotonic()
            if self._stopping.is_set():
                if not self._conns or (
                    self._flush_deadline is not None
                    and now >= self._flush_deadline
                ):
                    break
            timeout = self._select_timeout(now)
            try:
                events = self._selector.select(timeout)
            except OSError as e:
                # transient (EINTR-shaped); a poisoned selector would
                # spin here, so say so loudly and keep going — conns are
                # still torn down by deadlines/callbacks
                print(f"[netio] {self.name}: select failed: {e}", flush=True)
                events = []
            for key, mask in events:
                data = key.data
                if data == "accept":
                    self._do_accept()
                elif data == "waker":
                    self._drain_waker()
                else:
                    conn = data
                    if mask & selectors.EVENT_WRITE and not conn.closed:
                        self._flush(conn)
                    if mask & selectors.EVENT_READ and not conn.closed:
                        self._on_readable(conn)
            self._run_timers()
            self._run_callbacks()
        # loop exit: drop whatever is left, then release loop resources
        for conn in list(self._conns):
            self._teardown(conn)
        self._final_cleanup()

    def _select_timeout(self, now: float) -> float:
        with self._cb_lock:
            if self._callbacks:
                return 0.0
        if self._timers:
            return min(max(0.0, self._timers[0][0] - now), 0.5)
        return 0.5

    def _drain_waker(self) -> None:
        try:
            # d4pglint: disable=loop-blocking-call  -- non-blocking socketpair read; drains the wakeup bytes
            while self._waker_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _run_callbacks(self) -> None:
        while True:
            with self._cb_lock:
                if not self._callbacks:
                    return
                fn, args = self._callbacks.popleft()
            try:
                fn(*args)
            except Exception as e:  # a bad callback must not kill the loop
                print(f"[netio] {self.name}: callback failed: {e!r}", flush=True)

    def _call_at(self, when: float, fn: Callable, *args) -> None:
        self._timer_seq += 1
        heapq.heappush(self._timers, (when, self._timer_seq, fn, args))

    def _run_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _when, _seq, fn, args = heapq.heappop(self._timers)
            try:
                fn(*args)
            except Exception as e:  # a bad timer must not kill the loop
                print(f"[netio] {self.name}: timer failed: {e!r}", flush=True)

    def _wake(self) -> None:
        try:
            self._waker_w.send(b"\x01")
        except (BlockingIOError, InterruptedError):
            pass  # wake pipe full = loop is already waking
        except OSError:
            pass  # loop torn down

    # ----------------------------------------------------------------- accept
    def _do_accept(self) -> None:
        for _ in range(_ACCEPTS_PER_TICK):
            if self._listener is None:
                return
            try:
                # d4pglint: disable=loop-blocking-call  -- non-blocking listener; EWOULDBLOCK caught below
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                if self._stopping.is_set() or self._listener is None:
                    return
                if e.errno in _EXHAUSTION_ERRNOS:
                    self._shed_accept()
                    return
                if e.errno in (errno.EBADF, errno.EINVAL):
                    # listener died under us without a drain: loud, and
                    # stop selecting on it — the rest of the loop lives on
                    print(
                        f"[netio] {self.name}: accept loop dead: {e}",
                        flush=True,
                    )
                    self._close_listener()
                    return
                self._stats["accept_errors"] += 1
                return  # transient (ECONNABORTED et al.); selector re-fires
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # reply latency tweak only; not fatal
            conn = Connection(self, sock, addr)
            self._conns.add(conn)
            self._stats["conns_open"] += 1
            self._stats["conns_total"] += 1
            try:
                self._selector.register(
                    sock, selectors.EVENT_READ, conn
                )
            except (ValueError, KeyError, OSError) as e:
                print(f"[netio] {self.name}: register failed: {e}", flush=True)
                self._teardown(conn)
                continue
            if self._on_open is not None:
                try:
                    self._on_open(conn)
                except Exception as e:
                    print(
                        f"[netio] {self.name}: on_open failed: {e!r}",
                        flush=True,
                    )

    def _shed_accept(self) -> None:
        """Descriptor table full mid-accept. Burn the reserve fd to
        accept exactly one waiting connection, answer it ``OVERLOADED
        fd_exhausted`` best-effort, close it, reopen the reserve — the
        client gets an explicit admission-controlled shed and the accept
        loop survives. If even the reserve cannot reopen, pause
        accepting briefly instead of spinning on a perpetually-ready
        listener."""
        if self._reserve_fd is not None:
            try:
                os.close(self._reserve_fd)
            except OSError:
                pass
            self._reserve_fd = None
            sock = None
            try:
                # d4pglint: disable=loop-blocking-call  -- non-blocking listener, freed-fd one-shot accept
                sock, _addr = self._listener.accept()
            except OSError:
                sock = None
            if sock is not None:
                try:
                    sock.setblocking(False)
                    # d4pglint: disable=loop-blocking-call  -- non-blocking best-effort shed reply
                    sock.send(self._shed_reply)
                except OSError:
                    pass  # best-effort: the close below is the real answer
                try:
                    sock.close()
                except OSError:
                    pass
                self._stats["accept_shed"] += 1
            try:
                self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
            except OSError:
                self._reserve_fd = None
        if self._reserve_fd is None and not self._accept_paused \
                and self._listener is not None:
            # still exhausted: stop selecting on the listener for a beat
            self._accept_paused = True
            self._stats["accept_backoffs"] += 1
            try:
                self._selector.unregister(self._listener)
            except (KeyError, ValueError, OSError):
                pass
            self._call_at(
                time.monotonic() + _ACCEPT_BACKOFF_S, self._resume_accept
            )

    def _resume_accept(self) -> None:
        self._accept_paused = False
        if self._listener is None or self._stopping.is_set():
            return
        if self._reserve_fd is None:
            try:
                self._reserve_fd = os.open(os.devnull, os.O_RDONLY)
            except OSError:
                self._reserve_fd = None
        try:
            self._selector.register(self._listener, selectors.EVENT_READ,
                                    "accept")
        except (KeyError, ValueError, OSError):
            pass

    def _close_listener(self) -> None:
        if self._listener is None:
            return
        try:
            self._selector.unregister(self._listener)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._listener = None

    # ------------------------------------------------------------------- read
    def _on_readable(self, conn: Connection) -> None:
        try:
            # d4pglint: disable=loop-blocking-call  -- non-blocking socket; EWOULDBLOCK caught below
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._teardown(conn)
            return
        if not data:
            try:
                conn.assembler.check_eof()
            except protocol.ProtocolError as e:
                self._protocol_error(conn, e)
            else:
                self._teardown(conn)  # clean EOF at a frame boundary
            return
        conn.assembler.feed(data)
        completed = 0
        try:
            while True:
                frame = conn.assembler.next_frame()
                if frame is None:
                    break
                completed += 1
                self._stats["frames_in"] += 1
                self._on_frame(conn, *frame)
                if conn.closed:
                    return  # handler tore it down (chaos sock_reset)
        except protocol.ProtocolError as e:
            self._protocol_error(conn, e)
            return
        except OSError:
            self._teardown(conn)
            return
        except Exception as e:
            # a handler bug must cost one connection, never the loop
            print(
                f"[netio] {self.name}: on_frame failed: {e!r}", flush=True
            )
            self._teardown(conn)
            return
        # Read-progress deadline: arm on entering mid-frame, RE-arm only
        # on frame completion — so a slowloris drip (bytes but never a
        # frame) cannot reset its clock, while a busy pipeliner whose
        # buffer always holds a partial tail never gets evicted.
        if conn.assembler.mid_frame:
            if completed or conn._read_deadline is None:
                conn._read_deadline = time.monotonic() + self.read_stall_s
                if not conn._read_timer_armed:
                    conn._read_timer_armed = True
                    self._call_at(conn._read_deadline,
                                  self._check_read_deadline, conn)
        else:
            conn._read_deadline = None

    def _check_read_deadline(self, conn: Connection) -> None:
        conn._read_timer_armed = False
        if conn.closed or conn._read_deadline is None:
            return
        now = time.monotonic()
        if now < conn._read_deadline:  # progress since this timer was set
            conn._read_timer_armed = True
            self._call_at(conn._read_deadline,
                          self._check_read_deadline, conn)
            return
        self._stats["evicted_read_stall"] += 1
        self._evict(conn,
                    f"read stall: frame incomplete after {self.read_stall_s}s")

    # ------------------------------------------------------------------ write
    def _flush(self, conn: Connection) -> None:
        """Push queued frames into the kernel until it stops taking them.
        Loop thread only (cross-thread senders marshal via call_soon)."""
        if conn.closed:
            return
        progressed = False
        while True:
            with conn._lock:
                if not conn._out:
                    break
                head = conn._out[0]
            try:
                # d4pglint: disable=loop-blocking-call  -- non-blocking socket; EWOULDBLOCK caught below
                n = conn.sock.send(
                    memoryview(head)[conn._out_off:]
                )
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._teardown(conn)
                return
            if n <= 0:
                break
            progressed = True
            conn._out_off += n
            if conn._out_off >= len(head):
                conn._out_off = 0
                with conn._lock:
                    conn._out.popleft()
                    conn._out_bytes -= len(head)
                self._stats["frames_out"] += 1
        with conn._lock:
            pending = conn._out_bytes
        if pending:
            if pending > self.write_buffer_limit:
                # watermark breach: the peer is not draining and the
                # backlog is past what we are willing to hold for it
                self._stats["evicted_write_stall"] += 1
                self._evict(
                    conn,
                    f"write backlog {pending} bytes > limit "
                    f"{self.write_buffer_limit}",
                )
                return
            self._set_mask(conn, selectors.EVENT_READ | selectors.EVENT_WRITE)
            # Write-progress deadline: (re)armed on any kernel progress,
            # first armed when the backlog appears — SO_SNDTIMEO's
            # "no progress for N seconds" contract, loop-owned.
            if progressed or conn._write_deadline is None:
                conn._write_deadline = time.monotonic() + self.write_stall_s
                if not conn._write_timer_armed:
                    conn._write_timer_armed = True
                    self._call_at(conn._write_deadline,
                                  self._check_write_deadline, conn)
        else:
            conn._write_deadline = None
            self._set_mask(conn, selectors.EVENT_READ)
            if conn._close_requested:
                self._teardown(conn)

    def _check_write_deadline(self, conn: Connection) -> None:
        conn._write_timer_armed = False
        if conn.closed or conn._write_deadline is None:
            return
        now = time.monotonic()
        if now < conn._write_deadline:
            conn._write_timer_armed = True
            self._call_at(conn._write_deadline,
                          self._check_write_deadline, conn)
            return
        self._stats["evicted_write_stall"] += 1
        self._evict(
            conn,
            f"write stall: peer drained nothing for {self.write_stall_s}s",
        )

    def _set_mask(self, conn: Connection, mask: int) -> None:
        try:
            key = self._selector.get_key(conn.sock)
        except (KeyError, ValueError):
            return
        if key.events != mask:
            try:
                self._selector.modify(conn.sock, mask, conn)
            except (KeyError, ValueError, OSError):
                pass

    # --------------------------------------------------------------- teardown
    def _protocol_error(self, conn: Connection, exc) -> None:
        handler = self._on_protocol_error
        if handler is not None:
            try:
                handler(conn, exc)
            except Exception as e:
                print(
                    f"[netio] {self.name}: on_protocol_error failed: {e!r}",
                    flush=True,
                )
        else:
            conn.send(protocol.ERROR, 0, str(exc).encode("utf-8"))
        conn.close()  # flush the ERROR, then FIN (write deadline bounds it)

    def _evict(self, conn: Connection, reason: str) -> None:
        """Deadline/watermark eviction: best-effort one-shot ERROR notice
        (the peer's read side may still be intact), then immediate
        teardown — never a flush wait on a peer that already proved it
        will not drain."""
        try:
            # d4pglint: disable=loop-blocking-call  -- non-blocking one-shot courtesy notice; EWOULDBLOCK acceptable
            conn.sock.send(
                protocol.encode_frame(
                    protocol.ERROR, 0, reason.encode("utf-8")
                )
            )
        except OSError:
            pass
        self._teardown(conn)

    def _teardown(self, conn: Connection, abortive: bool = False) -> None:
        with conn._lock:
            if conn.closed:
                return
            conn.closed = True
            leftover = len(conn._out)
            conn._out.clear()
            conn._out_bytes = 0
        self._stats["dropped_frames"] += leftover
        conn._read_deadline = None
        conn._write_deadline = None
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        if abortive:
            protocol.abortive_close(conn.sock)
        else:
            try:
                conn.sock.close()
            except OSError:
                pass
        if conn in self._conns:
            self._conns.discard(conn)
            self._stats["conns_open"] -= 1
            if self._on_close is not None:
                try:
                    self._on_close(conn)
                except Exception as e:
                    print(
                        f"[netio] {self.name}: on_close failed: {e!r}",
                        flush=True,
                    )

    def _begin_shutdown(self, flush_timeout_s: float) -> None:
        self._close_listener()
        if not self._stopping.is_set():
            self._stopping.set()
            self._flush_deadline = time.monotonic() + flush_timeout_s
        for conn in list(self._conns):
            conn.close()  # flush-then-close; deadlines bound the flush

    def _final_cleanup(self) -> None:
        self._close_listener()
        try:
            self._selector.unregister(self._waker_r)
        except (KeyError, ValueError, OSError):
            pass
        for s in (self._waker_r, self._waker_w):
            try:
                s.close()
            except OSError:
                pass
        if self._reserve_fd is not None:
            try:
                os.close(self._reserve_fd)
            except OSError:
                pass
            self._reserve_fd = None
        try:
            self._selector.close()
        except OSError:
            pass
