"""One event-loop I/O core for every frame-speaking front-end.

``d4pg_tpu.netio`` is the C10k seam (ROADMAP item 4): a single
``selectors``-based loop thread owns every accepted connection — reads,
frame reassembly hand-off, buffered writes, per-connection progress
deadlines, and bounded accept — so a front-end holds tens of thousands
of mostly-idle connections with O(1) threads instead of one thread per
connection. The serve and router front-ends run on it (PR 20); the
fleet ingest keeps its thread path for now and adopts this seam next.

Division of labor (the PROTOCOL_WIRE_MODULES rule): this package moves
bytes and enforces *liveness* — it never parses or builds a frame
header. Framing lives in ``d4pg_tpu.serve.protocol``
(:class:`~d4pg_tpu.serve.protocol.FrameAssembler` on the read side,
:func:`~d4pg_tpu.serve.protocol.encode_frame` on the write side), so the
loop path is byte-identical to the blocking ``read_frame``/
``write_frame`` path by construction.

Robustness contract (docs/serving.md "Event-loop I/O core"):

- **read-progress deadline** — once a partial frame exists, the peer has
  ``read_stall_s`` to complete it; trickling bytes does not reset the
  clock (a slowloris drip never does), completing a frame does. Expiry
  evicts the connection.
- **write-progress deadline** — while reply bytes are buffered, the peer
  must drain *something* every ``write_stall_s`` (the SO_SNDTIMEO
  close-on-timeout contract, now loop-owned: one zero-window client
  stalls only itself, never a reply thread). A per-connection buffered-
  bytes watermark (``write_buffer_limit``) bounds what a never-reading
  peer can make the server hold.
- **bounded accept** — EMFILE/ENFILE mid-accept sheds the connection
  admission-controlled (a reserve fd is burned to accept + answer
  ``OVERLOADED fd_exhausted`` + close) instead of killing the accept
  loop; if even the reserve cannot reopen, accepting pauses briefly
  rather than spinning.

This package is JAX-free and numpy-free (host-only, stdlib + protocol):
thin front-ends must import it without paying the JAX import.
"""

from d4pg_tpu.netio.loop import (
    Connection,
    FrameLoop,
    configure_reply_timeout,
)

__all__ = ["Connection", "FrameLoop", "configure_reply_timeout"]
