"""League training (ISSUE 15): a crash-consistent PBT controller.

``python -m d4pg_tpu.league`` supervises N variant learners — each its
own run dir, fleet port, and hyperparameter genome — and runs seeded
exploit/explore over them: kill the worst quartile (SIGTERM drain →
bounded group SIGKILL, the exit-75 contract), clone the best via a
manifest-verified checkpoint FORK, perturb the genome, restart under
``--resume``, and gate the clone through an observe→promote|rollback
window (the canary state-machine shape). The controller itself journals
every durable decision to an atomically-written ``league.json`` so a
kill -9 mid-generation restarts, re-adopts still-live learners, and
never double-books a generation. Provably JAX-free (HOST_ONLY_MODULES).

See docs/league.md.
"""

from d4pg_tpu._lazy import lazy_exports

__getattr__, __dir__ = lazy_exports(
    __name__,
    {
        "LeagueController": "d4pg_tpu.league.controller",
        "LeagueConfig": "d4pg_tpu.league.controller",
        "perturb_genome": "d4pg_tpu.league.controller",
    },
)
