"""``python -m d4pg_tpu.league`` — the league controller CLI.

Everything after ``--`` is the BASE learner command; the controller
appends per-variant flags (genome, ``--log-dir``, ``--seed``,
``--variant-id``, ``--league-generation``, ``--resume``, and the fleet
wiring in fleet mode). Example — a seeded 3-variant league of real
train.py learners on localhost::

    python -m d4pg_tpu.league --dir /tmp/league --seed 7 --generations 1 \\
        --genome 'lr_actor=1e-4,max_episode_steps=50' \\
        --genome 'lr_actor=1e-4,max_episode_steps=200' \\
        --genome 'lr_actor=3e-4,max_episode_steps=200' \\
        -- python train.py --env Pendulum-v1 --hidden-sizes 16,16 \\
           --warmup 16 --bsize 8 --rmsize 512 --num-envs 1 \\
           --eval-interval 4 --eval-episodes 1 --checkpoint-interval 4 \\
           --total-steps 100000

SIGTERM/SIGINT stop the league gracefully (every learner drained, every
process group swept); kill -9 is the supported crash — rerun the same
command and the journal resumes the same generation. See docs/league.md.
"""

from __future__ import annotations

import argparse
import shlex
import sys


def parse_genome(spec: str) -> dict:
    """``k=v,k=v`` with numeric values (ints stay ints: batch_size and
    friends are structural)."""
    genome = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, sep, v = tok.partition("=")
        if not sep:
            raise ValueError(f"bad genome entry {tok!r} (want key=value)")
        try:
            genome[k.strip()] = int(v)
        except ValueError:
            genome[k.strip()] = float(v)
    if not genome:
        raise ValueError(f"empty genome spec {spec!r}")
    return genome


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m d4pg_tpu.league",
        description="crash-consistent PBT league controller "
                    "(docs/league.md)",
    )
    p.add_argument("--dir", required=True,
                   help="league root: per-variant run dirs (v0001, ...), "
                        "the league.json journal, league_events.jsonl, "
                        "league_summary.json")
    p.add_argument("--genome", action="append", required=True,
                   metavar="K=V,K=V",
                   help="one per variant slot (repeat N times): the seed "
                        "population's hyperparameter genomes")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--generations", type=int, default=1,
                   help="exploit/explore cycles to run before draining")
    p.add_argument("--poll-interval", type=float, default=0.5)
    p.add_argument("--gen-timeout", type=float, default=600.0,
                   help="force the exploit/explore decision on whatever "
                        "fitness exists after this many seconds")
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   help="SIGTERM -> group-SIGKILL escalation bound per "
                        "learner (the exit-75 drain window)")
    p.add_argument("--attest-timeout", type=float, default=180.0,
                   help="a forked clone must re-attest (trainer_meta "
                        "under its own variant id) within this, else "
                        "rollback")
    p.add_argument("--observe-timeout", type=float, default=300.0,
                   help="an attested clone must produce a fitness "
                        "reading within this, else rollback")
    p.add_argument("--fork-depth", type=int, default=2,
                   help="intact checkpoint steps copied per fork (>1 "
                        "gives the clone restore-fallback depth)")
    p.add_argument("--restart-attempts", type=int, default=4,
                   help="per-variant seeded Backoff budget before a "
                        "crash-looping variant is quarantined")
    p.add_argument("--fitness", choices=["metrics", "best_eval"],
                   default="metrics",
                   help="fitness signal: newest eval row in "
                        "metrics.jsonl (default; best_eval.json is the "
                        "fallback either way)")
    p.add_argument("--fleet-base-port", type=int, default=0,
                   help="fleet mode: slot i's learner ingests on "
                        "PORT+i with --num-envs 0 and publishes its "
                        "bundle; 0 = local collection")
    p.add_argument("--actors-per-variant", type=int, default=0,
                   help="fleet mode: actor hosts spawned per slot, "
                        "pinned to the slot's current variant id "
                        "(re-pointed when the variant is replaced)")
    p.add_argument("--actor-args", default="",
                   help="extra args for spawned fleet actor hosts")
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="controller chaos sites: variant_kill@N / "
                        "controller_kill@N (per control tick), "
                        "clone_corrupt@N (per fork)")
    p.add_argument("--summary-out", default=None,
                   help="also write the end-of-run summary artifact "
                        "(league_soak.json schema) here")
    p.add_argument("--debug-guards", action="store_true",
                   help="arm the conservation ledger: the end-of-run "
                        "summary re-checks every variant's process-tenure "
                        "identity against the FLOW_IDENTITIES manifest "
                        "and raises on imbalance")
    p.add_argument("learner", nargs=argparse.REMAINDER,
                   help="-- then the base learner command")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    learner = list(args.learner)
    if learner and learner[0] == "--":
        learner = learner[1:]
    if not learner:
        raise SystemExit(
            "no learner command: pass it after `--`, e.g. "
            "`... -- python train.py --env Pendulum-v1 ...`"
        )
    try:
        genomes = [parse_genome(g) for g in args.genome]
    except ValueError as e:
        raise SystemExit(str(e))
    if args.debug_guards:
        from d4pg_tpu.analysis import flowledger

        flowledger.enable()
    from d4pg_tpu.league.controller import LeagueConfig, LeagueController

    config = LeagueConfig(
        league_dir=args.dir,
        learner_argv=learner,
        genomes=genomes,
        seed=args.seed,
        generations=args.generations,
        poll_interval_s=args.poll_interval,
        gen_timeout_s=args.gen_timeout,
        drain_timeout_s=args.drain_timeout,
        attest_timeout_s=args.attest_timeout,
        observe_timeout_s=args.observe_timeout,
        fork_depth=args.fork_depth,
        restart_max_attempts=args.restart_attempts,
        fitness_source=args.fitness,
        fleet_base_port=args.fleet_base_port,
        actors_per_variant=args.actors_per_variant,
        # shlex: a quoted value with spaces (an actor --chaos plan) must
        # survive tokenization intact, not ship literal quote characters
        actor_argv=shlex.split(args.actor_args) if args.actor_args else [],
        chaos=args.chaos,
        summary_out=args.summary_out,
    )
    controller = LeagueController(config)
    from d4pg_tpu.utils.signals import install_graceful_signals

    install_graceful_signals(
        controller.request_stop,
        "[signal] {sig}: draining the league "
        "(second signal hard-kills)",
    )
    return controller.run()


if __name__ == "__main__":
    sys.exit(main())
