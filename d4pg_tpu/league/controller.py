"""The league controller: crash-consistent PBT over N variant learners.

Population Based Training (Jaderberg et al., 2017) and AlphaStar-style
league training (Vinyals et al., 2019) are exploit/explore loops over a
POPULATION of learners — exactly the workload the repo's single-learner
infrastructure (fleet HELLO negotiation, bundle lineage, crash-consistent
checkpoints, canary promote/rollback) generalizes to, IF deliberately
killing, cloning, and restarting learners is a safe, supervised,
resumable operation. This module makes it one:

**Population.** Each variant = its own run dir (``<league>/v<uid>``),
its own hyperparameter GENOME (serialized to ``variant.json``, the fork
commit record), its own seed, and — in fleet mode — its own ingest port
whose HELLO capability vector carries the variant id, so actor hosts
assigned to variant A can never stream into variant B's replay.

**Exploit/explore.** Every league generation: rank members on a fitness
signal read from each variant's metrics rows / ``best_eval.json``, kill
the worst quartile (SIGTERM drain → bounded group SIGKILL — the repo's
exit-75 preemption contract, through ``utils/procs.drain_or_kill``),
CLONE the best via checkpoint fork — copy the newest *manifest-verified*
steps through ``runtime/manifest.py`` (the same digests
``CheckpointManager.restore_verified`` trusts), perturb the genome,
restart under ``--resume`` — then gate the clone through the canary
state-machine shape: attest (the clone's ``trainer_meta.json`` must
re-appear under the clone's OWN variant id, proving the fork restored
and training progressed) → observe → promote | rollback (kill the clone,
re-fork the parent's unperturbed recipe).

**Crash consistency.** Every durable decision journals to an
atomically-written ``league.json`` BEFORE its effects are relied on, and
every apply step is idempotent, so a controller ``kill -9`` at any
instant restarts into the SAME generation: still-live learners (their
own setsid sessions — they outlive us) are re-adopted by PID + /proc
cmdline match, dead ones restart under per-variant seeded
``utils/retry.Backoff`` and quarantine when crash-looping (the
actor-pool discipline), and a half-applied generation replays its
recorded decisions instead of drawing new ones — a generation is never
double-booked. Process tenures are accounted exactly
(``spawned + adopted == exited_0 + exited_75 + exited_err + killed +
live`` per variant — schema-gated in the committed soak artifact).

Deliberately JAX-free (stdlib only; HOST_ONLY_MODULES-enforced): the
controller moves processes and JSON, never tensors.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from d4pg_tpu.analysis import flowledger
from d4pg_tpu.runtime import manifest as ckpt_manifest
from d4pg_tpu.utils import procs
from d4pg_tpu.utils.retry import Backoff

JOURNAL_SCHEMA = "league/v1"

# Genome key -> train.py flag. A genome is a plain dict over this
# vocabulary; unknown keys are refused at config parse so a typo cannot
# silently become a no-op hyperparameter.
GENOME_FLAGS = {
    "lr_actor": "--lr-actor",
    "lr_critic": "--lr-critic",
    "noise_epsilon": "--noise-epsilon",
    "tau": "--tau",
    "batch_size": "--bsize",
    "n_step": "--n-step",
    "max_episode_steps": "--max-steps",
}
# Multiplicative explore set (the PBT paper's resample-or-perturb,
# perturb half): continuous knobs only — integer/structural genes
# (batch_size, n_step, max_episode_steps) pass through unperturbed
# because they change compiled shapes / the MDP itself.
PERTURB_KEYS = ("lr_actor", "lr_critic", "noise_epsilon", "tau")
PERTURB_FACTORS = (0.8, 1.25)


def perturb_genome(genome: dict, rng: random.Random) -> dict:
    """The explore step: each continuous gene independently ×0.8 or
    ×1.25 (seeded — a league run's whole decision sequence replays)."""
    out = dict(genome)
    for k in PERTURB_KEYS:
        if k in out:
            out[k] = float(out[k]) * rng.choice(PERTURB_FACTORS)
    return out


def genome_argv(genome: dict) -> List[str]:
    argv: List[str] = []
    for k, v in sorted(genome.items()):
        flag = GENOME_FLAGS.get(k)
        if flag is None:
            raise ValueError(
                f"unknown genome key {k!r} (known: {sorted(GENOME_FLAGS)})"
            )
        argv += [flag, repr(v) if not isinstance(v, str) else v]
    return argv


def _atomic_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


@dataclass
class LeagueConfig:
    league_dir: str
    learner_argv: List[str]          # base learner command (after `--`)
    genomes: List[dict]              # one per slot (the seed population)
    seed: int = 0
    generations: int = 1
    poll_interval_s: float = 0.5
    gen_timeout_s: float = 600.0     # force a generation on stale fitness
    drain_timeout_s: float = 60.0    # SIGTERM -> SIGKILL escalation bound
    attest_timeout_s: float = 180.0  # fork must re-attest within this
    observe_timeout_s: float = 300.0  # ...and produce a fitness reading
    fork_depth: int = 2              # intact steps copied per fork
    restart_max_attempts: int = 4    # per-variant Backoff budget
    fitness_source: str = "metrics"  # metrics | best_eval
    # fleet mode: per-slot ingest ports + per-variant actor hosts
    fleet_base_port: int = 0         # 0 = local collection (no fleet)
    actors_per_variant: int = 0
    actor_argv: List[str] = field(default_factory=list)
    chaos: Optional[str] = None
    summary_out: Optional[str] = None


class LeagueController:
    """See the module docstring. Construct, then :meth:`run`."""

    def __init__(self, config: LeagueConfig, spawnlib=None):
        if len(config.genomes) < 2:
            raise ValueError(
                f"a league needs >= 2 variants, got {len(config.genomes)}"
            )
        for g in config.genomes:
            genome_argv(g)  # validates keys
        self.config = config
        self.dir = os.path.abspath(config.league_dir)
        os.makedirs(self.dir, exist_ok=True)
        self._spawnlib = spawnlib if spawnlib is not None \
            else procs.load_spawnlib()
        self._rng = random.Random(config.seed)
        self._chaos = None
        if config.chaos:
            from d4pg_tpu.chaos import ChaosInjector, ChaosPlan

            self._chaos = ChaosInjector(ChaosPlan.parse(config.chaos))
        self._stop = False
        # runtime-only (never journaled — a restart re-arms them)
        self._handles: Dict[int, object] = {}       # uid -> Spawned
        self._actor_handles: Dict[int, list] = {}   # slot -> [Spawned]
        self._backoffs: Dict[int, Backoff] = {}
        self._retry_at: Dict[int, float] = {}
        self._spawned_at: Dict[int, float] = {}
        # actor-host respawn pacing: same seeded-Backoff discipline the
        # learners get — a crash-looping actor must never become a
        # spawn-per-tick storm, and a slot that burns the budget stops
        # getting actors (logged) instead of respawning forever
        self._actor_backoffs: Dict[int, Backoff] = {}
        self._actor_retry_at: Dict[int, float] = {}
        self._actor_given_up: set = set()
        # fitness tail-read cache: (size, mtime) per run dir so the
        # 0.5 s control tick stats instead of re-reading unchanged files
        self._fitness_stat: Dict[int, tuple] = {}
        self._observe_armed_at: Optional[float] = None
        self._gen_opened_at = time.monotonic()
        self._events_path = os.path.join(self.dir, "league_events.jsonl")
        self._orphans_swept = 0
        self._stuck = False
        # journaled state
        self.state: dict = {}
        self._load_or_init()

    # ------------------------------------------------------------- journal
    def _journal_path(self) -> str:
        return os.path.join(self.dir, "league.json")

    def _commit(self) -> None:
        """Atomically persist the whole league state. Called at every
        durable transition — the write IS the decision; everything before
        it must be re-derivable, everything after idempotent."""
        _atomic_json(self._journal_path(), self.state)

    def _event(self, event: str, **kw) -> None:
        rec = {"t": round(time.monotonic(), 3), "event": event,
               "gen": self.state.get("generation"), **kw}
        print(f"[league] {json.dumps(rec)}", flush=True)
        with open(self._events_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def _load_or_init(self) -> None:
        path = self._journal_path()
        if os.path.exists(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                raise RuntimeError(
                    f"league journal {path} is unreadable ({e}); a torn "
                    "journal means the atomic-write contract broke — "
                    "refusing to guess league state"
                ) from e
            if doc.get("schema") != JOURNAL_SCHEMA:
                raise RuntimeError(
                    f"league journal schema {doc.get('schema')!r} != "
                    f"{JOURNAL_SCHEMA!r}"
                )
            if doc.get("seed") != self.config.seed or (
                doc.get("slots") != len(self.config.genomes)
            ):
                raise RuntimeError(
                    "league journal disagrees with the CLI (seed "
                    f"{doc.get('seed')} vs {self.config.seed}, slots "
                    f"{doc.get('slots')} vs {len(self.config.genomes)}) — "
                    "resume with the original arguments or use a fresh dir"
                )
            self.state = doc
            self._event("journal_resumed",
                        pending=bool(self.state.get("pending")))
            return
        variants: Dict[str, dict] = {}
        members: Dict[str, int] = {}
        for slot, genome in enumerate(self.config.genomes):
            uid = slot + 1
            members[str(slot)] = uid
            variants[str(uid)] = self._new_variant(
                uid, slot, dict(genome), parent=None, born_gen=0
            )
        self.state = {
            "schema": JOURNAL_SCHEMA,
            "seed": self.config.seed,
            "slots": len(self.config.genomes),
            "generation": 0,
            "next_uid": len(self.config.genomes) + 1,
            "members": members,
            "variants": variants,
            "lineage": [],
            "promotions": 0,
            "rollbacks": 0,
            "gen_baseline": {},
            "pending": None,
        }
        self._commit()
        self._event("league_created", slots=len(self.config.genomes))

    def _new_variant(self, uid: int, slot: int, genome: dict,
                     parent: Optional[int], born_gen: int) -> dict:
        return {
            "uid": uid,
            "slot": slot,
            "genome": genome,
            "parent": parent,
            "born_gen": born_gen,
            "seed": self.config.seed * 1000 + uid,
            "status": "new",   # new|live|dead|retired|quarantined|finished
            "pid": 0,
            "pgid": 0,
            "spawned": 0,
            "adopted": 0,
            "exited_0": 0,
            "exited_75": 0,
            "exited_err": 0,
            "killed": 0,
            "restarts": 0,
            "live": 0,
            "fitness": None,
            "fitness_step": -1,
        }

    # -------------------------------------------------------------- layout
    def run_dir(self, uid: int) -> str:
        return os.path.join(self.dir, f"v{uid:04d}")

    def _variant(self, uid: int) -> dict:
        return self.state["variants"][str(uid)]

    def _members(self) -> Dict[int, int]:
        return {int(s): u for s, u in self.state["members"].items()}

    def _fleet_port(self, slot: int) -> int:
        return self.config.fleet_base_port + slot

    def _learner_argv(self, v: dict) -> List[str]:
        argv = list(self.config.learner_argv)
        argv += genome_argv(v["genome"])
        argv += [
            "--log-dir", self.run_dir(v["uid"]),
            "--seed", str(v["seed"]),
            "--variant-id", str(v["uid"]),
            "--league-generation", str(v["born_gen"]),
            # always: a fresh dir ignores it, a forked/restarted one needs
            # it — the exit-75 contract's other half
            "--resume",
        ]
        if self.config.fleet_base_port:
            argv += [
                "--fleet-listen", str(self._fleet_port(v["slot"])),
                "--fleet-host", "127.0.0.1",
                "--fleet-bundle", os.path.join(self.run_dir(v["uid"]),
                                               "bundle"),
                "--num-envs", "0",
            ]
        return argv

    # --------------------------------------------------------- supervision
    def _spawn(self, uid: int, *, restart: bool = False) -> None:
        v = self._variant(uid)
        handle = self._spawnlib.spawn_group(
            self._learner_argv(v), f"v{uid:04d}"
        )
        self._handles[uid] = handle
        self._spawned_at[uid] = time.monotonic()
        v["pid"], v["pgid"] = handle.proc.pid, handle.pgid
        v["spawned"] += 1
        v["live"] = 1
        v["status"] = "live"
        if restart:
            v["restarts"] += 1
        # counter + pid + liveness commit in ONE atomic write: the
        # identity can only ever be off by an uncounted live process,
        # which the adoption scan at the next controller start recovers
        self._commit()
        self._event("learner_spawned", uid=uid, pid=v["pid"],
                    restart=restart)

    def _find_running(self, uid: int) -> Optional[int]:
        """A live learner for this variant's run dir, by /proc cmdline
        scan — the adoption path that makes spawn-vs-journal crashes
        recoverable (PID-reuse-safe: the cmdline must name the run dir)."""
        marker = self.run_dir(uid)
        for name in os.listdir("/proc"):
            if not name.isdigit():
                continue
            pid = int(name)
            cmd = procs.pid_cmdline(pid)
            if marker in cmd and "--log-dir" in cmd:
                return pid
        return None

    def _reconcile(self) -> None:
        """Controller (re)start: re-adopt still-live learners, classify
        the ones that died while nobody watched, find uncounted spawns."""
        for uid in sorted(self._members().values()):
            v = self._variant(uid)
            if v["status"] not in ("live", "new"):
                continue
            alive = (
                v["pid"]
                and procs.pid_alive(v["pid"])
                and self.run_dir(uid) in procs.pid_cmdline(v["pid"])
            )
            if v["live"] and alive:
                self._event("learner_adopted", uid=uid, pid=v["pid"])
                # same tenure continues — no counter movement; we just
                # lost the Popen handle, so supervision uses /proc
                self._spawned_at[uid] = time.monotonic()
                continue
            if v["live"] and not alive:
                # died while the controller was down: exit code unknowable
                # (re-parented to init) — conservatively a crash
                v["live"] = 0
                v["exited_err"] += 1
                v["status"] = "dead"
                self._commit()
                self._event("learner_died_unsupervised", uid=uid)
                continue
            pid = self._find_running(uid)
            if pid is not None:
                # spawn landed but its journal write didn't: adopt
                v["pid"], v["live"] = pid, 1
                try:
                    v["pgid"] = os.getpgid(pid)
                except (ProcessLookupError, OSError):
                    v["pgid"] = 0
                v["adopted"] += 1
                v["status"] = "live"
                self._commit()
                self._spawned_at[uid] = time.monotonic()
                self._event("learner_adopted_unjournaled", uid=uid, pid=pid)

    def _poll_rc(self, uid: int) -> Optional[int]:
        """None while running; the exit code (None→-1 for adopted
        processes whose rc is unknowable) once gone."""
        handle = self._handles.get(uid)
        v = self._variant(uid)
        if handle is not None:
            return handle.proc.poll()
        if procs.pid_alive(v["pid"]) and (
            self.run_dir(uid) in procs.pid_cmdline(v["pid"])
        ):
            return None
        return -1  # adopted process gone; rc unknowable

    def _classify_exit(self, uid: int, rc: Optional[int]) -> None:
        v = self._variant(uid)
        v["live"] = 0
        v["pid"] = 0
        if rc == 0:
            v["exited_0"] += 1
            v["status"] = "finished"
        elif rc == 75:
            v["exited_75"] += 1
            v["status"] = "dead"
        else:
            v["exited_err"] += 1
            v["status"] = "dead"
        self._commit()
        self._event("learner_exited", uid=uid, rc=rc, status=v["status"])

    def _supervise(self) -> None:
        """Restart dead members under per-variant seeded Backoff;
        quarantine crash-loopers (the actor-pool discipline)."""
        for _slot, uid in sorted(self._members().items()):
            v = self._variant(uid)
            if v["status"] == "live":
                rc = self._poll_rc(uid)
                if rc is None:
                    # stable for a while => the next failure starts the
                    # backoff schedule over (consecutive-failure rule)
                    if (
                        uid in self._backoffs
                        and time.monotonic() - self._spawned_at.get(uid, 0)
                        > 30.0
                    ):
                        self._backoffs.pop(uid, None)
                        self._retry_at.pop(uid, None)
                    continue
                self._classify_exit(uid, rc)
            if v["status"] == "new":
                self._spawn(uid)
                continue
            if v["status"] != "dead":
                continue
            if uid not in self._retry_at:
                bo = self._backoffs.setdefault(uid, Backoff(
                    base_s=0.5, max_s=10.0,
                    max_attempts=self.config.restart_max_attempts,
                    rng=random.Random(v["seed"] + 7919),
                ))
                delay = bo.next_delay()
                if delay is None:
                    v["status"] = "quarantined"
                    self._commit()
                    self._event("variant_quarantined", uid=uid,
                                restarts=v["restarts"])
                    continue
                self._retry_at[uid] = time.monotonic() + delay
                continue
            if time.monotonic() >= self._retry_at[uid]:
                del self._retry_at[uid]
                self._spawn(uid, restart=True)

    def _stop_learner(self, uid: int, *, reason: str) -> None:
        """The kill discipline: SIGTERM (the learner checkpoints and
        exits 75 — the preemption contract) → bounded wait → SIGKILL the
        whole process GROUP → orphan sweep. Exactly-once accounting:
        'killed' ticks with the same journal write that clears liveness."""
        v = self._variant(uid)
        if not v["live"]:
            return
        handle = self._handles.pop(uid, None)
        if handle is not None:
            rc = handle.stop(
                drain_timeout_s=self.config.drain_timeout_s,
            )
        else:
            rc = self._kill_adopted(v)
        v["live"] = 0
        v["pid"] = 0
        v["killed"] += 1
        v["status"] = "retired"
        self._commit()
        self._event("learner_killed", uid=uid, rc=rc, reason=reason)

    def _kill_adopted(self, v: dict) -> Optional[int]:
        """The drain escalation for a re-adopted learner we cannot
        wait() on: SIGTERM → poll /proc under the bound → group kill."""
        pid, pgid = v["pid"], v["pgid"]
        try:
            os.kill(pid, signal.SIGTERM)
        except (ProcessLookupError, OSError):
            pass
        if not procs.wait_pid_gone(pid, self.config.drain_timeout_s):
            procs.kill_group(pgid or pid, signal.SIGKILL)
            procs.wait_pid_gone(pid, 10.0)
        if pgid:
            self._orphans_swept += len(
                procs.reap_orphans([pgid], label=f"v{v['uid']:04d}")
            )
        return None

    # -------------------------------------------------------------- actors
    def _sync_actors(self) -> None:
        """Fleet mode: each slot runs ``actors_per_variant`` actor hosts
        pinned (``--variant``) to the slot's CURRENT member. A replaced
        member ⇒ drain the old hosts, spawn new ones against the new
        run dir's bundle once the new learner has published it."""
        if not self.config.fleet_base_port or not self.config.actors_per_variant:
            return
        for slot, uid in sorted(self._members().items()):
            v = self._variant(uid)
            handles = self._actor_handles.get(slot, [])
            died = False
            stale = [
                h for h in handles
                if getattr(h, "league_uid", None) != uid
                or h.proc.poll() is not None
            ]
            for h in stale:
                handles.remove(h)
                if getattr(h, "league_uid", None) != uid:
                    h.stop(drain_timeout_s=20.0)
                    self._event("actor_drained", slot=slot,
                                uid=getattr(h, "league_uid", None))
                else:
                    died = True
            if died:
                # crashed (not replaced): pace the respawn under the
                # slot's seeded Backoff — a broken --actor-args must
                # never become a spawn-per-tick storm
                bo = self._actor_backoffs.setdefault(slot, Backoff(
                    base_s=0.5, max_s=15.0, max_attempts=8,
                    rng=random.Random(self.config.seed + 500 + slot),
                ))
                delay = bo.next_delay()
                if delay is None:
                    if slot not in self._actor_given_up:
                        self._actor_given_up.add(slot)
                        self._event("actor_slot_given_up", slot=slot)
                else:
                    self._actor_retry_at[slot] = time.monotonic() + delay
            elif handles and (
                time.monotonic()
                - max(getattr(h, "spawned_at", 0.0) for h in handles)
                > 30.0
            ):
                # stable actors: the next crash starts the schedule over
                self._actor_backoffs.pop(slot, None)
            if v["status"] != "live":
                continue
            if slot in self._actor_given_up or (
                time.monotonic() < self._actor_retry_at.get(slot, 0.0)
            ):
                self._actor_handles[slot] = handles
                continue
            bundle = os.path.join(self.run_dir(uid), "bundle", "bundle.json")
            if not os.path.exists(bundle):
                continue  # learner hasn't published yet
            while len(handles) < self.config.actors_per_variant:
                n = len(handles)
                h = self._spawnlib.spawn_group(
                    [
                        sys.executable, "-m", "d4pg_tpu.fleet.actor",
                        "--connect",
                        f"127.0.0.1:{self._fleet_port(slot)}",
                        "--bundle", os.path.dirname(bundle),
                        "--variant", str(uid),
                        "--seed", str(v["seed"] + 100 + n),
                        "--reconnect-attempts", "400",
                    ] + list(self.config.actor_argv),
                    f"actor{slot}.{n}",
                )
                h.league_uid = uid
                h.spawned_at = time.monotonic()
                handles.append(h)
                self._event("actor_spawned", slot=slot, uid=uid, n=n)
            self._actor_handles[slot] = handles

    def _stop_actors(self, slot: Optional[int] = None) -> None:
        slots = [slot] if slot is not None else list(self._actor_handles)
        for s in slots:
            for h in self._actor_handles.get(s, []):
                h.stop(drain_timeout_s=20.0)
            self._actor_handles[s] = []

    # ------------------------------------------------------------- fitness
    def _read_fitness(self, uid: int) -> None:
        v = self._variant(uid)
        run = self.run_dir(uid)
        # stat gate (metrics mode): skip the tail read+parse when the
        # file is unchanged (the control tick would otherwise re-read
        # 256 KB per live variant twice a second to rediscover the same
        # newest row)
        sig = None
        if self.config.fitness_source == "metrics":
            try:
                st = os.stat(os.path.join(run, "metrics.jsonl"))
                sig = (st.st_size, st.st_mtime_ns)
            except OSError:
                sig = None
            if sig is not None and self._fitness_stat.get(uid) == sig:
                return
        fit = None
        if self.config.fitness_source == "metrics":
            fit = self._fitness_from_metrics(run)
            if fit is not None and sig is not None:
                self._fitness_stat[uid] = sig
        if fit is None:
            fit = self._fitness_from_best_eval(run)
        if fit is None:
            return
        score, step = fit
        if step != v["fitness_step"] or score != v["fitness"]:
            v["fitness"], v["fitness_step"] = score, step
            # fitness is advisory state: journaled so a restarted
            # controller ranks on the same numbers, but a lost update
            # only delays a generation, never corrupts one
            self._commit()

    @staticmethod
    def _fitness_from_metrics(run: str):
        """Newest eval row in metrics.jsonl (tail read — rows are
        append-only): EWMA return when present, else the raw eval mean."""
        path = os.path.join(run, "metrics.jsonl")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - (256 << 10)))
                tail = f.read().decode(errors="replace").splitlines()
        except OSError:
            return None
        for line in reversed(tail):
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn first/partial line of the tail window
            for key in ("avg_test_reward_ewma", "eval_return_mean"):
                if key in row:
                    return float(row[key]), int(row.get("step", 0))
        return None

    @staticmethod
    def _fitness_from_best_eval(run: str):
        try:
            with open(os.path.join(run, "best_eval.json")) as f:
                doc = json.load(f)
            return float(doc["eval_return_mean"]), int(doc.get("step", 0))
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _rankable(self) -> List[int]:
        """Members eligible for exploit/explore: live (or finished), with
        a fitness reading. Quarantined variants are excluded — they are
        already the losers, and killing them twice books nothing."""
        out = []
        for _slot, uid in sorted(self._members().items()):
            v = self._variant(uid)
            if v["status"] in ("live", "finished") and v["fitness"] is not None:
                out.append(uid)
        return out

    def _generation_ready(self) -> bool:
        baseline = self.state.get("gen_baseline", {})
        members = self._members()
        fresh = 0
        for uid in members.values():
            v = self._variant(uid)
            if v["status"] == "quarantined":
                continue
            if v["fitness"] is None:
                return False
            if v["fitness_step"] > baseline.get(str(uid), -1):
                fresh += 1
            else:
                return False
        if fresh >= 2:
            return True
        return False

    # --------------------------------------------------- the PBT machinery
    def _open_generation(self) -> None:
        """Record the fitness watermark each member must beat-or-refresh
        before the NEXT exploit/explore decision (journaled: a restarted
        controller waits on the same watermarks)."""
        self.state["gen_baseline"] = {
            str(uid): self._variant(uid)["fitness_step"]
            for uid in self._members().values()
        }
        self._commit()
        self._gen_opened_at = time.monotonic()

    def _plan_generation(self) -> None:
        """The exploit/explore decision, journaled BEFORE any effect: the
        worst quartile dies, each victim's slot is re-seeded with a
        perturbed clone of a top member. Seeded — the same league replays
        the same decisions."""
        ranked = sorted(
            self._rankable(), key=lambda u: self._variant(u)["fitness"]
        )
        if len(ranked) < 2:
            self._event("generation_skipped", why="fewer than 2 rankable")
            self._open_generation()
            return
        kills = max(1, len(ranked) // 4)
        actions = []
        for i in range(kills):
            victim = ranked[i]
            src = ranked[-1 - (i % max(1, len(ranked) - kills))]
            child_uid = self.state["next_uid"]
            self.state["next_uid"] += 1
            actions.append({
                "phase": "planned",
                "kill_uid": victim,
                "src_uid": src,
                "child_uid": child_uid,
                "genome": perturb_genome(
                    self._variant(src)["genome"], self._rng
                ),
                "reason": "clone",
                "bar_fitness": self._variant(victim)["fitness"],
                "fork_steps": [],
            })
        self.state["pending"] = {
            "gen": self.state["generation"],
            "actions": actions,
        }
        self._commit()
        self._event(
            "generation_planned",
            actions=[
                {k: a[k] for k in ("kill_uid", "src_uid", "child_uid")}
                for a in actions
            ],
        )

    def _advance_pending(self) -> None:
        pending = self.state.get("pending")
        if not pending:
            return
        for action in pending["actions"]:
            if action["phase"] != "done":
                self._advance_action(pending, action)
                if action["phase"] != "done":
                    return  # one in-flight action at a time
        # every action resolved: the generation commits exactly once
        self.state["pending"] = None
        self.state["generation"] = pending["gen"] + 1
        self._commit()
        self._event("generation_done", next_gen=self.state["generation"])
        self._open_generation()

    def _advance_action(self, pending: dict, action: dict) -> None:
        phase = action["phase"]
        if phase == "planned":
            # idempotent on replay: killing a dead learner books nothing
            # twice (the killed counter ticks inside _stop_learner's
            # single journal write, which the phase write here follows)
            self._stop_learner(action["kill_uid"], reason="pbt_cull")
            self._stop_actors(self._variant(action["kill_uid"])["slot"])
            action["phase"] = "culled"
            self._commit()
            return
        if phase == "culled":
            self._apply_fork(action)
            return
        if phase == "forked":
            child = action["child_uid"]
            if self._variant(child)["status"] == "new":
                self._spawn(child)
            self._observe_armed_at = time.monotonic()
            action["phase"] = "observing"
            self._commit()
            self._event("observe_started", uid=child)
            return
        if phase == "observing":
            self._observe(pending, action)

    def _apply_fork(self, action: dict) -> None:
        """Checkpoint FORK: verify-and-copy the newest intact steps from
        the source run dir, then write ``variant.json`` LAST — the fork's
        commit record (a replayed fork finding it skips the copy)."""
        src_uid, child_uid = action["src_uid"], action["child_uid"]
        victim = self._variant(action["kill_uid"])
        dst = self.run_dir(child_uid)
        marker = os.path.join(dst, "variant.json")
        if not os.path.exists(marker):
            if os.path.exists(dst):
                # a half-copied fork from a crashed attempt: rebuild whole
                shutil.rmtree(dst)
            os.makedirs(dst, exist_ok=True)
            steps = ckpt_manifest.fork_checkpoint(
                os.path.join(self.run_dir(src_uid), "checkpoints"),
                os.path.join(dst, "checkpoints"),
                depth=self.config.fork_depth,
            )
            action["fork_steps"] = steps
            if self._chaos is not None:
                e = self._chaos.tick("clone_corrupt")
                if e is not None and steps:
                    # Torn-fork fault: truncate the newest copied step
                    # AFTER its manifest landed — the clone's
                    # verify-on-restore must fall back to the older copy
                    # and log, never train on torn state.
                    from d4pg_tpu.chaos import truncate_checkpoint_step

                    sd = ckpt_manifest.default_step_dir(
                        os.path.join(dst, "checkpoints"), steps[-1]
                    )
                    if sd is not None:
                        truncate_checkpoint_step(sd)
            _atomic_json(marker, {
                "uid": child_uid,
                "slot": victim["slot"],
                "genome": action["genome"],
                "parent": src_uid,
                "born_gen": self.state["generation"],
                "seed": self.config.seed * 1000 + child_uid,
                "fork_steps": steps,
                "reason": action["reason"],
            })
        # journal the child + lineage + slot handover with the phase flip
        # (idempotent on replay: a crash between variant.json and this
        # commit re-enters here — never a duplicate lineage edge)
        if str(child_uid) not in self.state["variants"]:
            self.state["variants"][str(child_uid)] = self._new_variant(
                child_uid, victim["slot"], action["genome"],
                parent=src_uid, born_gen=self.state["generation"],
            )
        if not any(
            e["child"] == child_uid for e in self.state["lineage"]
        ):
            self.state["lineage"].append({
                "child": child_uid,
                "parent": src_uid,
                "gen": self.state["generation"],
                "reason": action["reason"],
            })
        self.state["members"][str(victim["slot"])] = child_uid
        action["phase"] = "forked"
        self._commit()
        self._event("checkpoint_forked", src=src_uid, child=child_uid,
                    steps=action["fork_steps"])

    def _attested(self, uid: int) -> bool:
        """trainer_meta.json re-written under the clone's OWN variant id
        = the fork restored and the clone committed a checkpoint of its
        own — the promotion attestation (the canary bundle-mtime shape)."""
        try:
            with open(os.path.join(
                self.run_dir(uid), "checkpoints", "trainer_meta.json"
            )) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return False
        return int(meta.get("variant_id", -1)) == uid

    def _observe(self, pending: dict, action: dict) -> None:
        child_uid = action["child_uid"]
        v = self._variant(child_uid)
        if self._observe_armed_at is None:
            self._observe_armed_at = time.monotonic()
        waited = time.monotonic() - self._observe_armed_at
        if v["status"] == "quarantined":
            if action["reason"] == "rollback_refork":
                # a crash-looping refork of the parent's OWN recipe is a
                # sick slot, not a bad genome: bounded give-up, never an
                # unbounded refork loop
                return self._give_up_slot(action, "refork_crash_looping")
            return self._rollback(pending, action, "clone_crash_looping")
        attested = self._attested(child_uid)
        if not attested:
            if waited > self.config.attest_timeout_s:
                if action["reason"] == "rollback_refork":
                    # a re-fork of the parent's OWN recipe failing to
                    # attest is not a bad genome — it is a sick slot.
                    # Bounded: quarantine it instead of re-forking forever.
                    return self._give_up_slot(action, "refork_attest_timeout")
                return self._rollback(pending, action, "attest_timeout")
            return
        if action["reason"] == "rollback_refork":
            # the parent's own recipe needs no observation window: it IS
            # the rollback target (the canary restore-old-bundle shape)
            return self._promote(action, why="rollback_refork_attested")
        self._read_fitness(child_uid)
        if v["fitness"] is not None and v["fitness_step"] >= 0:
            bar = action.get("bar_fitness")
            if bar is None or v["fitness"] >= bar:
                return self._promote(action, why="fitness_beats_bar")
            return self._rollback(pending, action, "fitness_below_bar")
        if waited > self.config.observe_timeout_s:
            return self._rollback(pending, action, "observe_timeout")

    def _give_up_slot(self, action: dict, why: str) -> None:
        """Terminal failure of a rollback re-fork: stop the clone,
        quarantine the slot's member, resolve the action as a (second)
        rollback so the generation can still commit."""
        uid = action["child_uid"]
        self._stop_learner(uid, reason=f"give_up:{why}")
        self._variant(uid)["status"] = "quarantined"
        self.state["rollbacks"] += 1
        action["phase"] = "done"
        self._commit()
        self._observe_armed_at = None
        self._event("slot_given_up", uid=uid, why=why)

    def _promote(self, action: dict, *, why: str) -> None:
        self.state["promotions"] += 1
        action["phase"] = "done"
        self._commit()
        self._observe_armed_at = None
        self._event("clone_promoted", uid=action["child_uid"], why=why)

    def _rollback(self, pending: dict, action: dict, why: str) -> None:
        """Kill the failed clone and re-fork the source's UNPERTURBED
        recipe into the slot (counted; the re-fork auto-promotes on
        attestation). Terminal-before-state-flip: the rollback event and
        counter commit with the action swap, atomically."""
        failed = action["child_uid"]
        self._stop_learner(failed, reason=f"rollback:{why}")
        child_uid = self.state["next_uid"]
        self.state["next_uid"] += 1
        self.state["rollbacks"] += 1
        replacement = {
            "phase": "culled",   # the victim is already gone
            "kill_uid": failed,
            "src_uid": action["src_uid"],
            "child_uid": child_uid,
            "genome": dict(self._variant(action["src_uid"])["genome"]),
            "reason": "rollback_refork",
            "bar_fitness": None,
            "fork_steps": [],
        }
        pending["actions"][pending["actions"].index(action)] = replacement
        self._commit()
        self._observe_armed_at = None
        self._event("clone_rolled_back", uid=failed, why=why,
                    refork_as=child_uid)

    # ----------------------------------------------------------- main loop
    def request_stop(self) -> None:
        """Signal-safe: just a flag the loop reads."""
        self._stop = True

    def tick(self) -> None:
        if self._chaos is not None:
            e = self._chaos.tick("controller_kill")
            if e is not None:
                # The crash the journal exists for: no cleanup, no
                # flush — the restarted controller must resume the SAME
                # generation and re-adopt every learner.
                print("[chaos] controller_kill: SIGKILL self", flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            e = self._chaos.tick("variant_kill")
            if e is not None:
                live = [u for u in sorted(self._members().values())
                        if self._variant(u)["live"]]
                if live:
                    victim = live[(self.config.seed + e.at) % len(live)]
                    vv = self._variant(victim)
                    print(f"[chaos] variant_kill: SIGKILL v{victim:04d} "
                          f"(pid {vv['pid']})", flush=True)
                    procs.kill_group(vv["pgid"] or vv["pid"], signal.SIGKILL)
        self._supervise()
        self._sync_actors()
        statuses = [
            self._variant(uid)["status"] for uid in self._members().values()
        ]
        if (
            sum(1 for s in statuses if s != "quarantined") < 2
            and not self.state.get("pending")
        ):
            # fewer than two members can ever rank again (exploit/explore
            # needs a comparison) and nothing is in flight: the league
            # cannot progress — stop LOUDLY (the all-quarantined
            # actor-pool rule), never spin silently forever. Covers both
            # the all-terminal case and the lone-survivor case.
            self._event("league_stuck", statuses=statuses)
            self._stuck = True
            self._stop = True
            return
        for uid in self._members().values():
            if self._variant(uid)["live"]:
                self._read_fitness(uid)
        self._advance_pending()
        if (
            self.state.get("pending") is None
            and self.state["generation"] < self.config.generations
        ):
            timed_out = (
                time.monotonic() - self._gen_opened_at
                > self.config.gen_timeout_s
            )
            if self._generation_ready() or (
                timed_out and len(self._rankable()) >= 2
            ):
                self._plan_generation()

    def run(self) -> int:
        self._reconcile()
        self._event("league_started", generation=self.state["generation"],
                    target=self.config.generations)
        while (
            not self._stop
            and self.state["generation"] < self.config.generations
        ):
            self.tick()
            if self.state["generation"] >= self.config.generations:
                break
            time.sleep(self.config.poll_interval_s)
        self.shutdown()
        summary = self.write_summary()
        ok = bool(summary["identity_ok"]) and summary["orphans_swept"] == 0
        self._event("league_finished",
                    generations=self.state["generation"],
                    promotions=self.state["promotions"],
                    rollbacks=self.state["rollbacks"],
                    identity_ok=ok)
        return 0 if ok and not self._stuck else 1

    def shutdown(self) -> None:
        """Stop every actor host and learner (graceful first), then sweep
        every process group this controller ever journaled — zero
        orphaned learners is an asserted contract, not a hope."""
        self._stop_actors()
        for uid in sorted(self._members().values()):
            self._stop_learner(uid, reason="shutdown")
        # Sweep only groups whose SURVIVORS still name this league on
        # their cmdline: a pgid journaled hours ago may have been
        # recycled by the kernel for an unrelated process group — the
        # same PID-reuse threat _reconcile defends adoption against, so
        # the kill side gets the same guard.
        pgids = [
            pg for pg in (
                v.get("pgid", 0) for v in self.state["variants"].values()
            )
            if pg and any(
                self.dir in procs.pid_cmdline(p)
                for p in procs.group_pids(pg)
            )
        ]
        self._orphans_swept += len(
            procs.reap_orphans(pgids, label="league")
        )
        self._orphans_swept += len(self._spawnlib.reap_orphans())

    # ------------------------------------------------------------- summary
    def write_summary(self) -> dict:
        variants = {}
        for uid_s, v in self.state["variants"].items():
            variants[uid_s] = {
                k: v[k] for k in (
                    "slot", "parent", "born_gen", "genome", "fitness",
                    "fitness_step", "spawned", "adopted", "exited_0",
                    "exited_75", "exited_err", "killed", "live",
                    "restarts", "status",
                )
            }
            variants[uid_s]["quarantined"] = v["status"] == "quarantined"
        identity_ok = all(
            v["spawned"] + v["adopted"]
            == v["exited_0"] + v["exited_75"] + v["exited_err"]
            + v["killed"] + v["live"]
            for v in variants.values()
        )
        # --debug-guards: the same tenure equation, machine-checked
        # against the FLOW_IDENTITIES manifest (no-op when disarmed)
        flowledger.check_rows("league-tenure", variants,
                              where="league summary")
        summary = {
            "backend": "cpu",
            "schema": "league-soak/v1",
            "seed": self.config.seed,
            "slots": self.state["slots"],
            "generations_completed": self.state["generation"],
            "promotions": self.state["promotions"],
            "rollbacks": self.state["rollbacks"],
            "quarantined": sum(
                1 for v in variants.values() if v["quarantined"]
            ),
            "chaos_injections": (
                self._chaos.injections_total if self._chaos else 0
            ),
            "orphans_swept": self._orphans_swept,
            "identity_ok": identity_ok,
            "members": self.state["members"],
            "variants": variants,
            "lineage": self.state["lineage"],
        }
        out = os.path.join(self.dir, "league_summary.json")
        _atomic_json(out, summary)
        if self.config.summary_out:
            _atomic_json(self.config.summary_out, summary)
        return summary
