"""PEP 562 lazy re-exports, shared by the package `__init__`s.

Keeps `import d4pg_tpu` free of JAX-heavy imports until a name is touched
(spawned actor-pool workers import only the gym adapter and must never pull
the JAX runtime — see `d4pg_tpu.envs`).
"""

from __future__ import annotations

import importlib
from typing import Callable, Mapping


def lazy_exports(
    module_name: str, exports: Mapping[str, str]
) -> tuple[Callable[[str], object], Callable[[], list[str]]]:
    """Build (``__getattr__``, ``__dir__``) for a module whose public names
    live in submodules. ``exports`` maps exported name → defining module."""

    def __getattr__(name: str):
        target = exports.get(name)
        if target is None:
            raise AttributeError(
                f"module {module_name!r} has no attribute {name!r}"
            )
        return getattr(importlib.import_module(target), name)

    def __dir__() -> list[str]:
        return sorted(exports)

    return __getattr__, __dir__
