"""Multi-host learner microbench (ISSUE 17): bit-exactness attestation +
per-host ingest scale-out.

Two claims, both chip-independent by construction:

1. BIT-EXACTNESS — the 2-process × 4-device global mesh (real
   ``jax.distributed`` over the gloo CPU backend, per-host ingest into
   local shards only) produces bit-identical results vs the 8-device
   single-process run of the same code: every TrainState leaf (params,
   targets, BOTH Adam moment sets), the assembled device ring, the
   device-PER tree sidecar, ``det_pmean`` reductions and
   ``fold_in(global shard index)`` in-kernel draws, after multiple
   megastep dispatches interleaved with ingest. Each topology also runs
   one steady-state dispatch under the ``no_transfers`` guard
   (``disallow_explicit`` H2D + ``disallow`` D2H), so the
   zero-transfer-bytes-per-grad-step row is ENFORCED, not sampled.
2. INGEST SCALE-OUT — per-host ingest means each process runs its own
   ``IngestServer`` feeding its own local ``ReplayBuffer``: the two
   writer stacks share NO state (disjoint buffers, ports, locks, no
   cross-host replay bytes). Aggregate capacity is therefore the sum of
   per-host capacities — each pod host brings its own CPUs. The bench
   host here has a SINGLE core, so co-scheduling two writers measures
   kernel time-slicing, not scale-out; the headline aggregate instead
   gives each writer's isolated stack the core to itself (modeling
   per-host CPUs) and sums, with the concurrent co-scheduled number
   reported alongside as disclosure. ``schema_check`` refuses artifacts
   whose attestation is broken, whose transfer row is nonzero, or whose
   writer scaling is ≤ 1.

Run as a script to (re)generate ``benchmarks/multihost_microbench.json``:

    python benchmarks/multihost_microbench.py

``tests/test_multihost.py`` drives the same topology child for the slow
bit-exactness test; ``tests/test_multihost_microbench.py`` runs the
ingest-scaling half at a small duration every tier-1 pass and pins the
committed artifact's schema.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ------------------------------------------------------- topology child
# One script, two topologies: ``nprocs`` 1 (the 8-device single-process
# oracle) or 2 (2 × 4-device jax.distributed over gloo). Every process
# deals itself the global write stream rows its shards own — the global
# writes k with (k % D) // L == rank, in increasing k order — so the
# interleaved stream is identical across topologies by construction.
CHILD_EXACT = textwrap.dedent(
    """
    import sys
    nprocs, rank, out = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nprocs}"
    )
    sys.path.insert(0, __REPO__)
    import numpy as np
    import jax
    if nprocs > 1:
        from d4pg_tpu.parallel import initialize_distributed
        initialize_distributed(
            coordinator_address=__COORD__,
            num_processes=nprocs, process_id=rank,
        )
    from jax.sharding import NamedSharding, PartitionSpec as P
    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.models.critic import DistConfig
    from d4pg_tpu.parallel import make_mesh, shard_train_state
    from d4pg_tpu.parallel.compat import shard_map
    from d4pg_tpu.parallel.distributed import gather_global, stage_global
    from d4pg_tpu.parallel.dp import det_pmean
    from d4pg_tpu.replay.device_per import DevicePerSync
    from d4pg_tpu.replay.device_ring import MultihostRingSync, device_ring_init
    from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
    from d4pg_tpu.runtime.megastep import make_megastep_device_per_sharded
    from d4pg_tpu.analysis import no_transfers

    D, K, B, C = 8, 2, 16, 128
    L = D // nprocs
    cfg = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16),
                     dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0))
    mesh = make_mesh(dp=D, tp=1)

    # One deterministic GLOBAL write stream, identical on every process
    # (same seed); each process adds only its deal — the global writes k
    # with (k % D) // L == rank, in increasing k order (host p's m-th
    # local write IS global write (m//L)*D + p*L + (m%L)).
    N1, N2 = 96, 64
    r = np.random.default_rng(0)
    g = dict(
        obs=r.normal(size=(N1 + N2, 3)).astype(np.float32),
        action=r.uniform(-1, 1, (N1 + N2, 1)).astype(np.float32),
        reward=r.uniform(-1, 0, N1 + N2).astype(np.float32),
        next_obs=r.normal(size=(N1 + N2, 3)).astype(np.float32),
        discount=np.full(N1 + N2, 0.99, np.float32),
    )
    def add_deal(buf, lo, hi):
        mine = [k for k in range(lo, hi) if (k % D) // L == rank]
        buf.add_batch(Transition(*(g[f][mine] for f in
            ("obs", "action", "reward", "next_obs", "discount"))))

    buf = ReplayBuffer(C // nprocs, 3, 1)
    ring = device_ring_init(C, 3, 1, mesh=mesh)
    sync = MultihostRingSync(buf, mesh, chunk_cap=64)
    per = DevicePerSync(C, alpha=0.6, mesh=mesh)
    sync.tree_hook = per.on_chunk
    mega = make_megastep_device_per_sharded(cfg, K, B, mesh)
    state = shard_train_state(create_train_state(cfg, jax.random.PRNGKey(1)), mesh)
    key = stage_global(mesh, P(), np.asarray(jax.random.PRNGKey(7)))

    met = None
    for lo, hi in ((0, N1), (N1, N1 + N2)):
        add_deal(buf, lo, hi)
        ring = sync.flush(ring)
        for _ in range(2):
            state, per.tree, key, met = mega(state, ring, per.tree, key)
    # steady state is zero-transfer on THIS topology too: even an
    # explicit device_put (or any D2H fetch) inside this dispatch raises
    with no_transfers():
        state, per.tree, key, met = mega(state, ring, per.tree, key)
    print(f"proc {rank} ZERO_TRANSFER_DISPATCH_OK")

    # det_pmean over the process-spanning mesh: fixed-order reduction
    vals = stage_global(
        mesh, P("dp", None),
        (np.arange(D * 4, dtype=np.float32) / 7.0).reshape(D, 4) ** 2,
    )
    red = jax.jit(
        shard_map(lambda x: det_pmean(x, "dp", D), mesh=mesh,
                  in_specs=P("dp", None), out_specs=P(), check_vma=False),
        out_shardings=NamedSharding(mesh, P()),
    )(vals)
    # shard-local in-kernel draws: fold_in(GLOBAL shard index)
    draws = jax.jit(
        shard_map(
            lambda k: jax.random.uniform(
                jax.random.fold_in(k[0], jax.lax.axis_index("dp")), (1, 4)
            ),
            mesh=mesh, in_specs=P(None), out_specs=P("dp", None),
            check_vma=False,
        ),
        out_shardings=NamedSharding(mesh, P("dp", None)),
    )(stage_global(mesh, P(None), np.asarray(jax.random.PRNGKey(11))[None]))

    snap = sync.gather_snapshot(ring)          # collective
    pa, mp = per.snapshot_host()               # collective
    leaves = [gather_global(x) for x in jax.tree_util.tree_leaves(state)]
    payload = {f"state_{i}": a for i, a in enumerate(leaves)}
    payload.update(snap)
    payload["per_pa"] = pa
    payload["per_mp"] = np.float32(mp)
    payload["det_pmean"] = gather_global(red)
    payload["draws"] = gather_global(draws)
    payload["critic_loss"] = gather_global(met["critic_loss"])
    if rank == 0:
        np.savez(out, **payload)
    print(f"proc {rank} EXACT_OK")
    """
)

CHILD_DISPATCHES = 5  # 2 phases x 2 + 1 guarded steady-state dispatch


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def child_env() -> dict:
    return {
        k: v
        for k, v in os.environ.items()
        # children must not inherit this process's platform pinning or a
        # tunneled-TPU plugin (PYTHONPATH site hooks, AXON_*/TPU_* vars)
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")
        and "AXON" not in k
        and "TPU" not in k
    }


def run_exact_topology(workdir: str, nprocs: int, timeout: int = 420) -> str:
    """Run the topology child at ``nprocs`` (1 or 2); returns the npz path
    process 0 wrote. Raises on any nonzero child or missing OK marker."""
    out = os.path.join(workdir, f"exact_p{nprocs}.npz")
    script = os.path.join(workdir, f"child_p{nprocs}.py")
    coord = f"127.0.0.1:{free_port()}"
    with open(script, "w") as f:
        f.write(
            CHILD_EXACT.replace("__REPO__", repr(REPO)).replace(
                "__COORD__", repr(coord)
            )
        )
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(nprocs), str(rank), out],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=child_env(), text=True,
        )
        for rank in range(nprocs)
    ]
    outs = [p.communicate(timeout=timeout)[0] for p in procs]
    for rank, (p, text) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"topology child nprocs={nprocs} rank {rank} rc="
                f"{p.returncode}:\n{text}"
            )
        for marker in (f"proc {rank} EXACT_OK",
                       f"proc {rank} ZERO_TRANSFER_DISPATCH_OK"):
            if marker not in text:
                raise RuntimeError(
                    f"topology child nprocs={nprocs} rank {rank} missing "
                    f"{marker!r}:\n{text}"
                )
    return out


def compare_npz(a_path: str, b_path: str) -> dict:
    """Byte-compare two topology payloads: same keys, same dtypes, same
    bits. Returns counts + any mismatching key names."""
    mismatches = []
    with np.load(a_path) as a, np.load(b_path) as b:
        if sorted(a.files) != sorted(b.files):
            mismatches.append(
                f"key sets differ: {sorted(a.files)} vs {sorted(b.files)}"
            )
            keys = sorted(set(a.files) & set(b.files))
        else:
            keys = sorted(a.files)
        state_leaves = sum(1 for k in keys if k.startswith("state_"))
        for k in keys:
            if a[k].dtype != b[k].dtype:
                mismatches.append(f"{k}: dtype {a[k].dtype} vs {b[k].dtype}")
            elif not np.array_equal(a[k], b[k]):
                mismatches.append(f"{k}: bits differ")
    return {
        "keys_compared": len(keys),
        "state_leaves": state_leaves,
        "mismatches": mismatches,
    }


# ---------------------------------------------------- ingest scale-out
def _bench_one_writer(obs_dim, action_dim, frame_windows, duration_s):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ingest_microbench import _bench_fleet

    return _bench_fleet(obs_dim, action_dim, frame_windows, duration_s)


def bench_ingest_scaling(
    obs_dim=3, action_dim=1, frame_windows=128, duration_s=1.5, writers=2,
) -> dict:
    """Aggregate windows/s of ``writers`` per-host ingest stacks vs one.

    Each stack is the REAL per-host path — ``FleetLink`` → localhost TCP
    → ``IngestServer`` reader/queue/writer → its own local
    ``ReplayBuffer`` — and the stacks are fully disjoint (own port, own
    buffer, own lock). The headline aggregate gives each stack the bench
    core to itself and sums (per-host CPUs are the definition of
    multi-host); a concurrent co-scheduled run is reported alongside —
    on a single-core bench host it measures time-slicing, which is why
    it is disclosure, not the headline."""
    single = _bench_one_writer(obs_dim, action_dim, frame_windows,
                               duration_s)
    per_writer = [
        _bench_one_writer(obs_dim, action_dim, frame_windows, duration_s)
        for _ in range(writers)
    ]
    aggregate = sum(r["windows_per_sec"] for r in per_writer)
    # concurrent disclosure run: same stacks, co-scheduled
    conc = [None] * writers

    def _run(i):
        conc[i] = _bench_one_writer(obs_dim, action_dim, frame_windows,
                                    duration_s)

    threads = [threading.Thread(target=_run, args=(i,), daemon=True,
                                name=f"writer-{i}")
               for i in range(writers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    concurrent_aggregate = sum(r["windows_per_sec"] for r in conc)
    return {
        "writers": writers,
        "obs_dim": obs_dim,
        "action_dim": action_dim,
        "frame_windows": frame_windows,
        "duration_s": duration_s,
        "bench_host_cores": os.cpu_count(),
        "methodology": (
            "isolated-stack-sum: the writer stacks share no state "
            "(disjoint buffers/ports/locks, no cross-host replay bytes), "
            "so aggregate capacity is the sum of per-host capacities — "
            "each stack is measured with the bench core to itself, "
            "modeling each pod host's own CPUs. The co-scheduled "
            "concurrent aggregate is reported as disclosure; on this "
            "bench host it measures single-core time-slicing, not "
            "scale-out."
        ),
        "writers_1_windows_per_sec": single["windows_per_sec"],
        "per_writer_windows_per_sec": [
            r["windows_per_sec"] for r in per_writer
        ],
        "writers_2_aggregate_windows_per_sec": aggregate,
        "writers_2_concurrent_windows_per_sec": concurrent_aggregate,
        "concurrent_wall_s": time.perf_counter() - t0,
        "scaling_x": aggregate / single["windows_per_sec"],
    }


# -------------------------------------------------------------- driver
def run_microbench(
    out_path: str | None = None,
    *,
    workdir: str | None = None,
    skip_exact: bool = False,
    frame_windows: int = 128,
    duration_s: float = 1.5,
) -> dict:
    out = {
        "metric": "multihost_microbench",
        # gloo CPU collectives + host sockets/numpy: chip-independent
        "backend": "cpu",
        "topologies": {
            "oracle": "1 process x 8 CPU devices",
            "subject": "2 processes x 4 CPU devices (jax.distributed, "
                       "gloo collectives)",
        },
    }
    if not skip_exact:
        import tempfile

        wd = workdir or tempfile.mkdtemp(prefix="multihost_bench_")
        single = run_exact_topology(wd, 1)
        multi = run_exact_topology(wd, 2)
        cmp_res = compare_npz(single, multi)
        exact = not cmp_res["mismatches"]
        out["bit_exact"] = {
            "dispatches": CHILD_DISPATCHES,
            "keys_compared": cmp_res["keys_compared"],
            "state_leaves": cmp_res["state_leaves"],
            "mismatches": cmp_res["mismatches"],
            # every TrainState leaf is in the compare set — params,
            # targets, and both Adam moment pytrees arrive as state_* keys
            "train_state": exact,
            "adam_moments": exact,
            "ring": exact,
            "per_tree": exact,
            "det_pmean": exact,
            "fold_in_draws": exact,
        }
        out["transfer_bytes_per_grad_step"] = {
            "procs_1": 0,
            "procs_2": 0,
            "enforced_by": (
                "jax transfer_guard (h2d disallow_explicit + d2h "
                "disallow) around a steady-state dispatch on each "
                "topology — the guard raises on ANY transfer, so the "
                "zero is enforced, not sampled"
            ),
        }
    out["ingest_scaling"] = bench_ingest_scaling(
        frame_windows=frame_windows, duration_s=duration_s,
    )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
    return out


if __name__ == "__main__":
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "multihost_microbench.json")
    result = run_microbench(path)
    be = result["bit_exact"]
    print(
        f"bit-exact: {be['keys_compared']} keys "
        f"({be['state_leaves']} state leaves) over {be['dispatches']} "
        f"dispatches — mismatches: {be['mismatches'] or 'none'}"
    )
    sc = result["ingest_scaling"]
    print(
        f"ingest: 1 writer {sc['writers_1_windows_per_sec']:,.0f} w/s | "
        f"{sc['writers']} writers {sc['writers_2_aggregate_windows_per_sec']:,.0f} w/s "
        f"aggregate ({sc['scaling_x']:.2f}x; concurrent co-scheduled "
        f"{sc['writers_2_concurrent_windows_per_sec']:,.0f} w/s on "
        f"{sc['bench_host_cores']} core(s))"
    )
    print("wrote", path)
