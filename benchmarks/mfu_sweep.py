"""MFU sweep: where does the framework's compute utilization land when the
shapes allow it? (VERDICT round-3 next #4)

The flagship bench's single-digit MFU is a property of the WORKLOAD (3x256
MLPs, batch 256: arithmetic intensity ~60 FLOP/B, far under the ~240 FLOP/B
ridge of a v5e) — this script provides the contrast points that make that
claim checkable rather than asserted:

1. batch sweep 256 -> 4096 on the flagship MLP config — MFU and HBM
   utilization per point (bigger batch raises intensity: the params/
   optimizer traffic amortizes over more rows);
2. the conv (pixel) critic config at 48x48x2 — convolutions carry far more
   FLOPs per byte than the tiny MLPs;
3. a "wide" MLP variant (1024-wide hiddens, batch 4096) — MXU-saturating
   matmul shapes with the same train-step machinery;
4. the MEGASTEP configuration (``--replay-placement device``): the fused
   device-resident-replay training loop (``runtime/megastep.py``) at the
   mlp256 / B >= 512 shapes where points 1-3 measured the 9% -> 53% MFU
   headroom — the data plane that exists to close exactly that gap, with
   ``transfer_bytes_per_grad_step`` 0 by construction and ``mfu`` from the
   same single-step XLA cost model as every other row;
5. the SHARDED megastep (``--replay-placement device --dp N``): the same
   loop spanning the dp mesh (striped sharded ring, shard-local draws,
   deterministic grad mean — ROADMAP item 2) at the wide shapes where tp/
   stack sharding is load-bearing, transfer bytes still 0;
6. the DEVICE-PER megastep (``--replay-placement device`` with PER on —
   ISSUE 14): the priority segment tree in HBM, so the wide-shape rows
   are finally reachable by runs using the sampling scheme the paper's
   D4PG actually uses (prioritized replay, Horgan et al. 2018) — with
   ``transfer_bytes_per_grad_step`` still 0 by construction;
7. the LARGE-BATCH RECIPE shape (ISSUE 16): device-PER with the fused
   descent-in-scan Pallas tier, bf16, at the exact B/K that
   ``train.py --p-replay --batch-scale 8 --fused-descent`` dispatches —
   the REAL prioritized training shape living at the MXU-filling point
   the sweep proved out (see docs/data_plane.md "Large-batch recipe").

Points 1-3 run through ``bench.bench_tpu`` (device-resident pool, fused
K-step scan); points 4-7 through ``bench.bench_megastep`` (device ring +
in-kernel draw; ``dp=`` for the sharded rows) — the SAME pinned timing
protocol (pipelined dispatches, donated state, value-transfer sync),
parameterized rather than copied, so the rows can never drift apart.

Run on the real chip:        python benchmarks/mfu_sweep.py
CPU-interpret megastep rows: JAX_PLATFORMS=cpu \
                             python benchmarks/mfu_sweep.py --megastep-only
CPU sharded rows:            JAX_PLATFORMS=cpu \
                             python benchmarks/mfu_sweep.py --sharded-only
CPU device-PER rows:         JAX_PLATFORMS=cpu \
                             python benchmarks/mfu_sweep.py --device-per-only
CPU large-batch row:         JAX_PLATFORMS=cpu \
                             python benchmarks/mfu_sweep.py --large-batch-only
(--megastep-only / --sharded-only / --device-per-only /
--large-batch-only keep the committed on-chip rows — the TPU tunnel has
been down since round 5 — and replace only their own row family, each
tagged with the backend that produced it; rerun WITHOUT the flags on the
TPU VM to refresh everything on-chip. ``--sharded`` / ``--device-per`` /
``--large-batch`` add their rows to a full refresh.)

Prints one JSON line per point and writes benchmarks/mfu_sweep_results.json.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_megastep, bench_tpu  # noqa: E402

RESULTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "mfu_sweep_results.json"
)


def bench_point(label: str, **kwargs) -> dict:
    out = bench_tpu("bfloat16", **kwargs)
    row = {
        "bench": "mfu_sweep",
        "config": label,
        "batch": kwargs.get("batch", 256),
        "compute_dtype": "bfloat16",
        "steps_per_sec": round(out["steps_per_sec"], 1),
    }
    for k in ("flops_per_grad_step", "bytes_per_grad_step"):
        if k in out:
            row[k] = round(out[k])
    if "flops_per_grad_step" in out and out.get("bytes_per_grad_step"):
        row["intensity_flop_per_byte"] = round(
            out["flops_per_grad_step"] / out["bytes_per_grad_step"], 1
        )
    for k, nd in (
        ("achieved_tflops", 3),
        ("mfu", 5),
        ("achieved_gbps", 1),
        ("xla_bytes_util", 4),
    ):
        if k in out:
            row[k] = round(out[k], nd)
    return row


def megastep_point(batch: int, *, k_steps: int = 32, steps: int = 6) -> dict:
    """One megastep row at the flagship mlp256 model: device placement,
    in-kernel uniform draw, zero per-grad-step transfers. Tagged with the
    backend so CPU-interpret placeholders are never mistaken for chip
    numbers."""
    import jax

    out = bench_megastep(
        placement="device", batch=batch, k=k_steps, steps=steps,
    )
    row = {
        "bench": "mfu_sweep",
        "config": "megastep_mlp256",
        "batch": batch,
        "compute_dtype": "float32",
        "backend": jax.default_backend(),
        "steps_per_sec": round(out["steps_per_sec"], 1),
        "transfer_bytes_per_grad_step": out["transfer_bytes_per_grad_step"],
    }
    for k, nd in (
        ("flops_per_grad_step", 0),
        ("achieved_tflops", 3),
        ("mfu", 5),
    ):
        if k in out:
            row[k] = round(out[k], nd) if nd else round(out[k])
    if jax.default_backend() == "cpu":
        row["note"] = (
            "CPU-interpret placeholder (TPU tunnel down); rerun "
            "benchmarks/mfu_sweep.py on-chip for the real MFU"
        )
    return row


def megastep_rows() -> list[dict]:
    rows = []
    # B >= 512 is where points 1-3 measured the MFU headroom opening up
    # (0.092 -> 0.232 from batch alone); 256 anchors the flagship shape.
    for batch in (256, 512, 1024):
        rows.append(megastep_point(batch))
        print(json.dumps(rows[-1]), flush=True)
    return rows


def sharded_point(batch: int, dp: int, *, hidden: int = 256,
                  k_steps: int = 32, steps: int = 4) -> dict:
    """One SHARDED megastep row (runtime/megastep.py:
    make_megastep_uniform_sharded): dp-sharded ring + shard-local draws,
    transfer bytes 0 by construction. Wide-shape points because that is
    where sharding is load-bearing (53% MFU only at MXU-friendly widths,
    mfu_sweep_results.json) — on CPU the steps/s is a placeholder like
    every other cpu-tagged row; the zero-transfer column is the
    chip-independent half."""
    import jax

    if jax.device_count() < dp:
        raise RuntimeError(
            f"sharded_point(dp={dp}) needs {dp} devices, have "
            f"{jax.device_count()} — on CPU run via the __main__ entry "
            "(it configures the virtual mesh) or set "
            "--xla_force_host_platform_device_count"
        )
    out = bench_megastep(
        placement="device", batch=batch, k=k_steps, steps=steps,
        hidden=hidden, dp=dp,
    )
    row = {
        "bench": "mfu_sweep",
        "config": f"sharded_megastep_mlp{hidden}",
        "batch": batch,
        "dp": dp,
        "compute_dtype": "float32",
        "backend": jax.default_backend(),
        "steps_per_sec": round(out["steps_per_sec"], 1),
        "transfer_bytes_per_grad_step": out["transfer_bytes_per_grad_step"],
    }
    if jax.default_backend() == "cpu":
        row["note"] = (
            "CPU virtual-mesh placeholder (TPU tunnel down); rerun "
            "benchmarks/mfu_sweep.py --sharded on a multi-chip VM for "
            "real scaling"
        )
    return row


def sharded_rows() -> list[dict]:
    rows = []
    # The wide shapes the sharding exists for: flagship width at large
    # batch, then the MXU width (hidden 1024 shards 128-wide per tp rank
    # at dp=8... dp-only mesh: batch splits 8-way, ring splits 8-way).
    for batch, hidden in ((512, 256), (1024, 512)):
        rows.append(sharded_point(batch, dp=8, hidden=hidden))
        print(json.dumps(rows[-1]), flush=True)
    return rows


def device_per_point(batch: int, dp: int | None = None, *, hidden: int = 256,
                     k_steps: int = 32, steps: int = 6) -> dict:
    """One DEVICE-RESIDENT PER megastep row (ISSUE 14): in-kernel
    stratified descent + IS weights + write-back, zero per-grad-step
    transfers WITH prioritized replay on. Wide-shape points because this
    is what makes the mfu headroom rows reachable by real PER runs; dp
    spans the virtual mesh with shard-local subtrees."""
    import jax

    if dp and jax.device_count() < dp:
        raise RuntimeError(
            f"device_per_point(dp={dp}) needs {dp} devices, have "
            f"{jax.device_count()} — on CPU run via the __main__ entry "
            "(it configures the virtual mesh)"
        )
    out = bench_megastep(
        placement="device", per=True, batch=batch, k=k_steps, steps=steps,
        hidden=hidden, dp=dp,
    )
    row = {
        "bench": "mfu_sweep",
        "config": f"device_per_megastep_mlp{hidden}",
        "batch": batch,
        "dp": int(dp or 1),
        "compute_dtype": "float32",
        "backend": jax.default_backend(),
        "steps_per_sec": round(out["steps_per_sec"], 1),
        "transfer_bytes_per_grad_step": out["transfer_bytes_per_grad_step"],
    }
    for k, nd in (
        ("flops_per_grad_step", 0),
        ("achieved_tflops", 3),
        ("mfu", 5),
    ):
        if k in out:
            row[k] = round(out[k], nd) if nd else round(out[k])
    if jax.default_backend() == "cpu":
        row["note"] = (
            "CPU-interpret placeholder (TPU tunnel down); rerun "
            "benchmarks/mfu_sweep.py --device-per on-chip for the real MFU"
        )
    return row


def device_per_rows() -> list[dict]:
    rows = []
    # The flagship shape, the headroom batch, and one mesh-spanning row.
    for batch, dp in ((256, None), (1024, None), (512, 8)):
        rows.append(device_per_point(batch, dp))
        print(json.dumps(rows[-1]), flush=True)
    return rows


def large_batch_point(all_rows: list[dict], *, scale: int = 8,
                      steps: int = 3) -> dict:
    """The ISSUE 16 flagship large-batch recipe row: the REAL
    ``--p-replay`` training shape — device-resident PER with the FUSED
    descent-in-scan tier (descent + loss as ONE Pallas program per scan
    step), bf16 compute, at the ``--batch-scale`` recipe's B/K (B=256·S,
    K=32/S, the exact shape ``train.py --batch-scale S`` dispatches).

    Three claims ride on this row, split by what a CPU can measure:

    * ``transfer_bytes_per_grad_step`` — 0 by construction, measured
      here and chip-independent (schema_check refuses nonzero);
    * the CPU-proxy ratios — this row vs the B=256 flagship recipe
      baseline, SAME fused data plane, measured in this run:
      ``transitions_per_sec_ratio`` is rows-consumed/s (steps/s ×
      batch), the amortization the recipe exists for;
    * ``mfu_onchip_proxy`` — the ≥2×-flagship-MFU claim, anchored to the
      committed ON-CHIP mlp256 rows at the same (width, batch, dtype)
      matmul shape (the model cost is the shared
      ``bench.model_flops_per_step`` oracle, so the proxy and a real
      on-chip rerun of this row cannot drift apart), plus ``recipe`` —
      the ready-to-run command for the on-chip number.
    """
    import jax

    base_batch, base_k = 256, 32
    batch, k = base_batch * scale, max(1, base_k // scale)
    fused = dict(
        placement="device", per=True, compute_dtype="bfloat16",
        projection_backend="pallas_fused", fused_descent=True,
    )
    base = bench_megastep(batch=base_batch, k=base_k, steps=steps, **fused)
    out = bench_megastep(batch=batch, k=k, steps=steps, **fused)
    row = {
        "bench": "mfu_sweep",
        "config": "large_batch_per_mlp256",
        "batch": batch,
        "batch_scale": scale,
        "k": k,
        "compute_dtype": "bfloat16",
        "backend": jax.default_backend(),
        "steps_per_sec": round(out["steps_per_sec"], 1),
        "baseline_steps_per_sec": round(base["steps_per_sec"], 1),
        "steps_per_sec_ratio": round(
            out["steps_per_sec"] / base["steps_per_sec"], 4
        ),
        "transitions_per_sec_ratio": round(
            out["steps_per_sec"] * batch
            / (base["steps_per_sec"] * base_batch), 2
        ),
        "transfer_bytes_per_grad_step": out["transfer_bytes_per_grad_step"],
    }
    for key, nd in (
        ("flops_per_grad_step", 0),
        ("achieved_tflops", 3),
        ("mfu", 5),
    ):
        if key in out:
            row[key] = round(out[key], nd) if nd else round(out[key])

    def _mlp256_mfu(b):
        for r in all_rows:
            if (r.get("config") == "mlp256" and r.get("batch") == b
                    and r.get("mfu")):
                return r["mfu"]
        return None

    flagship_mfu, shape_mfu = _mlp256_mfu(base_batch), _mlp256_mfu(batch)
    if flagship_mfu and shape_mfu:
        row["mfu_onchip_proxy"] = {
            "flagship_mfu": flagship_mfu,
            "shape_mfu": shape_mfu,
            "ratio_vs_flagship": round(shape_mfu / flagship_mfu, 2),
            "note": (
                f"committed on-chip mlp256 rows at B={base_batch} vs "
                f"B={batch}, bf16 — the same matmul shapes this recipe "
                "dispatches, costed by the same single-step oracle"
            ),
        }
    row["recipe"] = (
        "python train.py --env pendulum --p-replay "
        "--replay-placement device --device-tree-backend pallas "
        "--projection pallas_fused --compute-dtype bfloat16 "
        f"--steps-per-dispatch {base_k} --batch-scale {scale} "
        "--fused-descent --ingest-prefetch"
    )
    if jax.default_backend() == "cpu":
        row["note"] = (
            "CPU-interpret placeholder steps/s (TPU tunnel down); the "
            "ratios + zero-transfer column are measured here, the MFU "
            "claim is the committed on-chip proxy — rerun "
            "benchmarks/mfu_sweep.py --large-batch-only on-chip for the "
            "direct number"
        )
    return row


def large_batch_rows(all_rows: list[dict]) -> list[dict]:
    rows = [large_batch_point(all_rows)]
    print(json.dumps(rows[-1]), flush=True)
    return rows


def _replace_family(rows: list[dict], prefix: str, new_rows: list[dict]) -> list[dict]:
    """Drop rows whose config starts with ``prefix`` and append the fresh
    ones — the committed on-chip rows for every OTHER family survive a
    partial regen (the --megastep-only precedent)."""
    kept = [r for r in rows if not str(r.get("config", "")).startswith(prefix)]
    return kept + new_rows


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if "--sharded-only" in argv:
        with open(RESULTS) as f:
            rows = _replace_family(json.load(f), "sharded_megastep", sharded_rows())
    elif "--large-batch-only" in argv:
        with open(RESULTS) as f:
            committed = json.load(f)
        rows = _replace_family(
            committed, "large_batch", large_batch_rows(committed)
        )
    elif "--device-per-only" in argv:
        with open(RESULTS) as f:
            rows = _replace_family(
                json.load(f), "device_per_megastep", device_per_rows()
            )
    elif "--megastep-only" in argv:
        # Keep the committed on-chip rows and replace only the megastep
        # family — sharded_megastep rows survive too (prefix-disjoint:
        # "megastep" filters on the exact family, not the substring).
        with open(RESULTS) as f:
            rows = [
                r for r in json.load(f)
                if not str(r.get("config", "")).startswith("megastep")
            ]
        rows.extend(megastep_rows())
    else:
        rows = []
        # 1. batch scaling on the flagship MLP
        for batch in (256, 512, 1024, 2048, 4096):
            rows.append(bench_point("mlp256", batch=batch, k_steps=256, measure=8))
            print(json.dumps(rows[-1]), flush=True)
        # 2. conv critic (pixel workload): fewer fused steps — each is ~100x
        #    the MLP's FLOPs; smaller pool so pixel rows fit HBM comfortably
        rows.append(
            bench_point("conv48", batch=256, pixel=True, k_steps=32, measure=4,
                        pool_rows=8_192)
        )
        print(json.dumps(rows[-1]), flush=True)
        # 3. MXU-shaped MLP: 1024-wide, batch 4096
        rows.append(
            bench_point("mlp1024", batch=4096, hidden=1024, k_steps=64, measure=4)
        )
        print(json.dumps(rows[-1]), flush=True)
        # 4. the megastep data plane at the headroom shapes
        rows.extend(megastep_rows())
        # 5. the sharded megastep at the wide shapes (opt-in on a full
        #    refresh: needs a multi-device backend)
        if "--sharded" in argv:
            rows.extend(sharded_rows())
        # 6. device-resident PER at the headroom shapes (opt-in: the dp
        #    row needs a multi-device backend)
        if "--device-per" in argv:
            rows.extend(device_per_rows())
        # 7. the large-batch recipe's REAL --p-replay shape (ISSUE 16):
        #    fused descent-in-scan tier, bf16, B=2048/K=4. Runs after the
        #    mlp256 family so the on-chip MFU proxy cites THIS refresh.
        if "--large-batch" in argv:
            rows.extend(large_batch_rows(rows))
    with open(RESULTS, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"[mfu_sweep] wrote {RESULTS}", file=sys.stderr)


if __name__ == "__main__":
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" and (
        "--sharded" in sys.argv
        or "--sharded-only" in sys.argv
        or "--device-per" in sys.argv
        or "--device-per-only" in sys.argv
    ):
        # CPU virtual mesh for the sharded rows (before any jax backend
        # init — bench.py imports jax lazily inside its functions).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    main()
