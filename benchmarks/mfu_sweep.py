"""MFU sweep: where does the framework's compute utilization land when the
shapes allow it? (VERDICT round-3 next #4)

The flagship bench's single-digit MFU is a property of the WORKLOAD (3x256
MLPs, batch 256: arithmetic intensity ~60 FLOP/B, far under the ~240 FLOP/B
ridge of a v5e) — this script provides the contrast points that make that
claim checkable rather than asserted:

1. batch sweep 256 -> 4096 on the flagship MLP config — MFU and HBM
   utilization per point (bigger batch raises intensity: the params/
   optimizer traffic amortizes over more rows);
2. the conv (pixel) critic config at 48x48x2 — convolutions carry far more
   FLOPs per byte than the tiny MLPs;
3. a "wide" MLP variant (1024-wide hiddens, batch 4096) — MXU-saturating
   matmul shapes with the same train-step machinery.

Every point runs through ``bench.bench_tpu`` itself — the SAME pinned
protocol as the flagship line (fused K-step scan with device-side random
pool gather, donated state, value-transfer sync), parameterized rather
than copied, so the two can never drift apart.

Run on the real chip:  python benchmarks/mfu_sweep.py
Prints one JSON line per point and writes benchmarks/mfu_sweep_results.json.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_tpu  # noqa: E402


def bench_point(label: str, **kwargs) -> dict:
    out = bench_tpu("bfloat16", **kwargs)
    row = {
        "bench": "mfu_sweep",
        "config": label,
        "batch": kwargs.get("batch", 256),
        "compute_dtype": "bfloat16",
        "steps_per_sec": round(out["steps_per_sec"], 1),
    }
    for k in ("flops_per_grad_step", "bytes_per_grad_step"):
        if k in out:
            row[k] = round(out[k])
    if "flops_per_grad_step" in out and out.get("bytes_per_grad_step"):
        row["intensity_flop_per_byte"] = round(
            out["flops_per_grad_step"] / out["bytes_per_grad_step"], 1
        )
    for k, nd in (
        ("achieved_tflops", 3),
        ("mfu", 5),
        ("achieved_gbps", 1),
        ("xla_bytes_util", 4),
    ):
        if k in out:
            row[k] = round(out[k], nd)
    return row


def main() -> None:
    rows = []
    # 1. batch scaling on the flagship MLP
    for batch in (256, 512, 1024, 2048, 4096):
        rows.append(bench_point("mlp256", batch=batch, k_steps=256, measure=8))
        print(json.dumps(rows[-1]), flush=True)
    # 2. conv critic (pixel workload): fewer fused steps — each is ~100x
    #    the MLP's FLOPs; smaller pool so pixel rows fit HBM comfortably
    rows.append(
        bench_point("conv48", batch=256, pixel=True, k_steps=32, measure=4,
                    pool_rows=8_192)
    )
    print(json.dumps(rows[-1]), flush=True)
    # 3. MXU-shaped MLP: 1024-wide, batch 4096
    rows.append(
        bench_point("mlp1024", batch=4096, hidden=1024, k_steps=64, measure=4)
    )
    print(json.dumps(rows[-1]), flush=True)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mfu_sweep_results.json")
    with open(out, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"[mfu_sweep] wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
