"""Chip-independent fused-vs-unfused microbench smoke (tier-1-safe).

The flagship bench (``bench.py``) needs the TPU; when the tunnel is down
(as in rounds 5-6) a perf regression in the train step would otherwise be
invisible until the next chip window. This smoke runs ONE fused
(``projection_backend="pallas_fused"``, Pallas interpreter on CPU) and one
unfused ("xla" oracle) train step on whatever backend is available, and
records into a JSON artifact:

- relative step time (interpret-mode Pallas is EXPECTED to be slower on
  CPU — the interpreter executes the kernel op-by-op; the number exists so
  a 10× jump in either absolute time rings a bell, not as a TPU proxy);
- a bytes proxy: XLA cost-analysis "bytes accessed" of the compiled
  single-step program for each backend. On CPU this counts the interpreted
  kernel's inner ops rather than one opaque TPU kernel, so the USEFUL
  regression signal is the unfused program's bytes (the one-hot-matmul
  materialization the fused kernel exists to delete) and both programs'
  drift over rounds, not the cross-backend ratio.

Run as a script to (re)generate ``benchmarks/cpu_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/fused_microbench.py

``tests/test_fused_microbench.py`` runs the same function at smaller
shapes every tier-1 pass.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    batch: int = 128,
    hidden: int = 64,
    atoms: int = 51,
    timed_steps: int = 3,
) -> dict:
    """Time fused vs unfused train steps + collect the bytes proxy.

    Returns the artifact dict; writes it to ``out_path`` when given.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from d4pg_tpu.agent import D4PGConfig, create_train_state, jit_train_step
    from d4pg_tpu.models.critic import DistConfig

    rng = np.random.default_rng(0)
    obs_dim, act_dim = 17, 6
    batch_data = {
        "obs": jnp.asarray(rng.normal(size=(batch, obs_dim)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, size=(batch, act_dim)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, size=batch), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(batch, obs_dim)), jnp.float32),
        "discount": jnp.full((batch,), 0.99, jnp.float32),
        "weights": jnp.ones((batch,), jnp.float32),
    }

    out = {
        "metric": "fused_vs_unfused_cpu_microbench",
        "backend": jax.default_backend(),
        "batch": batch,
        "hidden": hidden,
        "atoms": atoms,
        "timed_steps": timed_steps,
    }
    for name, backend in (("unfused", "xla"), ("fused", "pallas_fused")):
        config = D4PGConfig(
            obs_dim=obs_dim,
            action_dim=act_dim,
            hidden_sizes=(hidden, hidden, hidden),
            dist=DistConfig(
                kind="categorical", num_atoms=atoms, v_min=-150.0, v_max=150.0
            ),
            projection_backend=backend,
        )
        state = create_train_state(config, jax.random.PRNGKey(0))
        step = jit_train_step(config, donate=False)
        try:
            cost = step.lower(state, batch_data).compile().cost_analysis()
            if isinstance(cost, list):  # older jax returns [dict]
                cost = cost[0]
            out[f"{name}_bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
            out[f"{name}_flops"] = float(cost.get("flops", 0.0))
        except Exception:  # d4pglint: disable=broad-except  -- optional XLA
            # cost-analysis probe: shape of the failure varies by backend/
            # jax version and the benchmark's timings land either way
            pass
        state, _, priorities = step(state, batch_data)  # compile + warmup
        jax.block_until_ready(priorities)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            state, _, priorities = step(state, batch_data)
        jax.block_until_ready(priorities)
        out[f"{name}_step_ms"] = (time.perf_counter() - t0) / timed_steps * 1e3
    if "unfused_step_ms" in out and "fused_step_ms" in out:
        out["fused_over_unfused_time"] = out["fused_step_ms"] / out["unfused_step_ms"]
    if out.get("unfused_bytes_accessed") and out.get("fused_bytes_accessed"):
        out["fused_over_unfused_bytes"] = (
            out["fused_bytes_accessed"] / out["unfused_bytes_accessed"]
        )
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(os.path.dirname(__file__), "cpu_microbench.json")
    print(json.dumps(run_microbench(artifact)))
