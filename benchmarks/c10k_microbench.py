"""C10k front-end microbench (tier-1-safe): one router process holds
ten thousand mostly-idle client connections on the netio event loop
while a small interactive population keeps getting answers inside its
SLO — with O(1) threads in the connection count and the accounting
identity exact at drain (ISSUE 20 acceptance).

The router runs as a SUBPROCESS so the thread claim is measurable from
the outside: ``/proc/<pid>/status`` ``Threads:`` is read once at a small
baseline connection count and again with the full population held; the
delta must stay inside a constant budget. A thread-per-connection
front-end fails this by construction (10k conns → ~10k threads); the
netio loop holds every connection on one thread.

Three committed headlines:

- ``held_connections`` — the max ``netio.conns_open`` the router
  attested over healthz while the idle population was up (≥ 10000 in
  the committed artifact; the fd soft limit here is 20000 per process).
- ``interactive.p99_ms`` — closed-loop act() latency measured WHILE the
  10k idle connections are held, pinned under ``slo_ms``.
- ``identity`` — the router's drain-time ``[flow-verdict]`` for the
  ``router`` family (requests_total == ok + overloaded + error), exact.

Run as a script to (re)generate ``benchmarks/c10k_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/c10k_microbench.py

``tests/test_c10k_microbench.py`` runs the same function at a small
connection count every tier-1 pass (the O(1)-threads and identity
claims hold at ANY scale; only the 10k floor needs the full run) and
pins the committed artifact's schema + headlines.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _threads_of(pid: int) -> int:
    """Kernel-attested thread count of a live process."""
    with open(f"/proc/{pid}/status", encoding="utf-8") as f:
        for line in f:
            if line.startswith("Threads:"):
                return int(line.split()[1])
    raise RuntimeError(f"no Threads: line in /proc/{pid}/status")


class _RouterProc:
    """Router subprocess with a stdout scraper: ephemeral port, the
    admitted line, and the drain-time ``[flow-verdict]`` records."""

    # port: written once by the reader thread before _port_evt is set;
    # every reader waits on the event first (wait_ready), so the write
    # happens-before any read
    _THREAD_SAFE = ("port",)

    def __init__(self, backends: str):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "d4pg_tpu.serve.router",
             "--backends", backends, "--port", "0", "--wait-replicas", "1",
             "--debug-guards"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        self.lines: list[str] = []
        self._port_evt = threading.Event()
        self._admit_evt = threading.Event()
        self.port: int | None = None
        self._reader = threading.Thread(
            target=self._pump, name="c10k-router-stdout", daemon=True
        )
        self._reader.start()

    def _pump(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))
            if "listening on" in line and not self._port_evt.is_set():
                addr = line.split("listening on", 1)[1].split()[0]
                self.port = int(addr.rsplit(":", 1)[1])
                self._port_evt.set()
            if "admitted 1/1" in line:
                self._admit_evt.set()

    def wait_ready(self, timeout: float = 120.0) -> int:
        if not self._port_evt.wait(timeout) or not self._admit_evt.wait(timeout):
            self.proc.kill()
            raise RuntimeError(
                "router never became ready:\n" + "\n".join(self.lines[-20:])
            )
        return self.port

    def drain(self, timeout: float = 60.0) -> int:
        self.proc.send_signal(signal.SIGTERM)
        rc = self.proc.wait(timeout)
        self._reader.join(10.0)
        return rc

    def flow_verdicts(self) -> list[dict]:
        out = []
        for line in self.lines:
            if "[flow-verdict]" in line:
                out.append(json.loads(line.split("[flow-verdict]", 1)[1]))
        return out


def run_microbench(
    out_path: str | None = None,
    *,
    conns: int = 10000,
    baseline_conns: int = 100,
    interactive_conns: int = 4,
    duration_s: float = 3.0,
    slo_ms: float = 250.0,
    thread_growth_budget: int = 4,
    hidden: int = 8,
) -> dict:
    """Hold ``conns`` idle connections on one router subprocess, measure
    interactive p99 beside them, and pin thread growth + the accounting
    identity. Raises on any broken contract so a bad artifact is never
    written."""
    import jax
    import numpy as np

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.serve import Overloaded, PolicyBundle, PolicyClient, PolicyServer
    from d4pg_tpu.serve.bundle import actor_template
    from d4pg_tpu.serve.protocol import probe_healthz

    cfg = D4PGConfig(obs_dim=4, action_dim=2, hidden_sizes=(hidden, hidden))
    bundle = PolicyBundle(
        config=cfg,
        actor_params=actor_template(cfg),
        action_low=np.full(2, -1.0, np.float32),
        action_high=np.full(2, 1.0, np.float32),
        obs_norm=None,
        meta={"source": "c10k_microbench"},
        path=None,
    )
    replica = PolicyServer(
        bundle, port=0, max_batch=16, max_wait_us=500, queue_limit=256,
        watch_bundle=False,
    )
    replica.start()
    router = _RouterProc(f"127.0.0.1:{replica.port}")
    socks: list[socket.socket] = []
    held_max = 0
    try:
        port = router.wait_ready()
        pid = router.proc.pid

        def probe() -> dict:
            return probe_healthz("127.0.0.1", port, timeout_s=10.0)

        def held_now() -> int:
            nonlocal held_max
            n = int(probe()["netio"]["conns_open"])
            held_max = max(held_max, n)
            return n

        def ramp_to(target: int, deadline_s: float = 180.0) -> None:
            """Open idle connections in backlog-sized batches, letting
            the bounded accept loop (64/tick) catch up between bursts."""
            t_end = time.monotonic() + deadline_s
            while len(socks) < target:
                for _ in range(min(256, target - len(socks))):
                    s = socket.create_connection(("127.0.0.1", port),
                                                 timeout=15.0)
                    socks.append(s)
                while held_now() < len(socks):
                    if time.monotonic() > t_end:
                        raise RuntimeError(
                            f"ramp stalled: {held_now()} accepted of "
                            f"{len(socks)} opened (target {target})"
                        )
                    time.sleep(0.05)

        # a short warmup so every constant-count router thread (replica
        # link reader, prober, dispatcher) exists before the baseline read
        with PolicyClient("127.0.0.1", port, timeout=10.0) as c:
            for _ in range(8):
                c.act(np.zeros(4, np.float32))

        ramp_to(baseline_conns)
        threads_baseline = _threads_of(pid)

        ramp_to(conns)
        threads_at_max = _threads_of(pid)
        held_now()

        # interactive traffic WHILE the idle population is held
        lat_ms: list[float] = []
        counts = {"ok": 0, "overloaded": 0, "error": 0}
        lock = threading.Lock()

        def interactive() -> None:
            obs = np.zeros(4, np.float32)
            try:
                with PolicyClient("127.0.0.1", port, timeout=10.0) as c:
                    t_end = time.monotonic() + duration_s
                    while time.monotonic() < t_end:
                        t0 = time.monotonic()
                        try:
                            c.act(obs)
                            with lock:
                                counts["ok"] += 1
                                lat_ms.append((time.monotonic() - t0) * 1e3)
                        except Overloaded:
                            with lock:
                                counts["overloaded"] += 1
            except Exception:  # d4pglint: disable=broad-except  -- counted into counts['error'], asserted zero after the run
                with lock:
                    counts["error"] += 1

        workers = [
            threading.Thread(target=interactive, name=f"c10k-client{i}",
                             daemon=True)
            for i in range(interactive_conns)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join(duration_s + 60.0)

        held_now()
        netio_final = probe()["netio"]
        threads_final = _threads_of(pid)
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        rc = router.drain() if router.proc.poll() is None else router.proc.poll()
        replica.drain()

    verdicts = [v for v in router.flow_verdicts() if v["family"] == "router"]
    identity_ok = bool(verdicts) and all(v["ok"] for v in verdicts)
    assert rc == 0, f"router exited {rc}:\n" + "\n".join(router.lines[-20:])
    assert identity_ok, f"router flow identity broken at drain: {verdicts}"
    assert held_max >= conns, (
        f"held {held_max} connections, target {conns}"
    )
    growth = threads_at_max - threads_baseline
    assert growth <= thread_growth_budget, (
        f"thread count grew {growth} ({threads_baseline} -> "
        f"{threads_at_max}) across {conns - baseline_conns} extra "
        f"connections — the loop must hold them on O(1) threads"
    )
    lat_ms.sort()
    p99_ms = lat_ms[int(0.99 * (len(lat_ms) - 1))] if lat_ms else None
    assert counts["ok"] > 0 and counts["error"] == 0, counts

    out = {
        "metric": "c10k_microbench",
        "backend": jax.default_backend(),
        "conns_target": conns,
        "held_connections": held_max,
        "slo_ms": slo_ms,
        "duration_s": duration_s,
        "interactive_conns": interactive_conns,
        "threads": {
            "baseline_conns": baseline_conns,
            "threads_baseline": threads_baseline,
            "threads_at_max": threads_at_max,
            "threads_final": threads_final,
            "growth": growth,
            "growth_budget": thread_growth_budget,
        },
        "interactive": {
            "p99_ms": p99_ms,
            "submitted": counts["ok"] + counts["overloaded"],
            **counts,
        },
        "identity": {
            "ok": identity_ok,
            "verdicts": verdicts,
        },
        "netio": {k: netio_final[k] for k in (
            "conns_open", "conns_total", "frames_in", "frames_out",
            "evicted_read_stall", "evicted_write_stall",
            "accept_shed", "accept_backoffs",
        )},
        "router_rc": rc,
    }

    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(os.path.dirname(__file__), "c10k_microbench.json")
    result = run_microbench(artifact)
    print(
        json.dumps(
            {
                "metric": "c10k_microbench",
                "held_connections": result["held_connections"],
                "thread_growth": result["threads"]["growth"],
                "interactive_p99_ms": result["interactive"]["p99_ms"],
                "identity_ok": result["identity"]["ok"],
                "artifact": artifact,
            }
        )
    )
