"""Chip-independent fleet-ingest microbench (tier-1-safe, JAX-free).

The PR-7 collection-fleet claims — localhost-socket ingest sustains
window rates far past what one learner consumes, the framing/staging
overhead over the in-process writer path is bounded, and past capacity
the bounded queue sheds EXPLICITLY instead of diverging — are all host
CPU work (sockets, numpy copies, the replay lock), so they stay
measurable with the TPU tunnel down, by the same argument as
``host_pipeline_microbench``.

Scenarios, per shape (flagship HalfCheetah-scale obs 17 / act 6 from
BASELINE.json, plus the Pendulum-scale small shape):

- ``inprocess`` — frame-sized batches straight into
  ``ReplayBuffer.add_batch`` (the exact call the ingest writer thread
  lands on): the upper bound the socket path is measured against.
- ``fleet``     — the REAL path: ``FleetLink`` → localhost TCP → framed
  protocol → ``IngestServer`` reader/queue/writer → the same
  ``add_batch``. Reported as windows/s and MB/s of wire payload, plus
  the ratio against ``inprocess``.
- ``shed``      — an offered-rate sweep against a deliberately slow
  consumer (a delay inside ``add_batch`` caps capacity BELOW the
  generator), open-loop raw-socket sender: per-rate shed fraction, with
  sub-saturation levels showing zero shed and the engagement point
  (first offered rate with nonzero shed) reported explicitly.
- ``multi_writer`` — the ISSUE-17 per-host scale-out row at the
  flagship shape: N fully disjoint writer stacks (each its own buffer,
  server, port, and replay lock — exactly what per-host ingest on a
  multi-host mesh gives each process). On a multi-core host each stack
  runs on its own core; this bench host has one core, so each stack is
  measured with the core to itself (serially) and the aggregate is the
  sum — the honest model of per-host CPUs, stated in ``methodology``.
  A co-scheduled concurrent run of the same stacks is also reported as
  disclosure of what one core does when forced to time-slice them.

Repeats are INTERLEAVED (inprocess/fleet alternate per repeat) so bursty
interference on the shared bench host hits both paths alike; the
headline keeps the best repeat with all repeats visible.

Run as a script to (re)generate ``benchmarks/ingest_microbench.json``:

    python benchmarks/ingest_microbench.py

``tests/test_ingest_microbench.py`` runs the same function at smaller
shapes every tier-1 pass and pins the committed artifact's schema.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from d4pg_tpu.fleet import wire  # noqa: E402
from d4pg_tpu.fleet.actor import FleetLink  # noqa: E402
from d4pg_tpu.fleet.ingest import IngestServer  # noqa: E402
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition  # noqa: E402
from d4pg_tpu.serve import protocol  # noqa: E402

NSTEP, GAMMA = 5, 0.99


def _frame_cols(n, obs_dim, action_dim, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "action": rng.standard_normal((n, action_dim)).astype(np.float32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "discount": rng.random(n).astype(np.float32),
    }


def _bench_inprocess(obs_dim, action_dim, frame_windows, duration_s):
    """Frame-sized add_batch calls — the writer thread's landing call,
    without the wire in front of it."""
    buf = ReplayBuffer(65536, obs_dim, action_dim)
    cols = _frame_cols(frame_windows, obs_dim, action_dim)
    t = Transition(cols["obs"], cols["action"], cols["reward"],
                   cols["next_obs"], cols["discount"])
    # warmup (page in the ring slices)
    for _ in range(3):
        buf.add_batch(t)
    n = 0
    start = time.perf_counter()
    while time.perf_counter() - start < duration_s:
        buf.add_batch(t)
        n += frame_windows
    elapsed = time.perf_counter() - start
    return {"windows_per_sec": n / elapsed, "windows": n}


def _bench_fleet(obs_dim, action_dim, frame_windows, duration_s, seed=0):
    """The real localhost path, flow-controlled by the server-advertised
    in-flight window exactly as the actor host runs it."""
    buf = ReplayBuffer(65536, obs_dim, action_dim)
    srv = IngestServer(
        buf, obs_dim=obs_dim, action_dim=action_dim, n_step=NSTEP,
        gamma=GAMMA, port=0, queue_limit=64,
    ).start()
    acked = [0]

    def on_ack(kind, m):
        if kind == "accepted":
            acked[0] += m

    try:
        link = FleetLink(
            "127.0.0.1", srv.port,
            dict(actor_id="bench", env="bench", obs_dim=obs_dim,
                 action_dim=action_dim, n_step=NSTEP, gamma=GAMMA,
                 generation=0),
            on_ack=on_ack,
        )
        fw = min(frame_windows, link.max_windows)
        cols = _frame_cols(fw, obs_dim, action_dim, seed=seed)
        payload_bytes = len(wire.encode_windows(0, **cols))
        # warmup — drain its acks and zero the counter before the clock
        # starts, so the headline only credits windows sent inside the
        # timed interval
        for _ in range(3):
            link.acquire_credit(5)
            link.send_windows((0, 0, False), cols)
        # Wait on the ACK COUNT, not inflight(): the reader pops the
        # pending entry (inflight -> 0) BEFORE invoking on_ack, so an
        # inflight()==0 poll can win that race and the last warmup ack
        # would land after the zeroing, over-crediting the timed run.
        warm = 3 * fw
        warm_deadline = time.monotonic() + 30
        while acked[0] < warm and time.monotonic() < warm_deadline:
            time.sleep(0.001)
        assert acked[0] == warm, (acked[0], warm)
        acked[0] = 0
        start = time.perf_counter()
        sent = 0
        while time.perf_counter() - start < duration_s:
            if not link.acquire_credit(5):
                raise RuntimeError(f"link died: {link.dead}")
            link.send_windows((0, 0, False), cols)
            sent += fw
        # drain: every sent frame acked before the clock stops (the ack is
        # the admission receipt, so acked/s is honest ingest throughput)
        deadline = time.monotonic() + 30
        while link.inflight() > 0 and time.monotonic() < deadline:
            time.sleep(0.001)
        elapsed = time.perf_counter() - start
        link.close()
        assert acked[0] == sent, (acked[0], sent)
        frames = sent // fw
        return {
            "windows_per_sec": acked[0] / elapsed,
            "mb_per_sec": frames * (payload_bytes + protocol.HEADER.size)
            / elapsed / 1e6,
            "frame_windows": fw,
            "payload_bytes_per_frame": payload_bytes,
            "windows": acked[0],
        }
    finally:
        srv.close()


def _bench_fleet_writers(obs_dim, action_dim, frame_windows, duration_s,
                         writers=2):
    """N disjoint single-writer stacks — per-host ingest scale-out.

    Multi-host ingest (``docs/multihost.md``) gives every process its own
    buffer shard, ingest server, and replay lock; nothing is shared
    across writers, so aggregate throughput is the sum of what each
    host's CPU sustains alone. This bench models those per-host CPUs on
    a shared bench host: each stack is measured in isolation (the core
    to itself), the aggregate is the sum, and a co-scheduled concurrent
    run is included as disclosure of single-core time-slicing."""
    single = _bench_fleet(obs_dim, action_dim, frame_windows, duration_s)
    per_writer = [
        _bench_fleet(obs_dim, action_dim, frame_windows, duration_s,
                     seed=w)["windows_per_sec"]
        for w in range(writers)
    ]
    aggregate = sum(per_writer)
    # disclosure: the same disjoint stacks co-scheduled on THIS host
    results = [None] * writers

    def run(w):
        results[w] = _bench_fleet(obs_dim, action_dim, frame_windows,
                                  duration_s, seed=w)["windows_per_sec"]

    threads = [
        threading.Thread(target=run, args=(w,), name=f"writer-{w}",
                         daemon=True)
        for w in range(writers)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    concurrent_wall = time.perf_counter() - t0
    return {
        "writers": writers,
        "bench_host_cores": os.cpu_count(),
        "methodology": (
            "isolated-stack-sum: each writer stack is fully disjoint "
            "(own buffer/server/port/lock, as per-host ingest is on a "
            "multi-host mesh); stacks are measured serially so each "
            "models a dedicated per-host CPU, aggregate = sum; the "
            "concurrent row co-schedules the same stacks on this host "
            "as disclosure"
        ),
        "writers_1_windows_per_sec": single["windows_per_sec"],
        "per_writer_windows_per_sec": per_writer,
        f"writers_{writers}_aggregate_windows_per_sec": aggregate,
        f"writers_{writers}_concurrent_windows_per_sec": sum(
            r for r in results if r is not None
        ),
        "concurrent_wall_s": concurrent_wall,
        "scaling_x": aggregate / single["windows_per_sec"],
    }


class _SlowBuffer:
    """Caps consumer capacity at ``1000/per_window_ms`` windows/s: the
    slow-device-stub move from serve_microbench, applied to the replay
    writer. Per-WINDOW (not per-call) so the ingest writer's frame
    coalescing cannot amortize the stub away — the capacity ceiling the
    offered-rate sweep must cross is exact by construction."""

    def __init__(self, obs_dim, action_dim, per_window_ms):
        self._inner = ReplayBuffer(65536, obs_dim, action_dim)
        self.per_window_s = per_window_ms / 1e3

    def add_batch(self, t):
        time.sleep(len(t.reward) * self.per_window_s)
        return self._inner.add_batch(t)


def _bench_shed(obs_dim, action_dim, frame_windows, offered_rates,
                duration_s, per_window_ms=0.2, queue_limit=4):
    """Open-loop raw-socket sender at fixed frame rates against a slow
    consumer; per-rate accepted/shed accounting from the acks."""
    levels = []
    for rate in offered_rates:  # frames/s offered
        srv = IngestServer(
            _SlowBuffer(obs_dim, action_dim, per_window_ms),
            obs_dim=obs_dim, action_dim=action_dim, n_step=NSTEP,
            gamma=GAMMA, port=0, queue_limit=queue_limit,
        ).start()
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.settimeout(10)
            protocol.write_frame(
                s, protocol.HELLO, 0,
                wire.encode_hello(actor_id="shed", env="bench",
                                  obs_dim=obs_dim, action_dim=action_dim,
                                  n_step=NSTEP, gamma=GAMMA, generation=0),
            )
            t, _r, _p = protocol.read_frame(s)
            assert t == protocol.HELLO_OK
            counts = {"accepted": 0, "shed": 0}
            replies = [0]

            def reader():
                try:
                    while True:
                        frame = protocol.read_frame(s)
                        if frame is None:
                            return
                        ft, _fr, fp = frame
                        if ft == protocol.WINDOWS_OK:
                            counts["accepted"] += wire.decode_windows_ok(fp)[0]
                        elif ft == protocol.OVERLOADED:
                            counts["shed"] += frame_windows
                        replies[0] += 1
                except OSError:
                    return  # sender closed the socket under us: done

            rt = threading.Thread(target=reader, name="shed-reader",
                                  daemon=True)
            rt.start()
            payload = wire.encode_windows(
                0, **_frame_cols(frame_windows, obs_dim, action_dim)
            )
            period = 1.0 / rate
            start = time.perf_counter()
            sent = 0
            while True:
                now = time.perf_counter()
                if now - start >= duration_s:
                    break
                if now - start >= sent * period:
                    protocol.write_frame(s, protocol.WINDOWS, sent + 1,
                                         payload)
                    sent += 1
                else:
                    time.sleep(min(period / 4, 0.001))
            deadline = time.monotonic() + 30
            while replies[0] < sent and time.monotonic() < deadline:
                time.sleep(0.005)
            s.close()
            rt.join(timeout=5)
            offered = sent * frame_windows
            lost = offered - counts["accepted"] - counts["shed"]
            levels.append({
                "offered_frames_per_sec": rate,
                "offered_windows_per_sec": rate * frame_windows,
                "windows_offered": offered,
                "windows_accepted": counts["accepted"],
                "windows_shed": counts["shed"] + lost,  # unanswered = lost
                "shed_rate": (counts["shed"] + lost) / max(offered, 1),
            })
        finally:
            srv.close()
    engaged = [lv["offered_windows_per_sec"] for lv in levels
               if lv["shed_rate"] > 0.0]
    return {
        "consumer_per_window_ms": per_window_ms,
        "consumer_capacity_windows_per_sec": 1e3 / per_window_ms,
        "queue_limit": queue_limit,
        "levels": levels,
        "shed_engagement_windows_per_sec": min(engaged) if engaged else None,
    }


def run_microbench(
    out_path: str | None = None,
    *,
    shapes=((17, 6), (3, 1)),
    frame_windows: int = 128,
    duration_s: float = 2.0,
    repeats: int = 3,
    shed_rates=(30, 90, 420),
    shed_duration_s: float = 1.5,
    writers: int = 2,
) -> dict:
    out = {
        "metric": "ingest_microbench",
        # host CPU work by construction (sockets/numpy/replay lock) — the
        # numbers are chip-independent, same argument as host_pipeline
        "backend": "cpu",
        "frame_windows": frame_windows,
        "duration_s": duration_s,
        "repeats": repeats,
        "shapes": {},
    }
    for obs_dim, action_dim in shapes:
        inproc_reps, fleet_reps = [], []
        for rep in range(repeats):  # interleaved: bursty host noise hits both
            inproc_reps.append(
                _bench_inprocess(obs_dim, action_dim, frame_windows,
                                 duration_s)
            )
            fleet_reps.append(
                _bench_fleet(obs_dim, action_dim, frame_windows, duration_s)
            )
        best_in = max(inproc_reps, key=lambda r: r["windows_per_sec"])
        best_fl = max(fleet_reps, key=lambda r: r["windows_per_sec"])
        key = f"obs{obs_dim}_act{action_dim}"
        out["shapes"][key] = {
            "obs_dim": obs_dim,
            "action_dim": action_dim,
            "row_bytes": 4 * wire.window_row_floats(obs_dim, action_dim),
            "inprocess": best_in,
            "fleet": best_fl,
            "fleet_over_inprocess": best_fl["windows_per_sec"]
            / best_in["windows_per_sec"],
            "inprocess_repeats": [r["windows_per_sec"] for r in inproc_reps],
            "fleet_repeats": [r["windows_per_sec"] for r in fleet_reps],
        }
    # shed sweep at the flagship shape only (the mechanics are shape-blind)
    obs_dim, action_dim = shapes[0]
    out["shed"] = _bench_shed(
        obs_dim, action_dim, min(frame_windows, 32), shed_rates,
        shed_duration_s,
    )
    # per-host ingest scale-out (ISSUE 17), also at the flagship shape
    out["multi_writer"] = _bench_fleet_writers(
        obs_dim, action_dim, frame_windows, duration_s, writers=writers,
    )
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
    return out


if __name__ == "__main__":
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ingest_microbench.json")
    result = run_microbench(path)
    for key, shape in result["shapes"].items():
        print(
            f"{key}: inprocess {shape['inprocess']['windows_per_sec']:,.0f} w/s"
            f" | fleet {shape['fleet']['windows_per_sec']:,.0f} w/s"
            f" ({shape['fleet']['mb_per_sec']:.1f} MB/s wire,"
            f" {shape['fleet_over_inprocess']:.2f}x of in-process)"
        )
    print(
        "shed engagement:",
        result["shed"]["shed_engagement_windows_per_sec"],
        "windows/s offered",
        [round(lv["shed_rate"], 3) for lv in result["shed"]["levels"]],
    )
    mw = result["multi_writer"]
    agg = mw[f"writers_{mw['writers']}_aggregate_windows_per_sec"]
    print(
        f"multi-writer: {mw['writers']} writers {agg:,.0f} w/s aggregate"
        f" ({mw['scaling_x']:.2f}x of one writer)"
    )
    print("wrote", path)
