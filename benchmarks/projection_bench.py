"""Benchmark: Pallas vs XLA categorical projection, f32 vs bf16 compute.

VERDICT round-1 weak #4/#7: the Pallas kernel was equivalence-tested but
never benchmarked on the real chip, and --compute-dtype bfloat16 existed
unmeasured. This script measures BOTH inside the fused train scan (the
context that matters — a kernel that wins in isolation but loses fused is
worthless) and standalone, across atom counts, and prints a JSON line per
configuration. Run on the real TPU:

    python benchmarks/projection_bench.py

Results feed PARITY.md and the evidence-based projection_backend default.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _bench(fn, *args, iters: int = 30, warmup: int = 3) -> float:
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_projection_standalone(batch: int = 256) -> list[dict]:
    """Raw projection op: XLA one-hot-matmul vs Pallas kernel."""
    from d4pg_tpu.ops import categorical_projection, make_support
    from d4pg_tpu.ops.pallas_projection import categorical_projection_pallas

    rows = []
    rng = np.random.default_rng(0)
    for atoms in (51, 101, 201):
        support = make_support(-150.0, 150.0, atoms)
        probs = jnp.asarray(
            rng.dirichlet(np.ones(atoms), size=batch), jnp.float32
        )
        rewards = jnp.asarray(rng.uniform(-1, 0, batch), jnp.float32)
        discounts = jnp.full((batch,), 0.99**3, jnp.float32)
        interpret = jax.default_backend() != "tpu"

        xla_fn = jax.jit(lambda p, r, d: categorical_projection(support, p, r, d))
        pallas_fn = jax.jit(
            lambda p, r, d: categorical_projection_pallas(
                support, p, r, d, interpret
            )
        )
        t_xla = _bench(xla_fn, probs, rewards, discounts)
        t_pallas = _bench(pallas_fn, probs, rewards, discounts)
        rows.append(
            {
                "bench": "projection_standalone",
                "atoms": atoms,
                "batch": batch,
                "xla_us": round(t_xla * 1e6, 1),
                "pallas_us": round(t_pallas * 1e6, 1),
                "pallas_speedup": round(t_xla / t_pallas, 2),
            }
        )
    return rows


def bench_fused_train(atoms: int, backend: str, dtype: str, K: int = 64,
                      batch: int = 256) -> dict:
    """grad-steps/s of the fused K-step train scan under each config."""
    from d4pg_tpu.agent import D4PGConfig, create_train_state
    from d4pg_tpu.agent.d4pg import fused_train_scan
    from d4pg_tpu.models.critic import DistConfig

    config = D4PGConfig(
        obs_dim=17, action_dim=6, hidden_sizes=(256, 256, 256),
        dist=DistConfig(kind="categorical", num_atoms=atoms,
                        v_min=-150.0, v_max=150.0),
        compute_dtype=dtype,
        projection_backend=backend,
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batches = {
        "obs": jnp.asarray(rng.normal(size=(K, batch, 17)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, (K, batch, 6)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, (K, batch)), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(K, batch, 17)), jnp.float32),
        "discount": jnp.full((K, batch), 0.99**3, jnp.float32),
        "weights": jnp.ones((K, batch), jnp.float32),
    }
    step = jax.jit(lambda s, b: fused_train_scan(config, s, b)[0])
    t = _bench(step, state, batches, iters=10)
    return {
        "bench": "fused_train_scan",
        "atoms": atoms,
        "projection": backend,
        "compute_dtype": dtype,
        "grad_steps_per_sec": round(K / t),
    }


def main() -> None:
    print(f"# backend: {jax.default_backend()}, device: {jax.devices()[0]}")
    for row in bench_projection_standalone():
        print(json.dumps(row))
    for atoms in (51, 101, 201):
        for backend in ("xla", "pallas"):
            print(json.dumps(bench_fused_train(atoms, backend, "float32")))
    # bf16 compute path (MXU-native matmuls), XLA projection
    for atoms in (51,):
        print(json.dumps(bench_fused_train(atoms, "xla", "bfloat16")))


if __name__ == "__main__":
    main()
