"""Chip-independent sharded-megastep microbench (tier-1-safe).

The ROADMAP-item-2 claim — the partition-rule learner spans a dp mesh
with the PR-6 zero-transfer steady state intact, and the capacity it
unlocks (wide REDQ ensembles + MoG heads) actually trains at
sharding-load-bearing shapes — must stay measurable with the TPU tunnel
down. Three rows:

- ``megastep_dp1``   — the single-device uniform megastep (the PR-6
  baseline at this shape), via ``bench.bench_megastep``;
- ``megastep_dp8``   — the SAME shape over the 8-way mesh
  (``bench_megastep(dp=8)``: striped sharded ring, shard-local draws,
  deterministic grad mean). Transfer bytes are counted from the exact
  arrays staged/fetched and must be ZERO per grad step for every device
  row — the zero-transfer budget surviving scale-out is the headline
  here, not CPU steps/s (8 virtual devices time-slice ~2 real cores, so
  the dp8/dp1 ratio on this box measures thread thrash, not the mesh;
  the schema smoke pins the transfer claim and the artifact tags the
  backend);
- ``megastep_per_dp8`` — DEVICE-RESIDENT PER over the same 8-way mesh
  (ISSUE 14: ``bench_megastep(dp=8, per=True)`` — shard-local priority
  subtrees over the striped ring, descent/IS-weights/write-back inside
  the sharded megastep, root combine via the deterministic fixed-order
  reductions). The zero-bytes contract now covers PRIORITIZED replay:
  ``schema_check.check_shard_microbench`` refuses an artifact whose PER
  row pays any per-grad-step transfer;
- ``ensemble_mog_wide`` — the capacity row: an E-wide critic ensemble
  with the mixture-of-Gaussians head at an MXU-friendly width through
  the GSPMD dp×tp step, member stack sharded over "tp" via the rule
  registry's stack_axes declaration (``bench.bench_ensemble_capacity``).

Run as a script to (re)generate ``benchmarks/shard_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/shard_microbench.py

On-chip recipe (when the TPU tunnel returns): run the same script
WITHOUT ``JAX_PLATFORMS=cpu`` on a multi-chip TPU VM (the virtual-mesh
flag is only applied for CPU runs); sweep view: ``python
benchmarks/mfu_sweep.py --sharded-only`` adds the sharded points at the
wide shapes while preserving the committed on-chip rows. The training-
run form of the same claim: ``python train.py --replay-placement device
--dp 8 --steps-per-dispatch 32 --debug-guards`` (the transfer guard
enforces the zero-transfer budget at the sharded dispatch site).

``tests/test_shard_microbench.py`` runs the same function at smaller
shapes every tier-1 pass and pins the committed artifact's schema +
headline (zero transfer bytes on both device rows, an ensemble row with
E >= 4 at width >= 512).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    batch: int = 256,
    k: int = 8,
    hidden: int = 256,
    rows: int = 16_384,
    steps: int = 4,
    dp: int = 8,
    repeats: int = 2,
    ens_hidden: int = 512,
    ens_batch: int = 256,
    ensemble: int = 4,
) -> dict:
    """Time dp=1 vs dp=N sharded megastep at one (batch, k, model) shape
    plus the ensemble/MoG capacity row; count per-grad-step transfer
    bytes (must stay 0 for device placement — the accounting is from the
    exact arrays staged, so the zero is chip-independent by construction).

    Same min-of-interleaved-repeats protocol as the sibling microbenches
    (all repeats kept under ``steps_per_sec_repeats``)."""
    import jax

    from bench import bench_ensemble_capacity, bench_megastep

    out = {
        "metric": "shard_microbench",
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "batch": batch,
        "k": k,
        "hidden": hidden,
        "rows": rows,
        "steps": steps,
        "repeats": repeats,
        "on_chip_recipe": (
            "unset JAX_PLATFORMS and rerun on a multi-chip TPU VM; sweep "
            "view: python benchmarks/mfu_sweep.py --sharded-only; training "
            "form: python train.py --replay-placement device --dp 8 "
            "--steps-per-dispatch 32 --debug-guards"
        ),
    }
    variants = [
        (
            "megastep_dp1",
            lambda: bench_megastep(
                placement="device", steps=steps, batch=batch, k=k,
                hidden=hidden, rows=rows,
            ),
        ),
        (
            f"megastep_dp{dp}",
            lambda: bench_megastep(
                placement="device", steps=steps, batch=batch, k=k,
                hidden=hidden, rows=rows, dp=dp,
            ),
        ),
        (
            f"megastep_per_dp{dp}",
            lambda: bench_megastep(
                placement="device", per=True, steps=steps, batch=batch,
                k=k, hidden=hidden, rows=rows, dp=dp,
            ),
        ),
        (
            "ensemble_mog_wide",
            lambda: bench_ensemble_capacity(
                ensemble=ensemble, hidden=ens_hidden, batch=ens_batch,
                dp=max(1, dp // 2), tp=2, steps=max(2, steps // 2),
            ),
        ),
    ]
    for _ in range(repeats):
        for name, fn in variants:
            r = fn()
            prev = out.get(name)
            r["steps_per_sec_repeats"] = (
                prev["steps_per_sec_repeats"] if prev else []
            ) + [round(r["steps_per_sec"], 1)]
            if prev is None or r["steps_per_sec"] > prev["steps_per_sec"]:
                out[name] = r
            else:
                prev["steps_per_sec_repeats"] = r["steps_per_sec_repeats"]
    dp_key = f"megastep_dp{dp}"
    if out["megastep_dp1"]["steps_per_sec"] > 0:
        out["dp_steps_ratio"] = round(
            out[dp_key]["steps_per_sec"]
            / out["megastep_dp1"]["steps_per_sec"],
            4,
        )
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU virtual mesh for the sharded rows; on-chip runs (no
        # JAX_PLATFORMS override) use the real device topology as-is.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    artifact = os.path.join(
        os.path.dirname(__file__), "shard_microbench.json"
    )
    print(json.dumps(run_microbench(artifact)))
