"""Chip-independent megastep-vs-host data-plane microbench (tier-1-safe).

The ROADMAP-item-1 claim — the device-resident ring + fused megastep
removes the per-grad-step H2D batch upload and D2H priority fetch that pin
the learner to the link (``BENCH_r04``: 9% MFU, ``hbm_util`` ≈ 1.3) — must
stay measurable with the TPU tunnel down. Two halves:

- **transfer bytes** are counted from the exact host arrays each loop
  stages/fetches (not estimated), so the before/after is chip-independent
  by construction: host path = full batch fields up + priorities down per
  dispatch; hybrid = [K, B] int32 indices + f32 IS weights up, [K, B]
  priorities down; device = ZERO;
- **steps/s** runs whatever backend is available (CPU interpret here) —
  on CPU the megastep still wins because the host path pays sampling +
  staging per dispatch on the same cores doing the math, but the number
  that matters is the on-chip one (recipe below).

Variants, all at the flagship learner shape (obs 17, act 6, 3×256 MLPs,
C51, batch 256, K=32 — the ``--steps-per-dispatch 32`` configuration the
host-pipeline bench pins):

- ``host_block_k32``   — the PR-2 host data plane (``sample_block`` +
  staged H2D batch), via ``bench.bench_host_pipeline``;
- ``hybrid_k32``       — host PER indices, on-device gather
  (``bench.bench_megastep(placement="hybrid")``) — the LEGACY PER
  placement since ISSUE 14, kept as the host-tree oracle row;
- ``device_k32``       — uniform in-kernel draw, zero transfers
  (``bench.bench_megastep(placement="device")``);
- ``device_per_k32``   — DEVICE-RESIDENT PER (ISSUE 14): the priority
  segment tree in HBM, descent + IS weights + write-back inside the
  fused megastep (``bench.bench_megastep(placement="device",
  per=True)``) — prioritized replay at the same ZERO transfer bytes
  per grad step as the uniform row, the finish line of the raw-speed
  arc (vs hybrid's [K, B] round-trip and host's full-batch traffic).

Run as a script to (re)generate ``benchmarks/megastep_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/megastep_microbench.py

On-chip recipe (when the TPU tunnel returns): run the same script WITHOUT
``JAX_PLATFORMS=cpu`` on the TPU VM, or take the sweep view —
``python benchmarks/mfu_sweep.py`` now includes the megastep points at
the mlp256/B≥512 shapes where ``mfu_sweep_results.json`` measured the
9% → 53% MFU headroom this data plane exists to reach. The training-run
form of the same claim: ``python train.py --replay-placement device
--steps-per-dispatch 32 --debug-guards`` (the transfer guard enforces the
zero-transfer budget at the dispatch site).

``tests/test_megastep_microbench.py`` runs the same function at smaller
shapes every tier-1 pass and pins the committed artifact's schema +
headline (megastep ≥ host steps/s, strictly lower transfer bytes).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    batch: int = 256,
    k: int = 32,
    hidden: int = 256,
    rows: int = 65_536,
    steps: int = 8,
    repeats: int = 2,
) -> dict:
    """Time host-block vs hybrid vs device paths at one (batch, k, model)
    shape; count per-grad-step transfer bytes for each.

    Same min-of-interleaved-repeats protocol as the host-pipeline
    microbench: the shared few-core bench host shows bursty interference,
    and min-of-repeats reads the machine's floor through it (all repeats
    kept under ``steps_per_sec_repeats``). Returns the artifact dict;
    writes it to ``out_path`` when given.
    """
    import jax

    from bench import bench_host_pipeline, bench_megastep

    out = {
        "metric": "megastep_microbench",
        "backend": jax.default_backend(),
        "batch": batch,
        "k": k,
        "hidden": hidden,
        "rows": rows,
        "steps": steps,
        "repeats": repeats,
        "on_chip_recipe": (
            "unset JAX_PLATFORMS and rerun on the TPU VM; sweep view: "
            "python benchmarks/mfu_sweep.py (megastep points); training "
            "form: python train.py --replay-placement device "
            "--steps-per-dispatch 32 --debug-guards"
        ),
    }
    variants = [
        (
            "host_block_k32",
            lambda: bench_host_pipeline(
                prefetch=False, sampler="block", steps=steps, batch=batch,
                k=k, hidden=hidden, rows=rows, compute_dtype="float32",
            ),
        ),
        (
            "hybrid_k32",
            lambda: bench_megastep(
                placement="hybrid", steps=steps, batch=batch, k=k,
                hidden=hidden, rows=rows,
            ),
        ),
        (
            "device_k32",
            lambda: bench_megastep(
                placement="device", steps=steps, batch=batch, k=k,
                hidden=hidden, rows=rows,
            ),
        ),
        (
            "device_per_k32",
            lambda: bench_megastep(
                placement="device", per=True, steps=steps, batch=batch,
                k=k, hidden=hidden, rows=rows,
            ),
        ),
    ]
    for _ in range(repeats):
        for name, fn in variants:
            r = fn()
            prev = out.get(name)
            r["steps_per_sec_repeats"] = (
                prev["steps_per_sec_repeats"] if prev else []
            ) + [round(r["steps_per_sec"], 1)]
            if prev is None or r["steps_per_sec"] > prev["steps_per_sec"]:
                out[name] = r
            else:
                prev["steps_per_sec_repeats"] = r["steps_per_sec_repeats"]
    host = out["host_block_k32"]
    for name in ("hybrid_k32", "device_k32", "device_per_k32"):
        if host["steps_per_sec"] > 0:
            out[f"{name}_steps_ratio"] = round(
                out[name]["steps_per_sec"] / host["steps_per_sec"], 4
            )
        if host["transfer_bytes_per_grad_step"] > 0:
            out[f"{name}_transfer_ratio"] = round(
                out[name]["transfer_bytes_per_grad_step"]
                / host["transfer_bytes_per_grad_step"],
                6,
            )
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(
        os.path.dirname(__file__), "megastep_microbench.json"
    )
    print(json.dumps(run_microbench(artifact)))
