"""Chip-independent host data-plane microbench (tier-1-safe).

The round-7 claim — the native batched replay gather/sample/write-back cuts
host time per dispatch vs the PR 1 legacy path — must stay measurable with
the TPU tunnel down: every timed stage here (PER descent, row gather,
staging, priority write-back) is HOST CPU work, so the before/after
comparison is chip-independent by construction; only the jitted train step
runs on whatever backend is available, and its time is reported separately
(``train_dispatch``) rather than folded into the host numbers.

Variants, all through ``bench.bench_host_pipeline``'s pinned loop:

- ``legacy_*``  — PR 1 data plane: per-batch ``sample()`` /
  ``sample_many`` + per-field fancy-index gathers + ``np.stack``;
- ``block_*``   — round-7 data plane: ``sample_block`` (one backend call
  into preallocated staging; with the native backend, one C call);
- ``*_numpy_*`` — NumPy-tree oracle baseline (native build unused).

Run as a script to (re)generate ``benchmarks/host_pipeline_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/host_pipeline_microbench.py

``tests/test_host_pipeline_microbench.py`` runs the same function at
smaller shapes every tier-1 pass.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    batch: int = 128,
    rows: int = 16_384,
    steps: int = 80,
    hidden: int = 64,
    ks: tuple = (1, 8),
    backends: tuple = ("auto", "numpy"),
    repeats: int = 3,
) -> dict:
    """Time legacy vs block samplers per tree backend and dispatch width.

    Each variant runs ``repeats`` times INTERLEAVED (full variant sweep per
    repeat, not back-to-back) and keeps the repeat with the lowest
    ``host_ms_per_dispatch``: the shared few-core bench host shows bursty
    interference that inflates every stage — including the sampler-
    independent ``train_dispatch`` — by 2-3× for seconds at a time, and
    min-of-repeats is the standard way to read the machine's floor through
    that. All repeats' host-ms readings are kept under ``host_ms_repeats``
    so the spread stays visible.

    Returns the artifact dict; writes it to ``out_path`` when given.
    """
    import jax

    from bench import bench_host_pipeline

    out = {
        "metric": "host_pipeline_microbench",
        "backend": jax.default_backend(),
        "batch": batch,
        "rows": rows,
        "steps": steps,
        "hidden": hidden,
        "repeats": repeats,
    }
    variants = [
        (f"{sampler}_{tb}_k{k}", dict(tree_backend=tb, sampler=sampler, k=k))
        for k in ks
        for tb in backends
        for sampler in ("legacy", "block")
    ]
    for _ in range(repeats):
        for name, kw in variants:
            r = bench_host_pipeline(
                prefetch=False,
                steps=steps,
                batch=batch,
                rows=rows,
                hidden=hidden,
                compute_dtype="float32",
                **kw,
            )
            # the resolved backend ("auto" may degrade to numpy when g++
            # is unavailable) is inside r["tree_backend"]
            prev = out.get(name)
            r["host_ms_repeats"] = (
                prev["host_ms_repeats"] if prev else []
            ) + [r["host_ms_per_dispatch"]]
            if prev is None or (
                r["host_ms_per_dispatch"] < prev["host_ms_per_dispatch"]
            ):
                out[name] = r
            else:
                prev["host_ms_repeats"] = r["host_ms_repeats"]
    for k in ks:
        legacy = out[f"legacy_auto_k{k}"]["host_ms_per_dispatch"]
        block = out[f"block_auto_k{k}"]["host_ms_per_dispatch"]
        if legacy > 0:
            # the headline: host data-plane time per dispatch, after/before
            out[f"host_ms_ratio_k{k}"] = round(block / legacy, 4)
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(
        os.path.dirname(__file__), "host_pipeline_microbench.json"
    )
    print(json.dumps(run_microbench(artifact)))
