"""Generate the committed composition matrix (ISSUE 13 acceptance).

``benchmarks/composition_matrix.json`` is the machine-readable claim
that EVERY scenario × placement cell of the data plane is either
``pass``, ``negotiated`` (honored with a declared downgrade action), or
a DECLARED capability gap with a machine-readable reason code — zero
undeclared refusals. The cells are ``d4pg_tpu.replay.source``'s
``composition_matrix()`` evaluated over its scenario grid; the
schema gate (``tools/d4pglint/schema_check.py:check_composition_matrix``)
re-evaluates the grid at lint time and fails on ANY drift, so a new
refusal can never land without a declared matrix cell.

The ``wire_encodings`` table states the fleet wire tradeoff the
negotiation chooses between (bytes per window row per obs mode, at the
flagship flat shape and the pixel shape — the 17.4 MB/s ingest bench is
why pixel rows never ride f32).

Chip-independent by construction (pure rule-table evaluation):
regenerate with ``python benchmarks/composition_matrix.py``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from d4pg_tpu.fleet import wire  # noqa: E402  (JAX-free)
from d4pg_tpu.replay import source  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "composition_matrix.json")

SCHEMA = "composition-matrix/v1"


def build() -> dict:
    cells = source.composition_matrix()
    counts = {"pass": 0, "negotiated": 0, "gap": 0}
    for c in cells:
        counts[c["verdict"]] += 1
    encodings = {}
    for label, (obs_dim, action_dim) in (
        ("flat_obs17_act6", (17, 6)),
        ("pixel_48x48x2_act1", (48 * 48 * 2, 1)),
    ):
        encodings[label] = {
            mode: {
                "row_bytes": wire.window_row_bytes(obs_dim, action_dim, mode),
                "max_windows_per_frame": wire.max_windows_per_frame(
                    obs_dim, action_dim, obs_mode=mode
                ),
            }
            for mode in source.OBS_MODES
        }
    return {
        "backend": "chip-independent",
        "schema": SCHEMA,
        "generated_by": "benchmarks/composition_matrix.py",
        "scenarios": [name for name, _ in source.SCENARIOS],
        "placements": list(source.PLACEMENTS),
        "counts": counts,
        "cells": cells,
        "wire_encodings": encodings,
    }


def main(out: str = OUT) -> int:
    doc = build()
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(
        f"wrote {out}: {doc['counts']['pass']} pass / "
        f"{doc['counts']['negotiated']} negotiated / "
        f"{doc['counts']['gap']} declared gaps over "
        f"{len(doc['cells'])} cells"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else OUT))
