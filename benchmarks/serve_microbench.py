"""Chip-independent serving microbench (tier-1-safe).

The PR-3 serving claims — dynamic batching multiplies throughput over
single-request serving, and past saturation the server sheds explicitly
with bounded latency instead of letting the queue diverge — must stay
measurable with the TPU tunnel down. The batching/queue/socket mechanics
are host CPU work; only the actor forward runs on the backend, so the
ratios and shed behavior are chip-independent by the same argument as
``host_pipeline_microbench``.

Three scenarios through ``bench.bench_serve``'s pinned load generator:

- ``throughput``  — real device calls, throughput-tuned window
  (``max_wait_us=5000``): the headline ``batched_over_single`` ratio
  (closed-loop saturated ÷ closed-loop single-request rps). Acceptance
  floor: ≥ 5×.
- ``low_latency`` — ``max_wait_us=0``: the latency-optimal end of the SLO
  knob; single-request p50 here is the floor a windowed config trades
  away (docs/serving.md).
- ``overload``    — a 20 ms slow-device stub caps capacity BELOW what the
  stdlib load generator can offer (the real batcher outruns it on this
  host), so the open-loop sweep crosses saturation and the queue-full /
  deadline shedding engages: shed-rate and p99 are reported per offered
  load level, with sub-saturation levels showing zero shed and flat p99.

Run as a script to (re)generate ``benchmarks/serve_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/serve_microbench.py

``tests/test_serve_microbench.py`` runs the same function at smaller
shapes every tier-1 pass and pins the committed artifact's schema + the
≥5× headline.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    hidden: int = 64,
    max_batch: int = 64,
    duration_s: float = 2.5,
    closed_wide: tuple = (4, 32),
    overload_rates: tuple = (300, 700, 1100),
    repeats: int = 3,
) -> dict:
    """Run the three scenarios; keep the best-throughput repeat of the
    headline scenario (min-of-repeats discipline — the shared bench host
    shows bursty interference; see host_pipeline_microbench), all repeats'
    ratios kept visible under ``ratio_repeats``."""
    import jax

    from bench import bench_serve

    out = {
        "metric": "serve_microbench",
        "backend": jax.default_backend(),
        "hidden": hidden,
        "max_batch": max_batch,
        "duration_s": duration_s,
        "repeats": repeats,
    }
    ratios = []
    best = None
    for _ in range(repeats):
        r = bench_serve(
            hidden=hidden,
            max_batch=max_batch,
            max_wait_us=5000,
            queue_limit=4 * max_batch,
            closed_profiles=((1, 1), closed_wide),
            open_load_factors=(0.5, 1.0),
            duration_s=duration_s,
        )
        ratios.append(r["batched_over_single"])
        # keep the best-RATIO repeat: the ratio is the metric of record,
        # and interference on this shared host deflates it (it slows the
        # many-threaded saturated phase far more than the single phase) —
        # min-of-repeats through that noise, same as host_pipeline
        if best is None or r["batched_over_single"] > best["batched_over_single"]:
            best = r
    out["throughput"] = best
    out["ratio_repeats"] = ratios
    out["batched_over_single"] = best["batched_over_single"]

    out["low_latency"] = bench_serve(
        hidden=hidden,
        max_batch=max_batch,
        max_wait_us=0,
        queue_limit=4 * max_batch,
        closed_profiles=((1, 1),),
        open_load_factors=(),
        duration_s=duration_s,
    )

    out["overload"] = bench_serve(
        hidden=32,
        max_batch=16,
        max_wait_us=2000,
        queue_limit=64,
        closed_profiles=((1, 1), (4, 16)),
        open_rates=overload_rates,
        duration_s=duration_s,
        # 100 ms SLO ≈ 4-5 stub service times of headroom: sub-saturation
        # levels ride queue jitter without shedding, so the per-level story
        # is clean (0 → 0 → engaged) instead of metastable edge noise.
        deadline_ms=100.0,
        infer_delay_ms=20.0,
    )

    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(os.path.dirname(__file__), "serve_microbench.json")
    result = run_microbench(artifact)
    print(
        json.dumps(
            {
                "metric": "serve_microbench",
                "batched_over_single": result["batched_over_single"],
                "single_rps": result["throughput"]["single_rps"],
                "saturated_rps": result["throughput"]["saturated_rps"],
                "overload_top_shed_rate": result["overload"]["open_loop"][-1][
                    "shed_rate"
                ],
                "artifact": artifact,
            }
        )
    )
