"""Chip-independent multi-tenant serving microbench (tier-1-safe).

The ISSUE-12 claims as a committed machine-checked artifact, the
``identity_ok`` discipline of ``router_microbench.json`` extended to the
multi-tenant surfaces:

- ``isolation``          — the same interactive population measured alone
  and under a FLOODING bulk tenant through the router's class-aware
  admission (quota/bulk-capacity shed): ``isolation_ok`` pins that the
  flood cannot move interactive p99 past its SLO, with the
  per-(tenant, class) accounting identity exact on every healthz row.
  Must hold on EVERY repeat — one leaked flood is a bug, not noise.
- ``autoscale_scaling``  — aggregate ok-rps measured at 1 replica and
  again after the healthz-driven autoscaler grew the fleet to 2 under
  load (in-process pool through ``router.add_backend``; the
  subprocess-spawning pool is proven in chaos_soak.sh leg 7). Best
  repeat kept (the shared 2-core bench host's interference discipline of
  router_microbench), all ratios visible under ``ratio_repeats``.

Per-replica capacity is pinned device-bound by the labeled
``infer_delay_ms`` slow-device stub — same argument as the router bench:
on a few-core host the real tiny-MLP batcher is host-bound and a second
in-process replica would measure GIL thrash, not admission or dispatch.

Run as a script to (re)generate ``benchmarks/multitenant_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/multitenant_microbench.py

``tests/test_multitenant_microbench.py`` runs the same function at a
smaller shape every tier-1 pass and pins the committed artifact's schema
+ the isolation and scaling headlines.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    hidden: int = 16,
    max_batch: int = 16,
    duration_s: float = 2.0,
    infer_delay_ms: float = 50.0,
    replica_capacity: int = 24,
    scale_window_s: float = 1.0,
    repeats: int = 3,
) -> dict:
    import jax

    from bench import bench_serve_multitenant

    out = {
        "metric": "multitenant_microbench",
        "backend": jax.default_backend(),
        "hidden": hidden,
        "max_batch": max_batch,
        "duration_s": duration_s,
        "infer_delay_ms": infer_delay_ms,
        "repeats": repeats,
    }
    ratios = []
    best = None
    for _ in range(repeats):
        r = bench_serve_multitenant(
            hidden=hidden,
            max_batch=max_batch,
            duration_s=duration_s,
            infer_delay_ms=infer_delay_ms,
            replica_capacity=replica_capacity,
            scale_window_s=scale_window_s,
        )
        iso = r["isolation"]
        assert iso["isolation_ok"], (
            "bulk flood moved interactive p99 past its SLO: "
            f"p99={iso['interactive_p99_ms']} slo={iso['slo_ms']}"
        )
        assert iso["tenant_identity_ok"] and iso["router_identity_ok"], (
            "per-tenant accounting identity broken under the flood: "
            f"{iso['tenants']}"
        )
        assert r["autoscale_scaling"]["identity_ok"], (
            "accounting identity broken across the scale-up: "
            f"{r['autoscale_scaling']}"
        )
        ratios.append(r["autoscale_scaling"]["scaling_2_over_1"])
        if best is None or (
            r["autoscale_scaling"]["scaling_2_over_1"]
            > best["autoscale_scaling"]["scaling_2_over_1"]
        ):
            best = r
    out.update(best)
    out["ratio_repeats"] = ratios

    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(
        os.path.dirname(__file__), "multitenant_microbench.json"
    )
    result = run_microbench(artifact)
    iso = result["isolation"]
    print(
        json.dumps(
            {
                "metric": "multitenant_microbench",
                "interactive_p99_ms_baseline":
                    iso["interactive_baseline"]["p99_ms"],
                "interactive_p99_ms_under_flood": iso["interactive_p99_ms"],
                "slo_ms": iso["slo_ms"],
                "isolation_ok": iso["isolation_ok"],
                "bulk_shed_rate": iso["bulk_shed_rate"],
                "autoscale_scaling_2_over_1":
                    result["autoscale_scaling"]["scaling_2_over_1"],
                "artifact": artifact,
            }
        )
    )
