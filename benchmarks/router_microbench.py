"""Chip-independent replica-front-end microbench (tier-1-safe).

The PR-8 serving-fleet claims — the router multiplies aggregate capacity
across replicas, and a mid-stream replica kill costs availability, never
accounting integrity — must stay measurable with the TPU tunnel down. The
dispatch/probe/failover mechanics are host CPU work; per-replica capacity
is pinned by a labeled ``infer_delay_ms`` slow-device stub (the same
device-bound-regime trick as serve_microbench's overload scenario: on a
few-core host the real tiny-MLP batcher is host-bound, so a second
in-process replica would just measure GIL thrash).

Two surfaces through ``bench.bench_serve_router``'s pinned load generator:

- ``scaling``      — the same closed population against 1 vs 2 replicas:
  aggregate throughput and p99. Acceptance floor: ≥ 1.5× at 2 replicas
  (ideal is 2.0×; the committed run shows 1.72× best-of-3 — 293 → 503
  rps with p99 251 → 179 ms — the gap to 2.0× being this 2-core host
  routing, probing, and generating load beside both replicas).
- ``availability`` — sustained closed-loop load on the 2-replica fleet
  while one replica is killed abruptly mid-stream: the accounting
  identity (submitted == ok + overloaded + failed, zero silent losses)
  must hold EXACTLY, and availability (ok/submitted) stays ≥ 0.99 because
  in-flight requests on the dead replica fail over via the router's
  bounded retry.

Run as a script to (re)generate ``benchmarks/router_microbench.json``:

    JAX_PLATFORMS=cpu python benchmarks/router_microbench.py

``tests/test_router_microbench.py`` runs the same function at smaller
shapes every tier-1 pass and pins the committed artifact's schema + the
scaling and availability headlines.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_microbench(
    out_path: str | None = None,
    *,
    hidden: int = 16,
    max_batch: int = 16,
    conns: int = 4,
    window: int = 16,
    duration_s: float = 2.0,
    infer_delay_ms: float = 50.0,
    repeats: int = 3,
) -> dict:
    """Run the scaling + availability legs; keep the best-scaling repeat
    (the shared bench host shows bursty interference that deflates the
    many-threaded 2-replica leg far more than the 1-replica leg — same
    min-of-repeats discipline as serve_microbench), all repeats' ratios
    kept visible under ``ratio_repeats``. The availability identity must
    hold on EVERY repeat — one silent loss anywhere is a bug, not noise."""
    import jax

    from bench import bench_serve_router

    out = {
        "metric": "router_microbench",
        "backend": jax.default_backend(),
        "hidden": hidden,
        "max_batch": max_batch,
        "duration_s": duration_s,
        "infer_delay_ms": infer_delay_ms,
        "repeats": repeats,
    }
    ratios = []
    best = None
    for _ in range(repeats):
        r = bench_serve_router(
            hidden=hidden,
            max_batch=max_batch,
            conns=conns,
            window=window,
            duration_s=duration_s,
            infer_delay_ms=infer_delay_ms,
        )
        assert r["availability"]["identity_ok"], (
            "accounting identity broken during replica kill: "
            f"{r['availability']}"
        )
        ratios.append(r["scaling_2_over_1"])
        if best is None or r["scaling_2_over_1"] > best["scaling_2_over_1"]:
            best = r
    out.update(best)
    out["ratio_repeats"] = ratios

    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
    return out


if __name__ == "__main__":
    artifact = os.path.join(os.path.dirname(__file__), "router_microbench.json")
    result = run_microbench(artifact)
    print(
        json.dumps(
            {
                "metric": "router_microbench",
                "scaling_2_over_1": result["scaling_2_over_1"],
                "rps_1": result["scaling"][0]["throughput_rps"],
                "rps_2": result["scaling"][1]["throughput_rps"],
                "availability": result["availability"]["availability"],
                "kill_identity_ok": result["availability"]["identity_ok"],
                "artifact": artifact,
            }
        )
    )
