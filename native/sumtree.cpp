// Native segment trees for prioritized replay.
//
// The host-side PER trees are the one part of the data path that must keep
// up with a TPU-speed learner from plain CPU code (SURVEY.md §7 hard part
// (b): the reference's pointer-chasing Python trees,
// prioritized_replay_memory.py:61-112, top out far below learner rate).
// Layout matches d4pg_tpu/replay/segment_tree.py: tree[1] is the root,
// leaves at [capacity, 2*capacity). Batched ops are scalar loops here —
// O(log C) per element with no interpreter overhead, which beats the
// vectorized-NumPy level passes at typical batch sizes (256) and large
// capacities (1e6).
//
// Exposed via a C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

struct Tree {
  int64_t capacity;       // power of two
  bool is_min;
  std::vector<double> v;  // size 2*capacity

  double combine(double a, double b) const {
    return is_min ? std::min(a, b) : a + b;
  }
};

int64_t next_pow2(int64_t n) {
  int64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void* st_create(int64_t capacity, int is_min) {
  Tree* t = new Tree();
  t->capacity = next_pow2(capacity);
  t->is_min = is_min != 0;
  double neutral = is_min ? std::numeric_limits<double>::infinity() : 0.0;
  t->v.assign(2 * t->capacity, neutral);
  return t;
}

void st_destroy(void* h) { delete static_cast<Tree*>(h); }

int64_t st_capacity(void* h) { return static_cast<Tree*>(h)->capacity; }

void st_set(void* h, const int64_t* idx, const double* vals, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t pos = idx[i] + t->capacity;
    t->v[pos] = vals[i];
    for (pos >>= 1; pos >= 1; pos >>= 1) {
      t->v[pos] = t->combine(t->v[2 * pos], t->v[2 * pos + 1]);
    }
  }
}

void st_get(void* h, const int64_t* idx, double* out, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) out[i] = t->v[idx[i] + t->capacity];
}

double st_root(void* h) { return static_cast<Tree*>(h)->v[1]; }

// Batched proportional-sampling descent; boundary convention matches the
// NumPy tree (prefix == left-subtree mass goes right, skipping zero leaves).
void st_find_prefix(void* h, const double* prefixes, int64_t* out, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) {
    double p = prefixes[i];
    int64_t pos = 1;
    while (pos < t->capacity) {
      double left = t->v[2 * pos];
      if (p >= left) {
        p -= left;
        pos = 2 * pos + 1;
      } else {
        pos = 2 * pos;
      }
    }
    out[i] = pos - t->capacity;
  }
}

// ------------------------------------------------------------- data plane
// Fused stratified-sample + gather: ONE call per learner dispatch does the
// K·B prefix-sum descents, the IS-weight computation, the generation-stamp
// capture, and the row gather of every transition field into caller-owned
// staging buffers. Replaces (descent call + 5 NumPy fancy-index gathers +
// np.stack + weight vector math) per dispatch — the Python-side data-plane
// cost Ape-X/Reverb identify as the throughput wall of distributed PER.
//
// prefixes are caller-generated (NumPy Generator) so the seeded draw stream
// is byte-identical to the NumPy oracle path. Draw j is dealt round-robin
// into output row (j % K)·B + j/K, i.e. contiguous [K, B] blocks whose
// batch i equals the NumPy path's flat[i::K] slice.
//
// obs_mode: 0 = float32 rows copied as-is; 1 = uint8 rows decoded to
// float32/255 (quantized pixel replay, decode-on-sample); 2 = uint8 rows
// copied raw (uint8 wire format — dequantized in-jit on device).
void st_sample_gather(void* sum_h, void* min_h, const double* prefixes,
                      int64_t n, int64_t deal_k, int64_t size, double beta,
                      const void* obs, const float* action,
                      const float* reward, const void* next_obs,
                      const float* discount, const int64_t* gen,
                      int64_t obs_dim, int64_t act_dim, int obs_mode,
                      int64_t* idx_out, int64_t* gen_out, float* w_out,
                      void* obs_out, float* act_out, float* rew_out,
                      void* next_obs_out, float* disc_out) {
  Tree* st = static_cast<Tree*>(sum_h);
  Tree* mt = static_cast<Tree*>(min_h);
  const double total = st->v[1];
  // Max IS weight from the min tree, same expression order as the NumPy
  // path so the f64 rounding (and the final f32 cast) agree exactly.
  const double max_w = std::pow((mt->v[1] / total) * (double)size, -beta);
  const int64_t bsz = n / deal_k;
  const float* obs_f = static_cast<const float*>(obs);
  const float* nobs_f = static_cast<const float*>(next_obs);
  const uint8_t* obs_u = static_cast<const uint8_t*>(obs);
  const uint8_t* nobs_u = static_cast<const uint8_t*>(next_obs);
  float* obs_out_f = static_cast<float*>(obs_out);
  float* nobs_out_f = static_cast<float*>(next_obs_out);
  uint8_t* obs_out_u = static_cast<uint8_t*>(obs_out);
  uint8_t* nobs_out_u = static_cast<uint8_t*>(next_obs_out);
  for (int64_t j = 0; j < n; ++j) {
    double p = prefixes[j];
    int64_t pos = 1;
    while (pos < st->capacity) {
      const double left = st->v[2 * pos];
      if (p >= left) {
        p -= left;
        pos = 2 * pos + 1;
      } else {
        pos = 2 * pos;
      }
    }
    int64_t idx = pos - st->capacity;
    if (idx > size - 1) idx = size - 1;
    const int64_t r = (j % deal_k) * bsz + j / deal_k;
    idx_out[r] = idx;
    gen_out[r] = gen[idx];
    const double prob = st->v[st->capacity + idx] / total;
    w_out[r] = (float)(std::pow(prob * (double)size, -beta) / max_w);
    rew_out[r] = reward[idx];
    disc_out[r] = discount[idx];
    std::memcpy(act_out + r * act_dim, action + idx * act_dim,
                act_dim * sizeof(float));
    if (obs_mode == 0) {
      std::memcpy(obs_out_f + r * obs_dim, obs_f + idx * obs_dim,
                  obs_dim * sizeof(float));
      std::memcpy(nobs_out_f + r * obs_dim, nobs_f + idx * obs_dim,
                  obs_dim * sizeof(float));
    } else if (obs_mode == 1) {
      const uint8_t* so = obs_u + idx * obs_dim;
      const uint8_t* sn = nobs_u + idx * obs_dim;
      float* dofs = obs_out_f + r * obs_dim;
      float* dnxt = nobs_out_f + r * obs_dim;
      for (int64_t c = 0; c < obs_dim; ++c) {
        dofs[c] = (float)so[c] / 255.0f;
        dnxt[c] = (float)sn[c] / 255.0f;
      }
    } else {
      std::memcpy(obs_out_u + r * obs_dim, obs_u + idx * obs_dim, obs_dim);
      std::memcpy(nobs_out_u + r * obs_dim, nobs_u + idx * obs_dim, obs_dim);
    }
  }
}

// Batched PER priority write-back: generation filter, (|td|+ε already
// applied caller-side) ^α, both tree updates, and the max-priority reduce
// in one call — the whole Python lock scope becomes this function. Entries
// whose slot was recycled since sampling (sample_gen[i] != cur_gen[idx[i]])
// are dropped, matching SampledIndices semantics. Returns the max applied
// pre-α priority, 0.0 when every entry was dropped (caller leaves
// max_priority untouched). sample_gen == nullptr applies unconditionally
// (raw-index form).
double st_update_priorities(void* sum_h, void* min_h, const int64_t* idx,
                            const double* pri, int64_t n,
                            const int64_t* sample_gen, const int64_t* cur_gen,
                            double alpha) {
  Tree* st = static_cast<Tree*>(sum_h);
  Tree* mt = static_cast<Tree*>(min_h);
  double mx = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    if (sample_gen != nullptr && sample_gen[i] != cur_gen[idx[i]]) continue;
    const double pa = std::pow(pri[i], alpha);
    for (int t = 0; t < 2; ++t) {
      Tree* tr = t ? mt : st;
      int64_t pos = idx[i] + tr->capacity;
      tr->v[pos] = pa;
      for (pos >>= 1; pos >= 1; pos >>= 1) {
        tr->v[pos] = tr->combine(tr->v[2 * pos], tr->v[2 * pos + 1]);
      }
    }
    if (pri[i] > mx) mx = pri[i];
  }
  return mx;
}

}  // extern "C"
