// Native segment trees for prioritized replay.
//
// The host-side PER trees are the one part of the data path that must keep
// up with a TPU-speed learner from plain CPU code (SURVEY.md §7 hard part
// (b): the reference's pointer-chasing Python trees,
// prioritized_replay_memory.py:61-112, top out far below learner rate).
// Layout matches d4pg_tpu/replay/segment_tree.py: tree[1] is the root,
// leaves at [capacity, 2*capacity). Batched ops are scalar loops here —
// O(log C) per element with no interpreter overhead, which beats the
// vectorized-NumPy level passes at typical batch sizes (256) and large
// capacities (1e6).
//
// Exposed via a C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

struct Tree {
  int64_t capacity;       // power of two
  bool is_min;
  std::vector<double> v;  // size 2*capacity

  double combine(double a, double b) const {
    return is_min ? std::min(a, b) : a + b;
  }
};

int64_t next_pow2(int64_t n) {
  int64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void* st_create(int64_t capacity, int is_min) {
  Tree* t = new Tree();
  t->capacity = next_pow2(capacity);
  t->is_min = is_min != 0;
  double neutral = is_min ? std::numeric_limits<double>::infinity() : 0.0;
  t->v.assign(2 * t->capacity, neutral);
  return t;
}

void st_destroy(void* h) { delete static_cast<Tree*>(h); }

int64_t st_capacity(void* h) { return static_cast<Tree*>(h)->capacity; }

void st_set(void* h, const int64_t* idx, const double* vals, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t pos = idx[i] + t->capacity;
    t->v[pos] = vals[i];
    for (pos >>= 1; pos >= 1; pos >>= 1) {
      t->v[pos] = t->combine(t->v[2 * pos], t->v[2 * pos + 1]);
    }
  }
}

void st_get(void* h, const int64_t* idx, double* out, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) out[i] = t->v[idx[i] + t->capacity];
}

double st_root(void* h) { return static_cast<Tree*>(h)->v[1]; }

// Batched proportional-sampling descent; boundary convention matches the
// NumPy tree (prefix == left-subtree mass goes right, skipping zero leaves).
void st_find_prefix(void* h, const double* prefixes, int64_t* out, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) {
    double p = prefixes[i];
    int64_t pos = 1;
    while (pos < t->capacity) {
      double left = t->v[2 * pos];
      if (p >= left) {
        p -= left;
        pos = 2 * pos + 1;
      } else {
        pos = 2 * pos;
      }
    }
    out[i] = pos - t->capacity;
  }
}

}  // extern "C"
