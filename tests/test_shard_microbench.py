"""Tier-1 smokes for the sharded-megastep microbench (ISSUE 9 acceptance).

Two halves, mirroring the other benchmark smokes:

- the GENERATOR runs end-to-end at tiny shapes (so a refactor that breaks
  ``bench_megastep(dp=)``/``bench_ensemble_capacity``/``run_microbench``
  fails here, not at artifact-regen time) — timing ratios are NOT
  asserted at this scale (8 virtual devices over ~2 cores measure thread
  thrash, not the mesh);
- the COMMITTED artifact (``benchmarks/shard_microbench.json``) keeps its
  schema and the chip-independent half of the headline: dp=1 AND dp>1
  megastep rows both at ZERO per-grad-step transfer bytes, plus the
  ensemble/MoG wide-shape capacity row — enforced both here and by
  ``tools.d4pglint.schema_check.check_shard_microbench`` (the lint gate
  covers hand-edits; this smoke covers regeneration drift).
"""

from __future__ import annotations

import json
import os

import pytest

pytest.importorskip("jax")

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks",
    "shard_microbench.json",
)


def test_generator_runs_at_small_shape(tmp_path):
    from benchmarks.shard_microbench import run_microbench

    out_path = str(tmp_path / "shard_microbench.json")
    out = run_microbench(
        out_path, batch=16, k=2, hidden=32, rows=512, steps=2, dp=4,
        repeats=1, ens_hidden=32, ens_batch=16, ensemble=4,
    )
    assert os.path.exists(out_path)
    for name in ("megastep_dp1", "megastep_dp4"):
        row = out[name]
        assert row["steps_per_sec"] > 0
        # the chip-independent half of the claim holds at ANY shape: the
        # sharded steady state stages/fetches NOTHING per grad step
        assert row["transfer_bytes_per_grad_step"] == 0.0
    assert out["megastep_dp4"]["dp"] == 4
    # ISSUE 14: the zero-bytes contract covers PRIORITIZED replay too —
    # shard-local device subtrees, nothing staged/fetched per grad step
    per_row = out["megastep_per_dp4"]
    assert per_row["per"] is True and per_row["dp"] == 4
    assert per_row["transfer_bytes_per_grad_step"] == 0.0
    ens = out["ensemble_mog_wide"]
    assert ens["ensemble"] == 4 and ens["steps_per_sec"] > 0
    with open(out_path) as f:
        json.load(f)  # artifact is valid JSON
    # the lint-side schema check accepts what the generator writes
    from tools.d4pglint.schema_check import check_shard_microbench

    assert check_shard_microbench(out_path) == []


def test_committed_artifact_schema_and_headline():
    with open(ARTIFACT) as f:
        doc = json.load(f)
    assert doc["metric"] == "shard_microbench"
    assert "backend" in doc and "on_chip_recipe" in doc
    dp_rows = {
        k: v for k, v in doc.items()
        if k.startswith("megastep_dp") and isinstance(v, dict)
    }
    assert "megastep_dp1" in dp_rows
    assert any(v["dp"] > 1 for v in dp_rows.values())
    for row in dp_rows.values():
        assert row["steps_per_sec"] > 0
        assert row["steps_per_sec_repeats"]
        assert row["transfer_bytes_per_grad_step"] == 0.0
    per_rows = {
        k: v for k, v in doc.items()
        if k.startswith("megastep_per_") and isinstance(v, dict)
    }
    assert per_rows, "committed artifact lost its device-PER rows"
    assert any(v["dp"] > 1 for v in per_rows.values())
    for row in per_rows.values():
        assert row["per"] is True
        assert row["transfer_bytes_per_grad_step"] == 0.0
        assert row["steps_per_sec"] > 0
    ens = doc["ensemble_mog_wide"]
    assert ens["ensemble"] >= 4
    assert ens["hidden"] >= 512  # the WIDE shape, where sharding is load-bearing
    assert ens["tp"] >= 2 and ens["ensemble_axis"] == "tp"
    assert ens["steps_per_sec"] > 0
    # and the lint gate agrees with the committed bytes
    from tools.d4pglint.schema_check import check_shard_microbench

    assert check_shard_microbench(ARTIFACT) == []


def test_committed_mfu_sweep_has_sharded_rows():
    sweep = os.path.join(os.path.dirname(ARTIFACT), "mfu_sweep_results.json")
    with open(sweep) as f:
        rows = json.load(f)
    sharded = [
        r for r in rows
        if str(r.get("config", "")).startswith("sharded_megastep")
    ]
    assert sharded, "mfu_sweep_results.json lost its sharded rows"
    for r in sharded:
        assert r["bench"] == "mfu_sweep"
        assert "backend" in r  # CPU placeholders must be distinguishable
        assert r["dp"] > 1
        assert r["transfer_bytes_per_grad_step"] == 0.0
        assert r["steps_per_sec"] > 0
    # the plain-megastep family survived the --sharded-only regen
    assert any(
        str(r.get("config", "")) == "megastep_mlp256" for r in rows
    ), "--sharded-only regen clobbered the megastep rows"


def test_committed_mfu_sweep_has_device_per_rows():
    """ISSUE 14: the sweep carries the device-PER family — the wide-shape
    rows reachable by runs using the paper's actual sampling scheme —
    with the zero-transfer column intact, and partial regens preserve
    every other family (the --megastep-only precedent)."""
    sweep = os.path.join(os.path.dirname(ARTIFACT), "mfu_sweep_results.json")
    with open(sweep) as f:
        rows = json.load(f)
    per = [
        r for r in rows
        if str(r.get("config", "")).startswith("device_per_megastep")
    ]
    assert per, "mfu_sweep_results.json lost its device-PER rows"
    for r in per:
        assert r["bench"] == "mfu_sweep"
        assert "backend" in r  # CPU placeholders must be distinguishable
        assert r["transfer_bytes_per_grad_step"] == 0.0
        assert r["steps_per_sec"] > 0
    assert any(r["dp"] > 1 for r in per), "no mesh-spanning device-PER row"
