"""ISSUE 13 byte-parity suite: fleet vs local window CONTENT per mode.

The one-data-plane contract is that HOW experience reaches replay
(in-process writers vs the fleet wire) never changes WHAT lands in it:

- f32 flat windows: byte-identical through WINDOWS and WINDOWS2;
- u8 pixel windows: the wire quantizes at exactly the replay buffer's
  store-time point, so the STORED uint8 bytes are fleet-vs-local
  identical;
- bf16 wire: the one DECLARED-lossy mode — content is pinned to
  f32-cast-through-bfloat16, nothing else;
- obs-norm: raw bytes identical AND the ingest-side statistics fold
  (once per original window) matches the local once-per-observed-step
  fold exactly;
- actor-side HER vs the learner-side HER path (the seeded parity
  oracle): same episode + same relabel rng ⇒ byte-identical buffers.

All at the raw ``add_batch`` level — no sockets, no trainers.
"""

from __future__ import annotations

import numpy as np
import pytest

from d4pg_tpu.fleet import wire
from d4pg_tpu.ops.obs_norm import RunningObsNorm
from d4pg_tpu.replay.her import HindsightWriter
from d4pg_tpu.replay.nstep_writer import NStepWriter
from d4pg_tpu.replay.uniform import ReplayBuffer, Transition
from d4pg_tpu.serve.protocol import ProtocolError
from d4pg_tpu.fleet.actor import _HerWriterFactory, _Spool

OBS, ACT, N_STEP, GAMMA = 5, 2, 3, 0.97


def _episode(rng, length=17):
    """One synthetic episode of raw env steps."""
    steps = []
    obs = rng.random(OBS).astype(np.float32)
    for t in range(length):
        a = (rng.random(ACT) * 2 - 1).astype(np.float32)
        r = float(rng.standard_normal())
        nxt = rng.random(OBS).astype(np.float32)
        steps.append((obs, a, r, nxt, t == length - 1))
        obs = nxt
    return steps


def _spool_to_buffer(spool, buf, obs_mode="f32", via_v2=True):
    """Drain a spool through the wire codec into ``buf.add_batch`` — the
    exact ingest data path, minus the socket."""
    while True:
        frame = spool.take_frame(64)
        if frame is None:
            return
        (gen, stats_gen, relabeled), cols = frame
        if via_v2:
            payload = wire.encode_windows2(
                gen, stats_gen, obs_mode, relabeled,
                cols["obs"], cols["action"], cols["reward"],
                cols["next_obs"], cols["discount"],
            )
            _g, _s, _m, _rel, out = wire.decode_windows2(payload, OBS, ACT)
        else:
            payload = wire.encode_windows(
                gen, cols["obs"], cols["action"], cols["reward"],
                cols["next_obs"], cols["discount"],
            )
            _g, out = wire.decode_windows(payload, OBS, ACT)
        buf.add_batch(Transition(
            out["obs"], out["action"], out["reward"],
            out["next_obs"], out["discount"],
        ))


def _assert_buffers_identical(a: ReplayBuffer, b: ReplayBuffer):
    assert len(a) == len(b)
    n = len(a)
    for col in ("obs", "action", "reward", "next_obs", "discount"):
        av, bv = getattr(a, col)[:n], getattr(b, col)[:n]
        assert av.dtype == bv.dtype
        assert av.tobytes() == bv.tobytes(), f"column {col} differs"


@pytest.mark.parametrize("via_v2", [False, True])
def test_f32_flat_byte_parity(via_v2):
    """Local NStepWriter → buffer  vs  NStepWriter → spool → wire →
    add_batch: byte-identical through WINDOWS (v1) AND WINDOWS2."""
    rng = np.random.default_rng(0)
    steps = _episode(rng)
    local = ReplayBuffer(128, OBS, ACT)
    w = NStepWriter(local, N_STEP, GAMMA)
    for obs, a, r, nxt, last in steps:
        w.add(obs, a, r, nxt, terminated=False, truncated=last)
    fleet = ReplayBuffer(128, OBS, ACT)
    spool = _Spool(512)
    w2 = NStepWriter(spool, N_STEP, GAMMA)
    for obs, a, r, nxt, last in steps:
        w2.add(obs, a, r, nxt, terminated=False, truncated=last)
    _spool_to_buffer(spool, fleet, via_v2=via_v2)
    _assert_buffers_identical(local, fleet)


def test_u8_pixel_byte_parity():
    """Pixel rows: local add_batch quantizes f32→u8 at store time; the
    fleet wire quantizes at the SAME formula, ships bytes, decodes ÷255,
    and add_batch re-quantizes — the stored uint8 bytes must be
    identical (the u8↔f32 round-trip is exact for all 256 values)."""
    rng = np.random.default_rng(1)
    pix = 12
    rows = 40
    obs = rng.random((rows, pix)).astype(np.float32)
    nxt = rng.random((rows, pix)).astype(np.float32)
    act = (rng.random((rows, ACT)) * 2 - 1).astype(np.float32)
    rew = rng.standard_normal(rows).astype(np.float32)
    disc = rng.random(rows).astype(np.float32)
    local = ReplayBuffer(64, pix, ACT, obs_dtype=np.uint8)
    local.add_batch(Transition(obs, act, rew, nxt, disc))
    payload = wire.encode_windows2(0, 0, "u8", False, obs, act, rew, nxt, disc)
    _g, _s, mode, _rel, cols = wire.decode_windows2(payload, pix, ACT)
    assert mode == "u8"
    fleet = ReplayBuffer(64, pix, ACT, obs_dtype=np.uint8)
    fleet.add_batch(Transition(
        cols["obs"], cols["action"], cols["reward"],
        cols["next_obs"], cols["discount"],
    ))
    _assert_buffers_identical(local, fleet)


def test_u8_roundtrip_exact_all_values():
    """Every uint8 value survives quantize→÷255→re-quantize exactly —
    the arithmetic fact the pixel parity rests on."""
    q = np.arange(256, dtype=np.uint8)[None, :]
    dec = q.astype(np.float32) / 255.0
    assert (wire.quantize_obs_u8(dec) == q).all()


def test_bf16_wire_is_declared_round():
    """bf16 mode content == f32 cast through bfloat16 — lossy exactly as
    declared, nothing else."""
    import ml_dtypes

    rng = np.random.default_rng(2)
    obs = (rng.standard_normal((9, OBS)) * 3).astype(np.float32)
    nxt = (rng.standard_normal((9, OBS)) * 3).astype(np.float32)
    act = (rng.random((9, ACT)) * 2 - 1).astype(np.float32)
    rew = rng.standard_normal(9).astype(np.float32)
    disc = rng.random(9).astype(np.float32)
    payload = wire.encode_windows2(
        1, 1, "bf16", False, obs, act, rew, nxt, disc
    )
    _g, _s, _m, _rel, cols = wire.decode_windows2(payload, OBS, ACT)
    want = obs.astype(ml_dtypes.bfloat16).astype(np.float32)
    assert (cols["obs"] == want).all()
    # the f32 side-columns stay byte-exact
    assert cols["action"].tobytes() == act.tobytes()
    assert cols["reward"].tobytes() == rew.tobytes()


def test_windows2_malformed():
    rng = np.random.default_rng(3)
    obs = rng.random((4, OBS)).astype(np.float32)
    act = rng.random((4, ACT)).astype(np.float32)
    r = rng.random(4).astype(np.float32)
    payload = wire.encode_windows2(0, 0, "f32", False, obs, act, r, obs, r)
    with pytest.raises(ProtocolError, match="declares"):
        wire.decode_windows2(payload[:-3], OBS, ACT)  # truncated body
    with pytest.raises(ProtocolError, match="header"):
        wire.decode_windows2(payload[:4], OBS, ACT)
    bad = bytearray(payload)
    bad[12] = 9  # unknown obs mode id
    with pytest.raises(ProtocolError, match="unknown obs mode"):
        wire.decode_windows2(bytes(bad), OBS, ACT)


def test_obs_norm_fold_parity_and_relabel_exclusion():
    """The ingest-side fold (once per ORIGINAL window, in window order)
    reproduces the local once-per-observed-step fold exactly — and
    relabeled windows never touch the statistics."""
    rng = np.random.default_rng(4)
    steps = _episode(rng, length=10)
    # local: fold each acted-on obs, in order (Trainer._ingest_obs)
    local = RunningObsNorm(OBS)
    for obs, *_ in steps:
        local.update(obs)
    # fleet: windows through a 1-step writer (window obs == step obs, in
    # order), folded per frame like IngestServer._write_frames
    spool = _Spool(512)
    w = NStepWriter(spool, 1, GAMMA)
    for obs, a, r, nxt, last in steps:
        w.add(obs, a, r, nxt, terminated=False, truncated=last)
    ingest = RunningObsNorm(OBS)
    while True:
        frame = spool.take_frame(3)  # several frames: the fold is per frame
        if frame is None:
            break
        (_g, _s, relabeled), cols = frame
        if not relabeled:
            ingest.update(cols["obs"])
    a, b = local.state_dict(), ingest.state_dict()
    assert a["count"] == b["count"]
    np.testing.assert_allclose(a["mean"], b["mean"], rtol=0, atol=1e-12)
    np.testing.assert_allclose(a["m2"], b["m2"], rtol=0, atol=1e-9)
    # relabeled windows: excluded
    spool.relabeled = True
    spool.add(np.full(OBS, 100.0), np.zeros(ACT), 0.0, np.zeros(OBS), 0.0)
    (_g, _s, relabeled), cols = spool.take_frame(8)
    assert relabeled
    before = ingest.state_dict()
    if not relabeled:  # pragma: no cover - the guard the ingest applies
        ingest.update(cols["obs"])
    assert ingest.state_dict() == before


def test_her_actor_side_vs_learner_oracle_byte_parity():
    """THE parity oracle: one episode through (a) the learner-side
    HindsightWriter writing straight into a buffer and (b) the
    actor-side factory+spool+wire path, with the same seeded relabel
    rng — the two buffers must be byte-identical, including the
    original→relabel insertion order."""
    rng = np.random.default_rng(5)
    length = 9
    eps = []
    pos = rng.random(2).astype(np.float32)
    goal = rng.random(2).astype(np.float32)
    for t in range(length):
        a = (rng.random(ACT) * 2 - 1).astype(np.float32)
        nxt_pos = np.clip(pos + 0.2 * a, 0, 1).astype(np.float32)
        r = -float(np.linalg.norm(nxt_pos - goal) >= 0.1)
        eps.append(dict(
            observation=pos, achieved_goal=pos, desired_goal=goal,
            action=a, reward=r, next_observation=nxt_pos,
            next_achieved_goal=nxt_pos, terminated=False,
        ))
        pos = nxt_pos

    def reward_fn(ag, dg):
        return -float(np.linalg.norm(np.asarray(ag) - np.asarray(dg)) >= 0.1)

    obs_dim = 4  # flatten(observation, goal)
    learner = ReplayBuffer(512, obs_dim, ACT)
    hw = HindsightWriter(
        writer_factory=lambda: NStepWriter(learner, N_STEP, GAMMA),
        compute_reward=reward_fn, k_future=3,
        rng=np.random.default_rng(77),
    )
    for s in eps:
        hw.add(**s)
    hw.end_episode(truncated=True)

    spool = _Spool(4096)
    factory = _HerWriterFactory(spool, N_STEP, GAMMA)
    hw2 = HindsightWriter(
        writer_factory=factory, compute_reward=reward_fn, k_future=3,
        rng=np.random.default_rng(77),
    )
    for s in eps:
        hw2.add(**s)
    factory.calls = 0
    hw2.end_episode(truncated=True)
    # original windows tagged original, relabels relabeled
    tags = [row[0] for row in spool.rows]
    assert tags[0] == (0, 0, False) and tags[-1][2] is True
    fleet = ReplayBuffer(512, obs_dim, ACT)

    while True:
        frame = spool.take_frame(64)
        if frame is None:
            break
        (gen, sg, rel), cols = frame
        payload = wire.encode_windows2(
            gen, sg, "f32", rel, cols["obs"], cols["action"],
            cols["reward"], cols["next_obs"], cols["discount"],
        )
        _g, _s, _m, _rel, out = wire.decode_windows2(payload, obs_dim, ACT)
        fleet.add_batch(Transition(
            out["obs"], out["action"], out["reward"],
            out["next_obs"], out["discount"],
        ))
    _assert_buffers_identical(learner, fleet)
