"""Parallel host actor pool + async actor/learner decoupling.

Covers the TPU-native replacement for the reference's N forked workers
(``main.py:399-403``): process-isolated host envs behind a batched step
interface, and the background-collector mode where the learner and actors
run concurrently against published params.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from d4pg_tpu.config import TrainConfig, apply_env_preset
from d4pg_tpu.runtime.actor_pool import HostActorPool

gym = pytest.importorskip("gymnasium")

ENV = "Pendulum-v1"


def _random_actions(rng, n, dim=1):
    return rng.uniform(-1, 1, (n, dim)).astype(np.float32)


class TestHostActorPool:
    @pytest.mark.slow
    def test_step_shapes_and_autoreset(self):
        pool = HostActorPool(ENV, 3, max_episode_steps=10, seed=0)
        try:
            obs = pool.reset_all(seed=0)
            assert obs.shape == (3, 3) and obs.dtype == np.float32
            rng = np.random.default_rng(0)
            for t in range(10):
                obs2, r, term, trunc, pol, succ, succ_rep = pool.step(_random_actions(rng, 3))
            # all three hit the TimeLimit on step 10 and auto-reset
            assert trunc.all() and not term.any()
            # the policy obs is the fresh post-reset state, not the terminal one
            assert not np.allclose(pol, obs2)
            assert obs2.shape == pol.shape == (3, 3)
            assert r.shape == (3,) and succ.shape == (3,)
            # Pendulum reports no is_success -> tri-state collapses to unreported
            assert not succ_rep.any() and not succ.any()
        finally:
            pool.close()

    @pytest.mark.slow
    def test_dmc_env_in_pool_workers(self):
        """dm_control ids must construct inside pool workers (the worker
        routes dmc:/dmc_pixels: through the dmc adapter; a bare GymAdapter
        crashed the child — the round-2 'tested interface, never trained'
        gap)."""
        pytest.importorskip("dm_control")
        pool = HostActorPool("dmc:cartpole:swingup", 2, max_episode_steps=20, seed=0)
        try:
            obs = pool.reset_all(seed=0)
            assert obs.shape == (2, 5) and obs.dtype == np.float32
            rng = np.random.default_rng(0)
            obs2, r, term, trunc, pol, succ, succ_rep = pool.step(
                _random_actions(rng, 2)
            )
            assert obs2.shape == (2, 5) and np.all(np.isfinite(r))
        finally:
            pool.close()

    @pytest.mark.slow
    def test_seeding_disjoint_and_reproducible(self):
        a = HostActorPool(ENV, 2, max_episode_steps=10, seed=7)
        b = HostActorPool(ENV, 2, max_episode_steps=10, seed=7)
        c = HostActorPool(ENV, 2, max_episode_steps=10, seed=8)
        try:
            oa, ob, oc = a.reset_all(), b.reset_all(), c.reset_all()
            np.testing.assert_allclose(oa, ob)  # same seed → same episodes
            assert not np.allclose(oa, oc)  # different seed → different
            assert not np.allclose(oa[0], oa[1])  # actors are disjoint streams
        finally:
            a.close()
            b.close()
            c.close()

    def test_transition_consistency(self):
        """next_obs must be the true successor: replaying the same action
        sequence in a single adapter gives identical transitions."""
        from d4pg_tpu.envs.gym_adapter import GymAdapter

        pool = HostActorPool(ENV, 1, max_episode_steps=50, seed=3)
        solo = GymAdapter(ENV, 50)
        try:
            obs_p = pool.reset_all(seed=100)[0]
            obs_s = solo.reset(seed=100)
            np.testing.assert_allclose(obs_p, obs_s, rtol=1e-6)
            rng = np.random.default_rng(1)
            for _ in range(5):
                a = _random_actions(rng, 1)
                obs2_p, r_p, *_ = pool.step(a)
                obs2_s, r_s, *_ = solo.step(a[0])
                np.testing.assert_allclose(obs2_p[0], obs2_s, rtol=1e-5)
                assert abs(r_p[0] - r_s) < 1e-4
        finally:
            pool.close()
            solo.close()


def _cfg(**kw):
    base = dict(
        env=ENV,
        num_envs=2,
        total_steps=3,
        warmup_steps=30,
        batch_size=16,
        replay_capacity=2_000,
        eval_interval=3,
        eval_episodes=1,
        max_episode_steps=20,
        checkpoint_interval=100_000,
    )
    base.update(kw)
    return apply_env_preset(TrainConfig(**base))


class TestTrainerPool:
    @pytest.mark.slow
    def test_pool_mode_trains(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_cfg(log_dir=str(tmp_path / "run")))
        try:
            assert t.has_pool and t.pool.num_actors == 2
            out = t.train()
            assert t.env_steps >= 30
            assert np.isfinite(out["critic_loss"])
            assert "eval_return_mean" in out
        finally:
            t.close()

    @pytest.mark.slow
    def test_pool_mode_cpu_actor_device(self, tmp_path):
        """--actor-device cpu: collection/eval forwards jit on the CPU
        backend against numpy params (the remote-TPU layout, where every
        default-device act is a ~100 ms link round-trip). On the CPU-only
        test platform the math is identical — this pins the wiring: the
        cpu-committed key stream, numpy param publication, and that training
        still converges through the alternate act path."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(_cfg(log_dir=str(tmp_path / "run"), actor_device="cpu"))
        try:
            assert t._act_backend == "cpu"
            out = t.train()
            assert np.isfinite(out["critic_loss"])
            # acting params are committed to the CPU device
            import jax

            cpu = jax.devices("cpu")[0]
            assert all(
                x.devices() == {cpu} for x in jax.tree.leaves(t._acting_params())
            )
        finally:
            t.close()

    @pytest.mark.slow
    def test_async_cpu_actor_publishes_numpy(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _cfg(
                log_dir=str(tmp_path / "run"),
                async_collect=True,
                publish_interval=2,
                total_steps=4,
                actor_device="cpu",
            )
        )
        try:
            out = t.train()
            assert t._collector is None
            assert np.isfinite(out["critic_loss"])
            import jax

            cpu = jax.devices("cpu")[0]
            assert all(
                x.devices() == {cpu} for x in jax.tree.leaves(t._actor_pub)
            )
        finally:
            t.close()

    @pytest.mark.slow
    def test_async_priority_writeback(self, tmp_path):
        """Background PER flusher: training proceeds without the learner
        blocking on priority fetches; the thread drains and joins cleanly,
        and the sampled indices' priorities actually moved off the
        max-priority inserts."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _cfg(
                log_dir=str(tmp_path / "run"),
                async_priority_writeback=True,
                steps_per_dispatch=2,
                total_steps=8,
            )
        )
        try:
            out = t.train()
            assert t._wb_thread is None and t._wb_error is None
            assert np.isfinite(out["critic_loss"])
            # after the final flush, some leaf priorities differ from the
            # uniform max-priority every insert starts at
            pri = t.buffer._sum.get(np.arange(min(len(t.buffer), 64)))
            assert len(np.unique(np.round(pri, 6))) > 1
        finally:
            t.close()

    @pytest.mark.slow
    def test_async_mode_trains_and_joins(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _cfg(
                log_dir=str(tmp_path / "run"),
                async_collect=True,
                publish_interval=2,
                total_steps=4,
            )
        )
        try:
            out = t.train()
            assert t._collector is None  # joined cleanly
            # pacing: learner never outran warmup + ratio·steps
            assert t.env_steps >= 30 + 1.0 * 4
            assert np.isfinite(out["critic_loss"])
            assert t._actor_pub is not None
        finally:
            t.close()

    @pytest.mark.slow
    def test_async_single_env_gets_pool(self, tmp_path):
        """--async-collect with num_envs=1 must still route through the pool
        (a dedicated worker process), not the in-thread single-env path."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _cfg(
                log_dir=str(tmp_path / "run"),
                num_envs=1,
                async_collect=True,
                total_steps=2,
            )
        )
        try:
            assert t.has_pool and t.pool.num_actors == 1
            out = t.train()
            assert np.isfinite(out["critic_loss"])
        finally:
            t.close()

    @pytest.mark.slow
    def test_async_train_twice(self, tmp_path):
        """Chunked training: a second train() must restart the collector
        (the stop event is cleared, not latched)."""
        from d4pg_tpu.runtime.trainer import Trainer

        t = Trainer(
            _cfg(log_dir=str(tmp_path / "run"), async_collect=True, total_steps=2)
        )
        try:
            t.train()
            steps_after_first = t.env_steps
            t.train(total_steps=2)
            assert t.grad_steps == 4
            assert t.env_steps >= steps_after_first
            assert t._collector is None
        finally:
            t.close()

    @pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
    def test_async_requires_pool(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        cfg = apply_env_preset(
            TrainConfig(
                env="pendulum",
                num_envs=2,
                async_collect=True,
                total_steps=2,
                warmup_steps=10,
                batch_size=8,
                replay_capacity=1_000,
                log_dir=str(tmp_path / "run"),
            )
        )
        t = Trainer(cfg)
        try:
            with pytest.raises(ValueError, match="actor pool"):
                t.train()
        finally:
            t.close()


def test_orphaned_workers_exit_when_parent_dies():
    """Satellite bugfix (ISSUE-5): pool workers used to block forever in
    conn.recv() when the parent died, stranding N gymnasium children.
    Now the worker polls with a timeout and exits once the parent is
    gone. Simulated with a subprocess parent that os._exit()s without
    closing — the hard-death path where no cleanup runs."""
    import time

    probe = (
        "import os\n"
        "from d4pg_tpu.runtime.actor_pool import HostActorPool\n"
        "pool = HostActorPool('Pendulum-v1', 2, max_episode_steps=20,\n"
        "                     seed=0, start_method='fork')\n"
        "pool.reset_all(seed=0)\n"
        "print(' '.join(str(p.pid) for p in pool._procs), flush=True)\n"
        "os._exit(0)  # die without close(): workers must self-terminate\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True,
        timeout=180,
    )
    assert out.returncode == 0, out.stderr
    pids = [int(x) for x in out.stdout.split()]
    assert len(pids) == 2

    def alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True

    deadline = time.monotonic() + 30  # worker poll period is 1 s
    while time.monotonic() < deadline and any(alive(p) for p in pids):
        time.sleep(0.5)
    leaked = [p for p in pids if alive(p)]
    for p in leaked:  # clean up before failing the assertion
        os.kill(p, 9)
    assert not leaked, f"orphaned pool workers leaked: {leaked}"


def test_gym_adapter_imports_without_jax():
    """Pool worker processes must stay lean: importing the adapter module
    alone (what ``actor_pool._worker`` does) must not pull in the JAX env
    stack, and must not load jax itself unless the host environment preloads
    it at interpreter startup (some TPU sites do, via sitecustomize)."""
    probe = (
        "import sys\n"
        "preloaded = 'jax' in sys.modules\n"
        "import d4pg_tpu.envs.gym_adapter\n"
        "jax_envs = [m for m in sys.modules if m.startswith('d4pg_tpu.envs.') "
        "and not m.endswith('gym_adapter')]\n"
        "print(preloaded or 'jax' not in sys.modules, jax_envs)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True, timeout=120
    )
    assert out.returncode == 0, out.stderr
    flag, envs = out.stdout.strip().split(" ", 1)
    assert flag == "True", "gym_adapter import loaded jax"
    assert envs == "[]", f"gym_adapter import loaded JAX env modules: {envs}"


@pytest.mark.slow
def test_pool_eval_parallel(tmp_path):
    """Host eval routes through a parallel eval pool when eval_episodes > 1:
    one batched act per env step across all episodes."""
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(
        _cfg(log_dir=str(tmp_path / "run"), eval_episodes=3, total_steps=2)
    )
    try:
        out = t.train()
        assert t._eval_pool is not None and t._eval_pool.num_actors == 3
        assert np.isfinite(out["eval_return_mean"])
        assert out["eval_return_std"] >= 0.0
    finally:
        t.close()


GOAL_ENV = "toy_goal_env:ToyGoal-v0"


class TestHERPool:
    @pytest.mark.slow
    def test_step_goal_views(self):
        """step_goal returns consistent pre/post goal views: prev.next == next
        under the flat obs the policy sees."""
        pool = HostActorPool(GOAL_ENV, 2, max_episode_steps=25, seed=0)
        try:
            obs = pool.reset_all(seed=0)
            assert obs.shape == (2, 4)  # concat(observation, desired_goal)
            a = np.full((2, 2), 0.5, np.float32)
            obs2, r, term, trunc, pol, s, s_rep, g0, g1 = pool.step_goal(a)
            assert s_rep.all()  # the env reports is_success
            for i in range(2):
                o0, ag0, dg0 = g0[i]
                o1, ag1, dg1 = g1[i]
                # achieved goal == observation in this env; goal fixed
                np.testing.assert_allclose(o0, ag0)
                np.testing.assert_allclose(dg0, dg1)
                # flat next_obs is concat(next observation, goal)
                np.testing.assert_allclose(obs2[i], np.concatenate([o1, dg1]))
        finally:
            pool.close()

    @pytest.mark.slow
    def test_her_pool_trains_and_relabels(self, tmp_path):
        """HER through the pool: original + relabeled transitions land in
        replay, training runs, and the env actually solves-ish under noise
        (toy env is trivially reachable)."""
        from d4pg_tpu.runtime.trainer import Trainer

        cfg = apply_env_preset(
            TrainConfig(
                env=GOAL_ENV,
                num_envs=2,
                her=True,
                her_k=2,
                n_step=1,
                total_steps=4,
                warmup_steps=60,
                batch_size=16,
                replay_capacity=4_000,
                eval_interval=4,
                eval_episodes=2,
                checkpoint_interval=10**6,
                log_dir=str(tmp_path / "run"),
            )
        )
        t = Trainer(cfg)
        try:
            assert t.has_pool and len(t.her_writers) == 2
            out = t.train()
            # HER adds relabeled copies: stored transitions exceed env steps
            assert len(t.buffer) > 60
            assert np.isfinite(out["critic_loss"])
            assert 0.0 <= out["success_rate"] <= 1.0
        finally:
            t.close()

    @pytest.mark.slow
    def test_her_pool_async(self, tmp_path):
        from d4pg_tpu.runtime.trainer import Trainer

        cfg = apply_env_preset(
            TrainConfig(
                env=GOAL_ENV,
                num_envs=2,
                her=True,
                her_k=1,
                n_step=1,
                total_steps=4,
                warmup_steps=60,
                batch_size=16,
                replay_capacity=4_000,
                eval_interval=4,
                eval_episodes=1,
                checkpoint_interval=10**6,
                async_collect=True,
                log_dir=str(tmp_path / "run"),
            )
        )
        t = Trainer(cfg)
        try:
            out = t.train()
            assert t._collector is None
            assert len(t.buffer) > 60
            assert np.isfinite(out["critic_loss"])
        finally:
            t.close()

    @pytest.mark.slow
    def test_her_pool_warmup_fills_buffer(self, tmp_path):
        """Warmup must not exit before the buffer can serve a batch: HER
        only flushes at episode ends, so step-counted warmup alone could
        leave replay empty (division-by-zero in PER sampling)."""
        from d4pg_tpu.runtime.trainer import Trainer

        cfg = apply_env_preset(
            TrainConfig(
                env=GOAL_ENV,
                num_envs=2,
                her=True,
                her_k=1,
                n_step=1,
                total_steps=2,
                warmup_steps=4,  # far less than one 25-step episode
                batch_size=16,
                replay_capacity=2_000,
                eval_interval=100,
                eval_episodes=1,
                checkpoint_interval=10**6,
                log_dir=str(tmp_path / "run"),
            )
        )
        t = Trainer(cfg)
        try:
            out = t.train()
            assert len(t.buffer) >= 16
            assert np.isfinite(out["critic_loss"])
        finally:
            t.close()


@pytest.mark.slow
def test_async_resume_still_collects(tmp_path):
    """Regression: async pacing must compare per-process FRESH env steps
    against the learner's ratio, not the checkpoint-restored global counter
    — the global comparison made resumed legs collect nothing and train
    forever off the frozen restored buffer."""
    import dataclasses

    from d4pg_tpu.runtime.trainer import Trainer

    base = _cfg(
        log_dir=str(tmp_path / "run"),
        async_collect=True,
        total_steps=6,
        snapshot_replay=True,
        checkpoint_interval=6,
    )
    t = Trainer(base)
    try:
        t.train()
    finally:
        t.close()

    cfg2 = dataclasses.replace(base, resume=True, total_steps=8)
    t2 = Trainer(cfg2)
    try:
        restored_env_steps = t2.env_steps
        assert restored_env_steps > 0  # meta restored
        t2.train()
        # the resumed leg collected fresh experience (ratio-paced) instead
        # of sleeping on the restored global counter
        assert t2.env_steps > restored_env_steps
    finally:
        t2.close()
