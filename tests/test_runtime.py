"""Runtime tests: trainer modes, checkpoint/resume, metrics, evaluator."""

import json
import os

import jax
import numpy as np
import pytest

from d4pg_tpu.agent import D4PGConfig, create_train_state, jit_train_step
from d4pg_tpu.runtime import CheckpointManager, MetricsLogger, evaluate
from d4pg_tpu.runtime.trainer import Trainer
from train import build_parser, config_from_args


def _tiny_args(tmp, extra=()):
    return build_parser().parse_args(
        [
            "--env", "pendulum",
            "--total-steps", "6",
            "--warmup", "130",
            "--eval-interval", "6",
            "--checkpoint-interval", "6",
            "--num-envs", "2",
            "--bsize", "16",
            "--log-dir", str(tmp),
            *extra,
        ]
    )


def test_trainer_sync_mode_end_to_end(tmp_path):
    t = Trainer(config_from_args(_tiny_args(tmp_path / "a")))
    out = t.train()
    t.close()
    assert "critic_loss" in out and np.isfinite(out["critic_loss"])
    assert len(t.buffer) > 0
    # metrics jsonl written
    lines = open(tmp_path / "a" / "metrics.jsonl").read().splitlines()
    assert len(lines) >= 1
    rec = json.loads(lines[-1])
    assert rec["step"] == 6
    assert "grad_steps_per_sec" in rec


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_trainer_keep_best(tmp_path):
    """Every eval crossing that beats the best-so-far persists the SCORED
    actor params (best_actor.npz) + best_eval.json, and load_best_actor
    restores them into a template pytree exactly."""
    from d4pg_tpu.runtime.trainer import load_best_actor

    t = Trainer(config_from_args(_tiny_args(tmp_path / "kb")))
    t.train()
    best_params = jax.device_get(t.state.actor_params)
    t.close()
    log = tmp_path / "kb"
    meta = json.loads((log / "best_eval.json").read_text())
    assert meta["step"] == 6 and np.isfinite(meta["eval_return_mean"])
    restored = load_best_actor(str(log), best_params)
    # single eval crossing at the final step → best == final params
    jax.tree.map(np.testing.assert_allclose, restored, best_params)
    # best_eval_return rides the metrics rows
    rec = json.loads(open(log / "metrics.jsonl").read().splitlines()[-1])
    assert rec["best_eval_return"] == meta["eval_return_mean"]


@pytest.mark.slow
def test_trainer_uniform_replay_mode(tmp_path):
    t = Trainer(config_from_args(_tiny_args(tmp_path / "u", ["--no-p-replay"])))
    out = t.train()
    t.close()
    assert np.isfinite(out["critic_loss"])


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_trainer_bf16_transfer_staging(tmp_path):
    """--transfer-dtype bfloat16 (the wide-obs link-bandwidth rung,
    docs/REMOTE_TPU.md): obs go over the wire as bf16 and are restored to
    f32 in-jit — training must stay finite and the staged arrays must
    actually be 2 bytes/element."""
    import ml_dtypes

    t = Trainer(
        config_from_args(
            _tiny_args(tmp_path / "bf", ["--env", "Pendulum-v1",
                                         "--transfer-dtype", "bfloat16"])
        )
    )
    staged = t._stage("obs", np.ones((4, 3), np.float32))
    assert staged.dtype == ml_dtypes.bfloat16
    assert t._stage("reward", np.ones(4, np.float32)).dtype == np.float32
    out = t.train()
    t.close()
    assert np.isfinite(out["critic_loss"])


@pytest.mark.slow
def test_bf16_staging_composes_with_dp(tmp_path):
    """--transfer-dtype bfloat16 --dp 8 (the BASELINE scale-out shape:
    link-starved host + multi-chip DP): rows cross the wire as bf16, the
    restore-to-f32 runs before the shard_map'd step, training stays
    finite. Both the K=1 and the fused K>1 dispatch paths."""
    import ml_dtypes

    for sub, extra in (
        ("dp1", []),
        ("dpk", ["--steps-per-dispatch", "2"]),
    ):
        t = Trainer(
            config_from_args(
                _tiny_args(
                    tmp_path / sub,
                    ["--env", "Pendulum-v1", "--transfer-dtype", "bfloat16",
                     "--dp", "8", "--bsize", "16", *extra],
                )
            )
        )
        assert t._stage("obs", np.ones((4, 3), np.float32)).dtype == ml_dtypes.bfloat16
        out = t.train()
        t.close()
        assert np.isfinite(out["critic_loss"])


@pytest.mark.slow
def test_hogwild_dp_trains_from_cli(tmp_path):
    """--dp-hogwild --dp 8 --steps-per-dispatch 2 end to end through the
    Trainer; and the two flag-validation errors."""
    t = Trainer(
        config_from_args(
            _tiny_args(
                tmp_path / "hw",
                ["--env", "Pendulum-v1", "--dp", "8", "--dp-hogwild",
                 "--steps-per-dispatch", "2", "--bsize", "16"],
            )
        )
    )
    out = t.train()
    t.close()
    assert np.isfinite(out["critic_loss"])
    with pytest.raises(ValueError, match="steps-per-dispatch"):
        Trainer(config_from_args(_tiny_args(
            tmp_path / "hw1", ["--env", "Pendulum-v1", "--dp", "8",
                               "--dp-hogwild", "--bsize", "16"])))
    with pytest.raises(ValueError, match="requires --dp"):
        Trainer(config_from_args(_tiny_args(
            tmp_path / "hw2", ["--env", "Pendulum-v1", "--dp-hogwild"])))


def test_uint8_wire_transfer_staging(tmp_path):
    """--transfer-dtype uint8 (pixel link rung): sampled rows leave the
    quantized replay as raw bytes; flat envs are rejected."""
    from d4pg_tpu.replay import ReplayBuffer

    buf = ReplayBuffer(8, 4, 1, obs_dtype=np.uint8, obs_scale=255.0,
                       decode_on_sample=False)
    buf.add(np.full(4, 0.5), np.zeros(1), 0.0, np.full(4, 0.25), 0.99)
    batch = buf.gather(np.zeros(1, np.int64))
    assert batch["obs"].dtype == np.uint8 and batch["obs"][0, 0] == 128
    # flat envs must reject the uint8 wire format with a clear error
    with pytest.raises(ValueError, match="pixel env"):
        Trainer(
            config_from_args(
                _tiny_args(tmp_path / "u8", ["--env", "Pendulum-v1",
                                             "--transfer-dtype", "uint8"])
            )
        )


@pytest.mark.slow
def test_uint8_wire_trains_end_to_end(tmp_path):
    """The in-jit dequantize (÷255) actually runs in a training step: a
    pixel env with the uint8 wire format must train to finite losses (a
    dropped ÷255 would feed [0,255] batches to an actor acting on [0,1]
    env obs — a silent 255× train/act scale mismatch)."""
    args = build_parser().parse_args(
        [
            "--env", "pixel_pendulum", "--transfer-dtype", "uint8",
            "--total-steps", "4", "--warmup", "40", "--num-envs", "2",
            "--eval-interval", "4", "--checkpoint-interval", "4",
            "--bsize", "8", "--rmsize", "4096",
            "--log-dir", str(tmp_path / "pix8"),
        ]
    )
    t = Trainer(config_from_args(args))
    assert not t.buffer._decode_on_sample  # raw bytes leave the buffer
    out = t.train()
    t.close()
    assert np.isfinite(out["critic_loss"])


@pytest.mark.slow
def test_trainer_her_mode(tmp_path):
    args = build_parser().parse_args(
        [
            "--env", "pointmass_goal", "--her", "--n-step", "1",
            "--total-steps", "4", "--warmup", "60",
            "--eval-interval", "4", "--checkpoint-interval", "4",
            "--bsize", "16", "--log-dir", str(tmp_path / "h"),
        ]
    )
    t = Trainer(config_from_args(args))
    out = t.train()
    t.close()
    assert "success_rate" in out


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_concurrent_eval_does_not_stall_learner(tmp_path):
    """VERDICT round-1 weak #2: host-env eval must run OFF the learner
    thread. With an artificially slow eval (0.8 s), the learner must make
    grad steps while the eval is in flight, and the final eval row must
    still land in metrics.jsonl before train() returns."""
    import time

    pytest.importorskip("gymnasium")
    args = build_parser().parse_args(
        [
            "--env", "Pendulum-v1", "--num-envs", "1",
            "--total-steps", "40", "--warmup", "40",
            "--eval-interval", "10", "--eval-episodes", "1",
            "--max-steps", "10", "--bsize", "16",
            "--rmsize", "2000", "--checkpoint-interval", "100000",
            "--log-dir", str(tmp_path / "ce"),
        ]
    )
    cfg = config_from_args(args)
    assert cfg.concurrent_eval  # the default
    t = Trainer(cfg)
    progress = []  # (grad_steps at eval entry, grad_steps at eval exit)
    real_eval = t._host_eval

    def slow_eval(eval_params=None):
        entry = t.grad_steps
        time.sleep(0.8)
        ev = real_eval(eval_params=eval_params)
        progress.append((entry, t.grad_steps))
        return ev

    t._host_eval = slow_eval
    try:
        out = t.train()
    finally:
        t.close()
    # learner advanced while at least one eval slept
    assert any(exit_ > entry for entry, exit_ in progress), progress
    assert "eval_return_mean" in out and np.isfinite(out["eval_return_mean"])
    rows = [
        json.loads(l)
        for l in open(tmp_path / "ce" / "metrics.jsonl").read().splitlines()
    ]
    eval_rows = [r for r in rows if "eval_return_mean" in r]
    # the FINAL crossing (step 40) is always evaluated (drained before return)
    assert eval_rows and eval_rows[-1]["step"] == 40


@pytest.mark.slow
def test_concurrent_eval_coalesces_to_latest(tmp_path):
    """Back-to-back crossings while an eval is in flight: the newer request
    replaces the waiting one (latest params win), and every processed eval
    is logged at the step it was requested."""
    import time

    pytest.importorskip("gymnasium")
    args = build_parser().parse_args(
        [
            "--env", "Pendulum-v1", "--num-envs", "1",
            "--total-steps", "30", "--warmup", "30",
            "--eval-interval", "5", "--eval-episodes", "1",
            "--max-steps", "5", "--bsize", "8",
            "--rmsize", "2000", "--checkpoint-interval", "100000",
            "--log-dir", str(tmp_path / "cl"),
        ]
    )
    t = Trainer(config_from_args(args))
    calls = []
    real_eval = t._host_eval

    def slow_eval(eval_params=None):
        calls.append(t.grad_steps)
        time.sleep(0.5)
        return real_eval(eval_params=eval_params)

    t._host_eval = slow_eval
    try:
        t.train()
    finally:
        t.close()
    rows = [
        json.loads(l)
        for l in open(tmp_path / "cl" / "metrics.jsonl").read().splitlines()
    ]
    eval_steps = [r["step"] for r in rows if "eval_return_mean" in r]
    # fewer evals than crossings (coalesced), logged steps strictly increase,
    # and the final crossing is present
    assert len(eval_steps) <= 6
    assert eval_steps == sorted(set(eval_steps))
    assert eval_steps[-1] == 30


def test_checkpoint_roundtrip(tmp_path):
    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16))
    state = create_train_state(config, jax.random.PRNGKey(0))
    step = jit_train_step(config, donate=False)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(8, 3)).astype(np.float32),
        "action": rng.uniform(-1, 1, size=(8, 1)).astype(np.float32),
        "reward": rng.uniform(-1, 0, size=8).astype(np.float32),
        "next_obs": rng.normal(size=(8, 3)).astype(np.float32),
        "discount": np.full(8, 0.99, np.float32),
        "weights": np.ones(8, np.float32),
    }
    state, _, _ = step(state, batch)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, state)
    mgr.wait()
    template = create_train_state(config, jax.random.PRNGKey(42))
    restored = mgr.restore(template)
    assert int(restored.step) == 1
    np.testing.assert_allclose(
        np.asarray(restored.critic_params["params"]["out"]["kernel"]),
        np.asarray(state.critic_params["params"]["out"]["kernel"]),
    )
    # optimizer moments survive too (reference saves none, SURVEY §5)
    flat_a = jax.tree_util.tree_leaves(restored.critic_opt_state)
    flat_b = jax.tree_util.tree_leaves(state.critic_opt_state)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    mgr.close()


@pytest.mark.slow
def test_trainer_resume(tmp_path):
    args = _tiny_args(tmp_path / "r")
    t = Trainer(config_from_args(args))
    t.train()
    t.close()
    args2 = _tiny_args(tmp_path / "r", ["--resume"])
    t2 = Trainer(config_from_args(args2))
    assert int(jax.device_get(t2.state.step)) == 6
    t2.close()


def test_metrics_logger(tmp_path):
    m = MetricsLogger(str(tmp_path / "m"), use_tensorboard=False)
    m.log(1, {"a": 1.0})
    m.log(2, {"a": 2.0, "b": -1.0})
    m.close()
    lines = [json.loads(l) for l in open(tmp_path / "m" / "metrics.jsonl")]
    assert lines[0]["a"] == 1.0 and lines[1]["b"] == -1.0


def test_evaluator_on_pendulum():
    from d4pg_tpu.envs import Pendulum

    config = D4PGConfig(obs_dim=3, action_dim=1, hidden_sizes=(16, 16))
    state = create_train_state(config, jax.random.PRNGKey(0))
    out = evaluate(config, Pendulum(), state.actor_params, jax.random.PRNGKey(1), 3)
    assert out["eval_return_mean"] < 0  # pendulum returns are negative
    # Pendulum never terminates and is not a goal env: success_rate must be
    # ABSENT, not a termination-derived lie (VERDICT round-2 weak #1).
    assert "success_rate" not in out


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_success_rate_only_on_goal_envs():
    """Goal envs (reports_success) get success_rate; locomotion envs, where
    termination means falling over, must not report one."""
    from d4pg_tpu.envs import PointMassGoal
    from d4pg_tpu.envs.locomotion import Hopper

    goal_env = PointMassGoal()
    config = D4PGConfig(
        obs_dim=goal_env.flat_obs_dim, action_dim=2, hidden_sizes=(16, 16)
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    out = evaluate(config, goal_env, state.actor_params, jax.random.PRNGKey(1), 2)
    assert "success_rate" in out and 0.0 <= out["success_rate"] <= 1.0

    hop = Hopper()
    config = D4PGConfig(
        obs_dim=hop.observation_dim, action_dim=hop.action_dim,
        hidden_sizes=(16, 16),
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    out = evaluate(
        config, hop, state.actor_params, jax.random.PRNGKey(1), 2, max_steps=8
    )
    assert "success_rate" not in out


@pytest.mark.slow
def test_trainer_fused_dispatch(tmp_path):
    """steps_per_dispatch=K runs K grad steps per device call and still
    writes back every batch's PER priorities."""
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = apply_env_preset(
        TrainConfig(
            env="pendulum",
            num_envs=4,
            total_steps=12,
            steps_per_dispatch=4,
            warmup_steps=200,
            batch_size=32,
            replay_capacity=2_000,
            eval_interval=8,
            eval_episodes=1,
            checkpoint_interval=10**6,
            log_dir=str(tmp_path / "run"),
        )
    )
    t = Trainer(cfg)
    try:
        out = t.train()
        assert t.grad_steps == 12
        assert np.isfinite(out["critic_loss"])
        # priorities were written back: the PER max-priority moved off its
        # initial value (projection losses are never exactly 1.0)
        assert t.buffer._max_priority != 1.0
    finally:
        t.close()


@pytest.mark.slow
def test_snapshot_replay_resume_skips_warmup(tmp_path):
    """--snapshot-replay: a resumed trainer restores the buffer and does not
    recollect warmup (the snapshot already paid it)."""
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer

    kw = dict(
        env="pendulum",
        num_envs=4,
        total_steps=2,
        warmup_steps=150,
        batch_size=32,
        replay_capacity=2_000,
        eval_interval=100,
        eval_episodes=1,
        checkpoint_interval=2,
        snapshot_replay=True,
        log_dir=str(tmp_path / "run"),
    )
    t = Trainer(apply_env_preset(TrainConfig(**kw)))
    t.train()
    saved = len(t.buffer)
    t.close()
    assert saved >= 150

    t2 = Trainer(apply_env_preset(TrainConfig(**kw, resume=True)))
    try:
        assert t2._replay_restored and len(t2.buffer) == saved
        start = t2.env_steps  # restored from trainer meta, not re-collected
        t2.train()
        # warmup skipped: only incidental collection happened
        assert t2.env_steps - start < 150
        assert t2.grad_steps == 4
    finally:
        t2.close()


@pytest.mark.slow
def test_resume_restores_env_steps_and_noise_schedule(tmp_path):
    """env_steps (which drives noise decay) survives resume via the trainer
    meta file; exploration does not restart at full scale."""
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.trainer import Trainer
    import dataclasses

    kw = dict(
        env="pendulum",
        num_envs=4,
        total_steps=2,
        warmup_steps=100,
        batch_size=32,
        replay_capacity=2_000,
        eval_interval=100,
        eval_episodes=1,
        checkpoint_interval=2,
        log_dir=str(tmp_path / "run"),
    )
    cfg = apply_env_preset(TrainConfig(**kw))
    cfg = dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, noise_decay_steps=120)
    )
    t = Trainer(cfg)
    t.train()
    steps1 = t.env_steps
    scale1 = t._noise_scale()
    t.close()
    assert steps1 >= 100 and scale1 < 1.0

    cfg2 = dataclasses.replace(cfg, resume=True)
    t2 = Trainer(cfg2)
    try:
        assert t2.env_steps == steps1
        assert t2._noise_scale() == pytest.approx(scale1)
        assert t2.ewma_return is not None
    finally:
        t2.close()


def test_interval_crossed():
    from d4pg_tpu.runtime.metrics import interval_crossed

    # K-step dispatches can jump over exact multiples; crossing still fires
    assert interval_crossed(0, 16, 10)
    assert interval_crossed(95, 105, 100)
    assert not interval_crossed(10, 19, 10)
    assert not interval_crossed(100, 100, 100)  # no advance, no fire
    assert interval_crossed(99, 100, 100)  # landing exactly on the multiple


def test_trainer_meta_roundtrip(tmp_path):
    from d4pg_tpu.runtime.checkpoint import (
        load_trainer_meta,
        save_trainer_meta,
        trainer_meta_path,
    )

    log_dir = str(tmp_path / "run")
    os.makedirs(os.path.join(log_dir, "checkpoints"))
    assert load_trainer_meta(log_dir) == {}  # missing file -> empty dict
    save_trainer_meta(log_dir, env_steps=12345, ewma_return=-42.5)
    meta = load_trainer_meta(log_dir)
    assert meta == {"env_steps": 12345, "ewma_return": -42.5}
    # atomic write: no .tmp left behind
    assert not os.path.exists(trainer_meta_path(log_dir) + ".tmp")


@pytest.mark.slow
def test_rss_watchdog_checkpoints_and_exits(tmp_path):
    """--max-rss-gb: a tiny limit trips at the first eval crossing; the
    trainer checkpoints and returns early instead of running to total."""
    import dataclasses

    cfg = config_from_args(_tiny_args(tmp_path / "w"))

    cfg = dataclasses.replace(
        cfg, max_rss_gb=0.001, total_steps=200, eval_interval=10,
        checkpoint_interval=1000,
    )
    t = Trainer(cfg)
    try:
        t.train()
        assert t.preempted  # callers key exit-75 off this
        assert t.grad_steps < 200  # preempted, not completed
        assert t.ckpt.latest_step() == t.grad_steps  # checkpointed at exit
        assert os.path.exists(
            os.path.join(cfg.log_dir, "checkpoints", "trainer_meta.json")
        )
    finally:
        t.close()
