"""Multi-host bring-up: 2-process localhost jax.distributed.

The reference's multi-"node" story is forked processes + shared memory
(``main.py:393-405``); ours is ``jax.distributed`` — every host runs the
same command, ``jax.devices()`` spans the cluster, and collectives ride the
mesh. No multi-host TPU exists here, so this exercises the REAL
``jax.distributed.initialize`` handshake with two local CPU processes
(coordinator on a localhost port), exactly what ``train.py --coordinator
--num-processes --process-id`` wires up.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

# Each child: 2 virtual CPU devices, so the global mesh is 2 procs × 2 = 4.
_CHILD = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, __REPO__)
    from d4pg_tpu.parallel import initialize_distributed, make_mesh

    info = initialize_distributed(
        coordinator_address=__COORD__,
        num_processes=2,
        process_id=int(sys.argv[1]),
    )
    assert info["process_count"] == 2, info
    assert info["local_device_count"] == 2, info
    assert info["global_device_count"] == 4, info
    mesh = make_mesh(dp=4)  # global mesh spans both processes' devices
    assert mesh.shape["dp"] == 4
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # One real cross-process collective: every process contributes its local
    # shard of a dp-sharded array; the jitted global sum must see all of it.
    arr = jax.make_array_from_callback(
        (4,),
        NamedSharding(mesh, P("dp")),
        lambda idx: jnp.arange(4.0)[idx],
    )
    total = jax.jit(
        jnp.sum, out_shardings=NamedSharding(mesh, P())
    )(arr)
    # fully-addressable replicated output: both processes can read it
    assert float(total) == 6.0, float(total)
    print(f"proc {info['process_index']} OK")
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_localhost_bringup(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "child.py"
    script.write_text(
        _CHILD.replace("__REPO__", repr(repo)).replace("__COORD__", repr(coord))
    )
    env = {
        k: v
        for k, v in os.environ.items()
        # children must not inherit this process's single-chip TPU client:
        # the tunneled-TPU plugin registers itself via PYTHONPATH site hooks
        # and AXON_*/TPU_* vars and would override JAX_PLATFORMS=cpu
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")
        and "AXON" not in k
        and "TPU" not in k
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"proc {rank} OK" in out
