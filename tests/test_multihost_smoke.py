"""Multi-host smoke wrapper: ``scripts/multihost_smoke.sh`` end to end —
2-process × 4-device mesh bring-up under ``--debug-guards``, the
``host_kill`` chaos site (SIGKILL one mesh process mid-training), the
survivor reap, and the full-mesh ``--resume`` from the last committed
coordinated checkpoint with bit-identical done-lines.

Wired into the test tree per the tier-1 clock-guard convention: every
leg spawns real train.py processes with a cold compile, so the whole
script is a slow-marked long leg — nothing from this smoke runs inside
the 60 s fast tier (the fast-tier multihost coverage is the in-process
half of ``tests/test_multihost.py``).
"""

import os
import subprocess
import time

import pytest

from conftest import clean_cpu_env

# Slow-tier ceiling for the whole script (two 2-process legs, each with
# a cold XLA compile on the 1-core CI box). A regression past it means a
# leg hung on a dead collective instead of being reaped.
SLOW_BUDGET_S = 540.0


@pytest.mark.slow
def test_multihost_smoke_script(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env()
    env["MULTIHOST_SMOKE_DIR"] = str(tmp_path / "run")
    t0 = time.monotonic()
    p = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "multihost_smoke.sh")],
        capture_output=True,
        text=True,
        timeout=SLOW_BUDGET_S + 60,
        env=env,
        cwd=repo,
    )
    elapsed = time.monotonic() - t0
    out = p.stdout + p.stderr
    assert p.returncode == 0, out[-4000:]
    assert "MULTIHOST_SMOKE_ASSERTS_OK" in p.stdout, out[-4000:]
    assert "MULTIHOST_SMOKE_OK" in p.stdout, out[-4000:]
    # the committed checkpoint the resume proved is a real on-disk artifact
    ckpt = str(tmp_path / "run" / "run" / "checkpoints")
    assert os.path.isdir(ckpt), out[-2000:]
    assert any(n.startswith("manifest_") for n in os.listdir(ckpt))
    assert elapsed < SLOW_BUDGET_S, (
        f"multihost smoke took {elapsed:.1f}s, past its stated "
        f"{SLOW_BUDGET_S:.0f}s slow-tier budget; a leg likely sat on a "
        "dead cross-process collective instead of being reaped"
    )
