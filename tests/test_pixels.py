"""Pixel path end-to-end: on-device renderer env, conv-encoded networks,
fused train step over flattened-pixel batches (BASELINE.json config 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from d4pg_tpu.agent import D4PGConfig, create_train_state, jit_train_step
from d4pg_tpu.envs import PixelPendulum, rollout
from d4pg_tpu.envs.pixel_pendulum import render_arm
from d4pg_tpu.models.critic import DistConfig


def test_render_arm_orientation():
    size = 32
    up = np.asarray(render_arm(jnp.asarray(0.0), size))
    down = np.asarray(render_arm(jnp.asarray(np.pi), size))
    c = size // 2
    # θ=0 is 'up': mass above the center row; θ=π below.
    assert up[: c - 2].sum() > up[c + 2 :].sum()
    assert down[c + 2 :].sum() > down[: c - 2].sum()
    assert 0.0 <= up.min() and up.max() <= 1.0
    # the stroke actually lights pixels (anti-aliased peak ≈ 0.8)
    assert up.max() > 0.7


def test_pixel_pendulum_shapes_and_jit():
    env = PixelPendulum(size=24)
    state, obs = jax.jit(env.reset)(jax.random.PRNGKey(0))
    assert obs.shape == (24 * 24 * 2,)
    state2, obs2, r, term, trunc = jax.jit(env.step)(state, jnp.asarray([0.5]))
    assert obs2.shape == (24 * 24 * 2,)
    assert float(r) <= 0.0
    assert float(term) == 0.0
    np.testing.assert_array_less(-1e-6, np.asarray(obs2))
    np.testing.assert_array_less(np.asarray(obs2), 1.0 + 1e-6)


def test_pixel_pendulum_velocity_channel():
    """The two channels differ when the pendulum moves (Markovian obs)."""
    env = PixelPendulum(size=24)
    state, _ = env.reset(jax.random.PRNGKey(1))
    # Force a fast-moving state: θ=π/2, θ̇=max speed.
    physics = jnp.asarray([jnp.pi / 2, 8.0])
    obs = env._obs(physics)
    frames = np.asarray(obs).reshape(24, 24, 2)
    assert np.abs(frames[..., 0] - frames[..., 1]).max() > 0.5
    # And match when static.
    obs_static = env._obs(jnp.asarray([jnp.pi / 2, 0.0]))
    frames_s = np.asarray(obs_static).reshape(24, 24, 2)
    np.testing.assert_allclose(frames_s[..., 0], frames_s[..., 1], atol=1e-5)


def test_pixel_rollout_scans_on_device():
    env = PixelPendulum(size=16)
    policy = lambda obs, key: jax.random.uniform(key, (1,), minval=-1.0, maxval=1.0)
    _, _, traj = rollout(env, policy, jax.random.PRNGKey(0), num_steps=8)
    assert traj.obs.shape == (8, 16 * 16 * 2)
    assert traj.next_obs.shape == (8, 16 * 16 * 2)


@pytest.mark.slow
def test_pixel_train_step_runs_and_learns():
    H, W, C = 16, 16, 2
    config = D4PGConfig(
        obs_dim=H * W * C,
        action_dim=1,
        hidden_sizes=(32, 32),
        pixel_shape=(H, W, C),
        encoder_embed_dim=16,
        dist=DistConfig(kind="categorical", num_atoms=21, v_min=-5, v_max=5),
    )
    state = create_train_state(config, jax.random.PRNGKey(0))
    # Encoder params exist in BOTH networks.
    assert any("PixelEncoder" in k for k in state.actor_params["params"])
    assert any("PixelEncoder" in k for k in state.critic_params["params"])
    step = jit_train_step(config, donate=False)
    rng = np.random.default_rng(0)
    B = 16
    batch = {
        "obs": jnp.asarray(rng.uniform(0, 1, size=(B, H * W * C)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, size=(B, 1)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, size=B), jnp.float32),
        "next_obs": jnp.asarray(rng.uniform(0, 1, size=(B, H * W * C)), jnp.float32),
        "discount": jnp.full((B,), 0.99, jnp.float32),
        "weights": jnp.ones((B,), jnp.float32),
    }
    state2, metrics, priorities = step(state, batch)
    assert int(state2.step) == 1
    for v in metrics.values():
        assert np.isfinite(float(v))
    # The conv encoder itself receives gradient.
    enc_before = [
        v for k, v in jax.tree_util.tree_leaves_with_path(state.critic_params)
        if "PixelEncoder" in jax.tree_util.keystr(k)
    ]
    enc_after = [
        v for k, v in jax.tree_util.tree_leaves_with_path(state2.critic_params)
        if "PixelEncoder" in jax.tree_util.keystr(k)
    ]
    deltas = [float(jnp.abs(a - b).max()) for a, b in zip(enc_before, enc_after)]
    assert max(deltas) > 0


@pytest.mark.slow
def test_pixel_trainer_smoke(tmp_path):
    """Trainer end-to-end on the pixel env: warmup, a few fused grad steps
    over conv-encoded flattened-pixel batches, eval — no host renderer."""
    import dataclasses

    from train import build_parser, config_from_args
    from d4pg_tpu.runtime import Trainer

    args = build_parser().parse_args(
        [
            "--env", "pixel_pendulum",
            "--total-steps", "4",
            "--warmup", "64",
            "--eval-interval", "1000000",
            "--checkpoint-interval", "1000000",
            "--num-envs", "2",
            "--bsize", "8",
            "--log-dir", str(tmp_path / "pix"),
        ]
    )
    cfg = config_from_args(args)
    cfg = dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, hidden_sizes=(32, 32), encoder_embed_dim=16)
    )
    trainer = Trainer(cfg)
    assert trainer.config.agent.pixel_shape == (48, 48, 2)
    trainer.warmup()
    out = trainer.train(total_steps=4)
    trainer.close()
    assert np.isfinite(out["critic_loss"])


def test_random_shift_augmentation():
    """DrQ shift: content preserved (interior pixels move, edge-pad fills),
    per-sample independent, deterministic under a fixed key, zero pad = id."""
    from d4pg_tpu.ops import random_shift

    H = W = 12
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.uniform(0, 1, (8, H * W * 2)), jnp.float32)
    out = random_shift(flat, jax.random.PRNGKey(0), (H, W, 2), pad=4)
    assert out.shape == flat.shape
    assert 0.0 <= float(out.min()) and float(out.max()) <= 1.0
    # deterministic
    out2 = random_shift(flat, jax.random.PRNGKey(0), (H, W, 2), pad=4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # different key → different shifts (almost surely)
    out3 = random_shift(flat, jax.random.PRNGKey(1), (H, W, 2), pad=4)
    assert not np.allclose(np.asarray(out), np.asarray(out3))
    # shifts are per-sample: identical inputs can land on distinct crops
    same = jnp.broadcast_to(flat[:1], flat.shape)
    outs = np.asarray(random_shift(same, jax.random.PRNGKey(2), (H, W, 2)))
    assert np.unique(outs.round(6), axis=0).shape[0] > 1


@pytest.mark.slow  # compile-heavy (conftest fast-tier budget)
def test_train_step_augment_keys_advance():
    """Pixel configs thread the PRNG through the state so every train step
    augments differently; flat configs leave the key untouched."""
    cfgs = {
        "pixel": D4PGConfig(
            obs_dim=8 * 8 * 2, action_dim=1, hidden_sizes=(16, 16),
            pixel_shape=(8, 8, 2), encoder_embed_dim=8,
            dist=DistConfig(num_atoms=11, v_min=-5, v_max=5),
        ),
        "flat": D4PGConfig(
            obs_dim=4, action_dim=1, hidden_sizes=(16, 16),
            dist=DistConfig(num_atoms=11, v_min=-5, v_max=5),
        ),
    }
    rng = np.random.default_rng(0)
    for name, cfg in cfgs.items():
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        B = 4
        batch = {
            "obs": jnp.asarray(rng.uniform(0, 1, (B, cfg.obs_dim)), jnp.float32),
            "action": jnp.zeros((B, 1), jnp.float32),
            "reward": jnp.zeros((B,), jnp.float32),
            "next_obs": jnp.asarray(rng.uniform(0, 1, (B, cfg.obs_dim)), jnp.float32),
            "discount": jnp.full((B,), 0.9, jnp.float32),
            "weights": jnp.ones((B,), jnp.float32),
        }
        state2, _, _ = jit_train_step(cfg, donate=False)(state, batch)
        changed = not np.array_equal(np.asarray(state.key), np.asarray(state2.key))
        assert changed == (name == "pixel"), name


def test_uint8_replay_roundtrip():
    """Pixel replay stores uint8 (4x less RAM); [0,1] floats round-trip
    within quantization error 1/255."""
    from d4pg_tpu.replay import PrioritizedReplayBuffer, ReplayBuffer
    from d4pg_tpu.replay.uniform import Transition

    rng = np.random.default_rng(0)
    obs = rng.uniform(0, 1, size=(16, 32)).astype(np.float32)
    nxt = rng.uniform(0, 1, size=(16, 32)).astype(np.float32)
    for buf in (
        ReplayBuffer(64, 32, 2, obs_dtype=np.uint8),
        PrioritizedReplayBuffer(64, 32, 2, obs_dtype=np.uint8),
    ):
        assert buf.obs.dtype == np.uint8 and buf.next_obs.dtype == np.uint8
        idx = buf.add_batch(
            Transition(obs, np.zeros((16, 2), np.float32),
                       np.zeros(16, np.float32), nxt, np.ones(16, np.float32))
        )
        got = buf.gather(np.asarray(idx))
        assert got["obs"].dtype == np.float32
        np.testing.assert_allclose(got["obs"], obs, atol=1.0 / 255.0 + 1e-7)
        np.testing.assert_allclose(got["next_obs"], nxt, atol=1.0 / 255.0 + 1e-7)


def test_pixel_preset_wires_encoder_and_capacity():
    """The public preset API alone (no Trainer) must yield a conv-encoded
    agent and a pixel-sized replay default."""
    from d4pg_tpu.config import TrainConfig, apply_env_preset

    cfg = apply_env_preset(TrainConfig(env="pixel_pendulum"))
    assert cfg.agent.pixel_shape == (48, 48, 2)
    assert cfg.agent.obs_dim == 48 * 48 * 2
    assert cfg.replay_capacity == 100_000
    # explicit user capacity wins over the preset cap
    cfg2 = apply_env_preset(TrainConfig(env="pixel_pendulum", replay_capacity=5_000))
    assert cfg2.replay_capacity == 5_000


def test_uint8_replay_rejects_byte_range_scale():
    """obs_scale≠255 is a train/act input-scale trap (stored rows decode to
    [0,1] while acting feeds the raw env range to the same actor), so the
    buffer refuses it at construction — byte-image envs must normalize at
    the env boundary instead (advisor round-1 #2)."""
    from d4pg_tpu.replay import ReplayBuffer

    with pytest.raises(ValueError, match="env boundary"):
        ReplayBuffer(32, 16, 1, obs_dtype=np.uint8, obs_scale=1.0)


def test_cli_default_path_applies_pixel_preset():
    """`train.py --env pixel_pendulum` with NO extra flags must get the
    conv encoder and the pixel-sized replay cap (preset not gated on
    --v-min/--v-max)."""
    from train import build_parser, config_from_args

    cfg = config_from_args(build_parser().parse_args(["--env", "pixel_pendulum"]))
    assert cfg.agent.pixel_shape == (48, 48, 2)
    assert cfg.replay_capacity == 100_000
    assert cfg.agent.dist.v_min == -300.0
    # explicit flags still win
    cfg2 = config_from_args(build_parser().parse_args(
        ["--env", "pixel_pendulum", "--v-min", "-50", "--rmsize", "7000"]))
    assert cfg2.agent.dist.v_min == -50.0
    assert cfg2.replay_capacity == 7_000
