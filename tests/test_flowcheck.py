"""Flow conservation, both halves (ISSUE 19 acceptance fixture).

The seeded bug is the PR-7 FleetLink vanished-windows class: an
unexpected reply type for a KNOWN req_id pops the pending entry and
kills the link WITHOUT booking the windows as dropped, so they vanish
from the ``windows_emitted == accounted`` identity. One test re-seeds
that bug into the real source and asserts the static ``flowcheck`` pass
names the unbooked exit; one drives a LIVE FleetLink into the same arm
against an impostor server whose books ignore the drop, and asserts the
runtime ConservationLedger raises at drain. Plus unit coverage for the
ledger itself and the committed flow-identities artifact.
"""

import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

from d4pg_tpu.analysis import flowledger
from d4pg_tpu.analysis.flowledger import ConservationError
from d4pg_tpu.fleet import wire
from d4pg_tpu.fleet.actor import FleetLink
from d4pg_tpu.serve import protocol
from tools.d4pglint.core import lint_source
from tools.d4pglint.wholeprog.config import FLOW_IDENTITIES
from tools.d4pglint.wholeprog.flowcheck import identity_counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ACTOR_REL = "d4pg_tpu/fleet/actor.py"
OBS, ACT, NSTEP, GAMMA = 5, 2, 3, 0.99


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _actor_src() -> str:
    with open(os.path.join(REPO, ACTOR_REL)) as f:
        return f.read()


# ------------------------------------------------------------- static half
def test_real_actor_source_is_conservation_clean():
    findings, _ = lint_source(_actor_src(), ACTOR_REL, checks=["flowcheck"])
    assert findings == [], findings


def test_seeded_fleetlink_bug_caught_by_static_pass():
    """Delete the unexpected-reply-type booking (the historical bug) and
    the pass must name the now-unbooked ``raise`` exit in _read_loop."""
    src = _actor_src()
    lines = src.splitlines()
    booked = [
        i for i, ln in enumerate(lines)
        if '"dropped"' in ln
        and i + 1 < len(lines)
        and "unexpected reply type" in lines[i + 1]
    ]
    assert len(booked) == 1, "seeded-bug site moved: update this test"
    del lines[booked[0]]
    findings, _ = lint_source(
        "\n".join(lines), ACTOR_REL, checks=["flowcheck"]
    )
    assert findings, "static pass missed the seeded vanished-windows bug"
    msgs = [f.message for f in findings]
    assert any(
        "FleetLink._read_loop" in m and "raise" in m for m in msgs
    ), msgs


# ------------------------------------------------------------ ledger units
@pytest.fixture(autouse=True)
def _reset_ledger():
    flowledger.reset()
    yield
    flowledger.reset()


def test_ledger_disabled_is_a_noop():
    assert flowledger.check("fleet-actor", {"windows_emitted": 9}) is None


def test_ledger_balanced_emits_verdict_line(capsys):
    flowledger.enable()
    assert flowledger.check(
        "router",
        {"requests_total": 5, "replies_ok": 3, "replies_overloaded": 1,
         "replies_error": 1},
        where="unit",
    )
    line = [
        ln for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("[flow-verdict] ")
    ]
    assert len(line) == 1
    doc = json.loads(line[0][len("[flow-verdict] "):])
    assert doc["family"] == "router" and doc["ok"] is True
    assert doc["counters"]["requests_total"] == 5


def test_ledger_imbalance_raises_named_error():
    flowledger.enable()
    with pytest.raises(ConservationError) as ei:
        flowledger.check(
            "fleet-ingest",
            {"windows_from_actors": 4, "windows_from_mirror": 1,
             "windows_ingested": 3},
            where="unit",
        )
    assert "fleet-ingest" in str(ei.value)
    assert "windows_ingested" in str(ei.value)


def test_ledger_per_row_families(capsys):
    flowledger.enable()
    rows = {
        "acme/interactive": {"requests": 3, "ok": 2, "overloaded": 1,
                             "error": 0},
        "acme/bulk": {"requests": 2, "ok": 1, "overloaded": 0, "error": 0},
    }
    with pytest.raises(ConservationError) as ei:
        flowledger.check_rows("router-tenant", rows, where="unit")
    assert "acme/bulk" in str(ei.value)
    doc = json.loads(
        capsys.readouterr().out.splitlines()[0][len("[flow-verdict] "):]
    )
    assert doc["counters"] == {"rows": 2, "bad_rows": 1}
    rows["acme/bulk"]["error"] = 1
    assert flowledger.check_rows("router-tenant", rows, where="unit")


# -------------------------------------------------------- committed artifact
def test_committed_flow_identities_artifact_is_fresh():
    from tools.d4pglint.core import parse_default_files, repo_root
    from tools.d4pglint.wholeprog.flowcheck import build_flow_graph

    with open(os.path.join(REPO, "benchmarks", "flow_identities.json")) as f:
        committed = json.load(f)
    root = repo_root()
    rebuilt = build_flow_graph(parse_default_files(root), root)
    assert committed == rebuilt, (
        "benchmarks/flow_identities.json is stale — regenerate with "
        "`python -m tools.d4pglint.wholeprog.flowcheck --write`"
    )
    for fam, doc in committed["families"].items():
        assert doc["assertion_sites"], f"{fam}: identity asserted nowhere"


def test_every_family_identity_parses_and_references_known_counters():
    for fam, doc in FLOW_IDENTITIES.items():
        names = identity_counters(doc)
        assert names, fam
        # the ledger's evaluator must accept every committed identity
        flowledger.enable()
        flowledger.check(fam, {n: 0 for n in names}, where="unit") \
            if not doc.get("per_row") else \
            flowledger.check_rows(fam, {"r": {n: 0 for n in names}},
                                  where="unit")
        flowledger.reset()


# ------------------------------------------------------------- runtime half
def _impostor_server(reply_type: int, state: dict):
    """Handshakes, reads ONE windows frame, answers it with
    ``reply_type`` — protocol betrayal after a clean HELLO."""
    lsock = socket.create_server(("127.0.0.1", 0))
    state["port"] = lsock.getsockname()[1]

    def serve():
        conn, _ = lsock.accept()
        with conn:
            frame = protocol.read_frame(conn)  # HELLO
            protocol.write_frame(
                conn, protocol.HELLO_OK, frame[1],
                wire.encode_hello_ok(
                    generation=0, max_windows=64, max_inflight=4
                ),
            )
            t, req_id, _payload = protocol.read_frame(conn)
            assert t == protocol.WINDOWS
            protocol.write_frame(conn, reply_type, req_id, b"gotcha")
            state["replied"] = True
            time.sleep(0.5)  # let the client read before RST
    threading.Thread(target=serve, name="impostor", daemon=True).start()
    return lsock


def _frame_cols(n):
    rng = np.random.default_rng(0)
    return {
        "obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "action": rng.standard_normal((n, ACT)).astype(np.float32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, OBS)).astype(np.float32),
        "discount": rng.random(n).astype(np.float32),
    }


def _drive_link_into_unexpected_reply(on_ack):
    state = {}
    lsock = _impostor_server(reply_type=protocol.ACT_OK, state=state)
    link = FleetLink(
        "127.0.0.1", state["port"],
        dict(actor_id="seeded", env="e", obs_dim=OBS, action_dim=ACT,
             n_step=NSTEP, gamma=GAMMA, generation=0),
        on_ack=on_ack,
    )
    try:
        assert link.acquire_credit(5)
        link.send_windows((0, 0, False), _frame_cols(3))
        assert _wait(lambda: link.dead is not None)
        assert "unexpected reply type" in str(link.dead)
    finally:
        link.close()
        lsock.close()


def test_seeded_fleetlink_bug_caught_by_ledger():
    """Live FleetLink hits the unexpected-reply arm. With the seeded
    bug's books (the drop never recorded), the ledger raises at drain;
    with honest books the same drain balances."""
    stats = {k: 0 for k in (
        "windows_emitted", "windows_acked", "windows_stale", "windows_shed",
        "windows_dropped_reconnect", "windows_dropped_spool", "spool_depth",
    )}
    lock = threading.Lock()
    kinds = {"accepted": "windows_acked", "stale": "windows_stale",
             "shed": "windows_shed", "dropped": "windows_dropped_reconnect"}

    def buggy_on_ack(kind, n):
        with lock:
            if kind != "dropped":  # the seeded bug: drops vanish
                stats[kinds[kind]] += n

    stats["windows_emitted"] = 3
    _drive_link_into_unexpected_reply(buggy_on_ack)
    flowledger.enable()
    with pytest.raises(ConservationError) as ei:
        flowledger.check("fleet-actor", stats, where="actor drain")
    assert "fleet-actor" in str(ei.value)
    assert "consumed without booking" in str(ei.value)

    # control: honest books → the SAME drain balances
    for k in kinds.values():
        stats[k] = 0

    def honest_on_ack(kind, n):
        with lock:
            stats[kinds[kind]] += n

    _drive_link_into_unexpected_reply(honest_on_ack)
    assert flowledger.check("fleet-actor", stats, where="actor drain")


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
