"""REDQ-style critic ensembles (agent/state.py:critic_ensemble) — the
capacity arc the sharded learner unlocks (ROADMAP item 2).

Pins: stacked init (E independent members), the train step under both
heads (categorical and MoG), that the random-subset size M is load-
bearing (M=1 vs M=E runs diverge), config validation, and the GSPMD
member-parallel layout (stack axis sharded over "tp" via the rule
registry's stack_axes declaration).
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from d4pg_tpu.agent import D4PGConfig, create_train_state  # noqa: E402
from d4pg_tpu.agent.d4pg import _stacked_critics, jit_train_step  # noqa: E402
from d4pg_tpu.models.critic import DistConfig  # noqa: E402


def _cfg(**kw) -> D4PGConfig:
    base = dict(
        obs_dim=3,
        action_dim=1,
        hidden_sizes=(16, 16),
        critic_ensemble=4,
        ensemble_min_targets=2,
        dist=DistConfig(num_atoms=11, v_min=-5.0, v_max=5.0),
    )
    base.update(kw)
    return D4PGConfig(**base)


def _batch(rng, B=8, obs_dim=3, act_dim=1):
    return {
        "obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-1, 1, (B, act_dim)), jnp.float32),
        "reward": jnp.asarray(rng.uniform(-1, 0, B), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(B, obs_dim)), jnp.float32),
        "discount": jnp.full((B,), 0.99, jnp.float32),
        "weights": jnp.ones((B,), jnp.float32),
    }


def test_stacked_init_is_E_independent_members():
    state = create_train_state(_cfg(), jax.random.PRNGKey(0))
    for tree in (
        state.critic_params,
        state.target_critic_params,
    ):
        for leaf in jax.tree_util.tree_leaves(tree):
            assert leaf.shape[0] == 4
    k = state.critic_params["params"]["hidden_0"]["kernel"]
    # independent inits: no two members share bits
    for i in range(1, 4):
        assert not np.array_equal(np.asarray(k[0]), np.asarray(k[i]))
    # Adam moments stack along (optax mirrors the param tree)
    mom = jax.tree_util.tree_leaves(state.critic_opt_state)
    assert any(m.ndim and m.shape[0] == 4 for m in mom)


@pytest.mark.parametrize("kind", ["categorical", "mixture_gaussian"])
def test_train_step_runs_under_both_heads(kind):
    cfg = _cfg(
        dist=DistConfig(
            kind=kind, num_atoms=11, num_mixtures=3, v_min=-5.0, v_max=5.0
        )
    )
    state = create_train_state(cfg, jax.random.PRNGKey(0))
    step = jit_train_step(cfg, donate=False)
    rng = np.random.default_rng(0)
    for i in range(2):
        state, metrics, priorities = step(state, _batch(rng))
    assert np.isfinite(float(metrics["critic_loss"]))
    assert np.isfinite(float(metrics["actor_loss"]))
    assert priorities.shape == (8,)
    assert np.isfinite(np.asarray(priorities)).all()


def test_subset_size_is_load_bearing():
    """M=1 and M=E backups must differ: same seed, same data, different
    in-target minimization — if the subset never mattered the two runs
    would stay bit-identical."""
    rng = np.random.default_rng(1)
    batches = [_batch(rng) for _ in range(3)]
    outs = []
    for m in (1, 4):
        cfg = _cfg(ensemble_min_targets=m)
        state = create_train_state(cfg, jax.random.PRNGKey(0))
        step = jit_train_step(cfg, donate=False)
        for b in batches:
            state, _, _ = step(state, b)
        outs.append(jax.device_get(state.critic_params))
    la, lb = map(jax.tree_util.tree_leaves, outs)
    assert any(not np.array_equal(a, b) for a, b in zip(la, lb))


def test_config_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        _stacked_critics(_cfg(twin_critic=True))
    with pytest.raises(ValueError, match=">= 2"):
        _stacked_critics(_cfg(critic_ensemble=1))
    with pytest.raises(ValueError, match="ensemble_min_targets"):
        _stacked_critics(_cfg(ensemble_min_targets=5))
    with pytest.raises(ValueError, match="ensemble_min_targets"):
        _stacked_critics(_cfg(ensemble_min_targets=0))
    assert _stacked_critics(_cfg()) == 4
    assert _stacked_critics(_cfg(critic_ensemble=0, twin_critic=True)) == 2
    assert _stacked_critics(_cfg(critic_ensemble=0)) == 0


@pytest.mark.slow
def test_gspmd_member_parallel_layout():
    """auto_parallel_train_step(ensemble_axis="tp"): the member stack
    shards over "tp" (each device holds E/tp WHOLE members — the
    expert-parallel layout from the stack_axes declaration), the step
    trains and stays finite under the MoG head at a tp-unfriendly width
    (the concat layer replicates per the rules)."""
    from d4pg_tpu.parallel import (
        auto_parallel_train_step,
        make_mesh,
        shard_batch,
        shard_train_state,
        stack_axes_for,
    )

    cfg = _cfg(
        hidden_sizes=(64, 64),
        dist=DistConfig(
            kind="mixture_gaussian", num_mixtures=3, v_min=-5.0, v_max=5.0
        ),
    )
    mesh = make_mesh(dp=4, tp=2)
    state = shard_train_state(
        create_train_state(cfg, jax.random.PRNGKey(0)), mesh,
        stack_axes=stack_axes_for(cfg, "tp"),
    )
    step = auto_parallel_train_step(cfg, mesh, donate=False, ensemble_axis="tp")
    rng = np.random.default_rng(0)
    batch = {k: np.asarray(v) for k, v in _batch(rng, B=64).items()}
    out_state, metrics, priorities = step(state, shard_batch(batch, mesh))
    assert np.isfinite(float(metrics["critic_loss"]))
    assert priorities.shape == (64,)
    leaf = out_state.critic_params["params"]["hidden_0"]["kernel"]
    shapes = {s.data.shape for s in leaf.addressable_shards}
    assert shapes == {(2, 3, 64)}  # 4 members / tp=2, trailing dims whole
