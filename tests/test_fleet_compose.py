"""ISSUE 13 end-to-end composition: the newly-opened fleet cells driven
through the REAL components — Trainer (fleet-only ingest, guards on) fed
by a REAL FleetActor over real sockets — for HER + obs-norm (goal env)
and u8 pixels (host pixel env + numpy conv policy). Fast variants run a
handful of grad steps in the fast tier; the 400-step acceptance runs are
slow-marked (chaos_soak.sh leg 8 drives them through the CLIs too)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import toy_goal_env  # noqa: F401  (registers ToyGoal-v0)
from d4pg_tpu.config import TrainConfig
from d4pg_tpu.fleet.actor import FleetActor
from d4pg_tpu.fleet.ingest import IngestServer
from d4pg_tpu.replay.uniform import ReplayBuffer

GOAL_ENV = "toy_goal_env:ToyGoal-v0"


def _trainer_cfg(tmp_path, **over):
    base = dict(
        env=GOAL_ENV,
        her=True,
        her_k=2,
        obs_norm=True,
        num_envs=0,
        fleet_listen=0,
        fleet_host="127.0.0.1",
        fleet_bundle=str(tmp_path / "bundle"),
        fleet_publish_interval=4,
        fleet_max_gen_lag=2,
        warmup_steps=24,
        batch_size=8,
        replay_capacity=512,
        n_step=3,
        total_steps=8,
        eval_interval=100000,
        checkpoint_interval=100000,
        concurrent_eval=False,
        debug_guards=True,
        log_dir=str(tmp_path / "run"),
        seed=3,
    )
    base.update(over)
    agent_over = base.pop("agent_over", {})
    cfg = TrainConfig(**base)
    import dataclasses

    agent = dataclasses.replace(
        cfg.agent, hidden_sizes=(16, 16), **agent_over
    )
    return dataclasses.replace(cfg, agent=agent)


def _run_fleet_fed(cfg, actor_kwargs, steps):
    """Build the Trainer, feed it with a real FleetActor thread, train
    ``steps`` grad steps under guards, and return (trainer_result,
    fleet_counters, actor_stats)."""
    from d4pg_tpu.runtime.trainer import Trainer

    t = Trainer(cfg)
    stop = threading.Event()
    actor = FleetActor(
        connect=f"127.0.0.1:{t._fleet.port}",
        bundle_dir=cfg.fleet_bundle,
        stop_event=stop,
        batch_windows=8,
        poll_interval_s=0.2,
        seed=11,
        **actor_kwargs,
    )
    th = threading.Thread(target=actor.run, name="test-fleet-actor",
                          daemon=True)
    th.start()
    try:
        result = t.train(total_steps=steps)
        counters = t._fleet.counters()
    finally:
        stop.set()
        th.join(timeout=30)
        t.close()
    assert not th.is_alive()
    return result, counters, actor.stats()


def test_fleet_her_obsnorm_guarded_smoke(tmp_path):
    """The flagship newly-opened composition: a fleet-fed HER + obs-norm
    learner under --debug-guards — actor-side relabeling, stats riding
    the bundle, generation-tagged windows — trains a few steps with zero
    guard trips (guards raise on any) and exact ingest accounting."""
    cfg = _trainer_cfg(tmp_path)
    result, counters, stats = _run_fleet_fed(
        cfg, dict(env_id=GOAL_ENV, her=True, her_k=2), steps=8
    )
    assert counters["windows_ingested"] > 0
    assert counters["handshake_refusals"] == 0
    # the actor relabeled: more windows than env steps ever stepped
    assert stats["windows_emitted"] > stats["env_steps"] > 0
    # the learner's statistics really folded from ingested windows
    # (obs-norm e2e: count tracks ORIGINAL windows only)
    from_ingest = counters["windows_ingested"]
    assert 0 < int(result.get("replay_size", 0)) <= from_ingest


def test_fleet_her_requires_her_actor(tmp_path):
    """A non-HER actor against the HER learner is refused at HELLO with
    the structured reason — the old CLI hard-stop, relocated to the
    negotiation and made per-connection."""
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = _trainer_cfg(tmp_path, total_steps=2)
    t = Trainer(cfg)
    try:
        stop = threading.Event()
        actor = FleetActor(
            connect=f"127.0.0.1:{t._fleet.port}",
            bundle_dir=cfg.fleet_bundle,
            env_id=GOAL_ENV,
            her=False,  # mismatch: learner negotiates her=True
            stop_event=stop,
            reconnect_attempts=1,
            seed=1,
        )
        with pytest.raises(RuntimeError, match="refused handshake"):
            # the refusal is fatal inside the first connect attempt
            actor._ensure_link()
        assert t._fleet.counters()["handshake_refusals"] >= 1
    finally:
        t.close()


def test_fleet_pixel_u8_ingest_e2e(tmp_path):
    """The pixel cell, socket to buffer: a REAL FleetActor on the
    JAX-free host pixel env with the numpy conv policy streams
    u8-quantized WINDOWS2 frames into an ingest server; the stored
    uint8 rows must round-trip the wire exactly (spot-checked against
    the actor's own quantization)."""
    import jax

    from d4pg_tpu.agent.state import D4PGConfig
    from d4pg_tpu.fleet import wire
    from d4pg_tpu.serve.bundle import actor_template, export_bundle

    size = 48
    obs_dim = size * size * 2
    agent = D4PGConfig(
        obs_dim=obs_dim, action_dim=1, hidden_sizes=(16, 16),
        pixel_shape=(size, size, 2), n_step=3,
    )
    bundle = tmp_path / "pixel_bundle"
    export_bundle(
        str(bundle), agent, actor_template(agent),
        meta={"generation": 0, "env": "pixel_pendulum"},
    )
    buf = ReplayBuffer(256, obs_dim, 1, obs_dtype=np.uint8)
    srv = IngestServer(
        buf, obs_dim=obs_dim, action_dim=1, n_step=3, gamma=0.99,
        host="127.0.0.1", port=0,
        caps={"obs_mode": "u8", "her": False, "obs_norm": False},
    ).start()
    stop = threading.Event()
    actor = FleetActor(
        connect=f"127.0.0.1:{srv.port}",
        bundle_dir=str(bundle),
        env_id="pixel_pendulum_host",
        batch_windows=4,
        max_env_steps=24,
        stop_event=stop,
        seed=5,
    )
    try:
        stats = actor.run()
        assert stats["windows_acked"] > 0
        # acks are sent at ADMISSION; windows_ingested ticks after the
        # writer thread's add_batch — bounded wait for the queue drain
        # (under CI load the writer can lag the last acked frame)
        deadline = time.monotonic() + 30
        while (
            srv.counters()["windows_ingested"] != stats["windows_acked"]
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert srv.counters()["windows_ingested"] == stats["windows_acked"]
        # stored rows are u8 and consistent with the wire quantizer:
        # decode(÷255) → re-quantize is identity, so every stored byte
        # row must survive its own round-trip
        n = len(buf)
        assert n > 0 and buf.obs.dtype == np.uint8
        dec = buf.obs[:n].astype(np.float32) / 255.0
        assert (wire.quantize_obs_u8(dec) == buf.obs[:n]).all()
    finally:
        stop.set()
        srv.close()


def test_her_flush_carries_episode_start_tag(tmp_path):
    """A mid-episode bundle hot-swap must not re-stamp already-acted HER
    experience as fresh: the episode buffers in the relabeler until
    flush, so the flush is tagged with the generation in force when the
    episode BEGAN (conservative: ingest may drop a partially-fresh
    episode as stale, never accept stale windows as fresh)."""
    from d4pg_tpu.runtime.trainer import Trainer

    cfg = _trainer_cfg(tmp_path, total_steps=2)
    t = Trainer(cfg)
    try:
        actor = FleetActor(
            connect="127.0.0.1:1",  # never dialed: no flush in this test
            bundle_dir=cfg.fleet_bundle,
            env_id=GOAL_ENV,
            her=True,
            her_k=1,
            seed=2,
        )
        assert actor._her_episode_tag[0] == (
            actor.policy.generation, actor.policy.stats_generation
        )
        start_tag = actor._her_episode_tag[0]
        # act a few steps, then simulate a mid-episode hot-swap the way
        # _maybe_reload_bundle applies one
        for _ in range(3):
            actor._step_envs()
        actor.policy.generation += 7
        actor.policy.stats_generation += 7
        actor.spool.generation = actor.policy.generation
        actor.spool.stats_generation = actor.policy.stats_generation
        # run the episode to its end (ToyGoal truncates at 25 steps)
        for _ in range(40):
            actor._step_envs()
            if len(actor.spool):
                break
        assert len(actor.spool) > 0, "episode never flushed"
        assert all(
            row[0][:2] == start_tag for row in actor.spool.rows
        ), "flushed HER windows must carry the episode-START tag"
        # the NEXT episode adopts the live policy's tag
        assert actor._her_episode_tag[0] == (
            actor.policy.generation, actor.policy.stats_generation
        )
    finally:
        t.close()


@pytest.mark.slow
def test_fleet_her_obsnorm_400_steps_acceptance(tmp_path):
    """ISSUE 13 acceptance: the fleet-fed HER + obs-norm learner runs
    400 grad steps under --debug-guards with zero guard trips (any trip
    raises) and the at-most-once accounting identity exact."""
    cfg = _trainer_cfg(tmp_path, total_steps=400, fleet_publish_interval=50)
    result, counters, stats = _run_fleet_fed(
        cfg, dict(env_id=GOAL_ENV, her=True, her_k=2), steps=400
    )
    assert counters["windows_ingested"] >= 400
    acct = (stats["windows_acked"] + stats["windows_stale"]
            + stats["windows_shed"] + stats["windows_dropped_reconnect"]
            + stats["windows_dropped_spool"] + stats["spool_depth"])
    assert acct == stats["windows_emitted"], (acct, stats)


@pytest.mark.slow
def test_fleet_pixel_400_steps_acceptance(tmp_path):
    """ISSUE 13 acceptance, pixel leg: a fleet-fed pixel learner (u8
    wire) runs 400 grad steps under --debug-guards, fed by the JAX-free
    host pixel env twin."""
    cfg = _trainer_cfg(
        tmp_path,
        env="pixel_pendulum",
        her=False,
        obs_norm=False,
        total_steps=400,
        fleet_publish_interval=100,
        warmup_steps=16,
        replay_capacity=256,
        eval_episodes=1,
    )
    result, counters, stats = _run_fleet_fed(
        cfg,
        dict(env_id="pixel_pendulum_host", noise_sigma=0.3),
        steps=400,
    )
    assert counters["windows_ingested"] >= 400
    assert counters["handshake_refusals"] == 0
    acct = (stats["windows_acked"] + stats["windows_stale"]
            + stats["windows_shed"] + stats["windows_dropped_reconnect"]
            + stats["windows_dropped_spool"] + stats["spool_depth"])
    assert acct == stats["windows_emitted"], (acct, stats)
