"""Native C++ tree backend: equivalence with the NumPy trees + PER usage."""

import numpy as np
import pytest

from d4pg_tpu.replay import MinTree, PrioritizedReplayBuffer, SumTree

native = pytest.importorskip("d4pg_tpu.replay.native")

try:
    native.load_library()
    HAVE_NATIVE = True
except Exception:
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE, reason="g++ build unavailable")


def test_native_matches_numpy_sum_tree():
    rng = np.random.default_rng(0)
    a, b = SumTree(1000), native.NativeSumTree(1000)
    for _ in range(30):
        idx = rng.integers(0, 1000, size=64)
        vals = rng.uniform(0, 10, size=64)
        # de-dup (backends differ on in-batch duplicate ordering semantics)
        idx, keep = np.unique(idx, return_index=True)
        vals = vals[keep]
        a.set(idx, vals)
        b.set(idx, vals)
        assert a.sum() == pytest.approx(b.sum())
        q = rng.integers(0, 1000, size=32)
        np.testing.assert_allclose(a.get(q), b.get(q))
        prefixes = rng.uniform(0, a.sum(), size=128)
        np.testing.assert_array_equal(
            a.find_prefixsum_idx(prefixes), b.find_prefixsum_idx(prefixes)
        )


def test_native_matches_numpy_min_tree():
    rng = np.random.default_rng(1)
    a, b = MinTree(512), native.NativeMinTree(512)
    for _ in range(20):
        idx = rng.integers(0, 512, size=33)
        vals = rng.uniform(0.01, 5, size=33)
        idx, keep = np.unique(idx, return_index=True)
        a.set(idx, vals[keep])
        b.set(idx, vals[keep])
        assert a.min() == pytest.approx(b.min())


def test_per_with_native_backend():
    buf = PrioritizedReplayBuffer(256, 3, 2, tree_backend="native")
    rng = np.random.default_rng(2)
    for i in range(50):
        buf.add(rng.normal(size=3), rng.normal(size=2), float(i), rng.normal(size=3), 0.99)
    batch = buf.sample(32, rng, step=0)
    assert batch["obs"].shape == (32, 3)
    buf.update_priorities(batch["indices"], rng.uniform(0.1, 2, size=32))
    batch2 = buf.sample(32, rng, step=100)
    assert np.all(batch2["weights"] > 0)


def test_native_proportional_statistics():
    rng = np.random.default_rng(3)
    t = native.NativeSumTree(16)
    p = np.array([1.0, 2.0, 4.0, 8.0])
    t.set(np.arange(4), p)
    draws = t.find_prefixsum_idx(rng.uniform(0, t.sum(), size=100_000))
    freq = np.bincount(draws, minlength=4)[:4] / 100_000
    np.testing.assert_allclose(freq, p / p.sum(), atol=0.01)
