"""Property tests: tree invariants + proportional-sampling statistics."""

import numpy as np
import pytest

from d4pg_tpu.replay import MinTree, SumTree


def test_sum_tree_invariant_random_updates():
    rng = np.random.default_rng(0)
    t = SumTree(100)
    ref = np.zeros(t.capacity)
    for _ in range(50):
        idx = rng.integers(0, 100, size=17)
        vals = rng.uniform(0, 5, size=17)
        # emulate last-write-wins for duplicates like the tree does
        t.set(idx, vals)
        ref[idx] = vals
        assert t.sum() == pytest.approx(ref.sum())
        np.testing.assert_allclose(t.get(np.arange(100)), ref[:100])


def test_min_tree_invariant():
    rng = np.random.default_rng(1)
    t = MinTree(64)
    ref = np.full(t.capacity, np.inf)
    for _ in range(30):
        idx = rng.integers(0, 64, size=9)
        vals = rng.uniform(0.1, 5, size=9)
        t.set(idx, vals)
        ref[idx] = vals
        assert t.min() == pytest.approx(ref.min())


def test_prefixsum_idx_definition():
    t = SumTree(8)
    t.set(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))  # cumsum 1,3,6,10
    got = t.find_prefixsum_idx(np.array([0.0, 0.5, 1.0, 2.99, 3.0, 9.99]))
    np.testing.assert_array_equal(got, [0, 0, 1, 1, 2, 3])


def test_proportional_sampling_statistics():
    rng = np.random.default_rng(2)
    t = SumTree(16)
    p = np.array([1.0, 2.0, 4.0, 8.0])
    t.set(np.arange(4), p)
    draws = t.find_prefixsum_idx(rng.uniform(0, t.sum(), size=200_000))
    freq = np.bincount(draws, minlength=4)[:4] / 200_000
    np.testing.assert_allclose(freq, p / p.sum(), atol=0.01)


def test_non_pow2_capacity_padding():
    t = SumTree(100)
    assert t.capacity == 128
    t.set(np.array([99]), np.array([7.0]))
    assert t.sum() == pytest.approx(7.0)
    assert t.find_prefixsum_idx(np.array([3.0]))[0] == 99
