"""Running observation normalization (ops/obs_norm.py) — HER-DDPG's
clip((x−μ)/σ, ±5) at the trainer's data boundary (round 5; the reference
has no counterpart, its normalize_env.py scales actions only)."""

import dataclasses

import numpy as np
import pytest

from d4pg_tpu.ops.obs_norm import RunningObsNorm


def test_welford_matches_numpy_in_any_batch_split():
    rng = np.random.default_rng(0)
    data = rng.normal(3.0, 2.5, size=(1000, 7)) * np.linspace(0.1, 10, 7)
    norm = RunningObsNorm(7)
    # uneven incremental batches must reach the same moments as one pass
    for chunk in np.array_split(data, [13, 100, 101, 500, 999]):
        norm.update(chunk)
    assert norm.count == 1000
    np.testing.assert_allclose(norm.mean, data.mean(axis=0), rtol=1e-10)
    np.testing.assert_allclose(norm.std, data.std(axis=0), rtol=1e-10)


def test_normalize_clips_and_floors_std():
    norm = RunningObsNorm(2, clip_range=5.0, eps=1e-2)
    # dim 0 varies, dim 1 is constant (std 0 → eps floor, no div-by-zero)
    norm.update(np.array([[0.0, 4.0], [2.0, 4.0], [4.0, 4.0]]))
    out = norm.normalize(np.array([1000.0, 4.0]))
    assert out[0] == 5.0  # clipped
    assert out[1] == 0.0  # (4-4)/eps = 0
    assert out.dtype == np.float32


def test_state_roundtrip():
    rng = np.random.default_rng(1)
    norm = RunningObsNorm(4)
    norm.update(rng.normal(size=(57, 4)))
    fresh = RunningObsNorm(4)
    fresh.load_state_dict(norm.state_dict())
    np.testing.assert_allclose(fresh.mean, norm.mean)
    np.testing.assert_allclose(fresh.std, norm.std)
    assert fresh.count == norm.count
    x = rng.normal(size=(3, 4))
    np.testing.assert_array_equal(fresh.normalize(x), norm.normalize(x))


def test_state_dict_reads_single_publication():
    """Torn-read fix (advisor round 5): state_dict must read (count, mean,
    m2) from the SAME single-tuple publication normalize uses — never from
    attributes a concurrent update() may have half-written. Simulated by
    tearing the attributes after the last publication."""
    rng = np.random.default_rng(2)
    norm = RunningObsNorm(3)
    norm.update(rng.normal(size=(40, 3)))
    published = norm.state_dict()
    # a mid-update thread switch: attribute written, publication not yet
    norm.mean = norm.mean + 100.0
    norm.count = norm.count + 7
    sd = norm.state_dict()
    assert sd["count"] == published["count"]
    np.testing.assert_allclose(sd["mean"], published["mean"])
    np.testing.assert_allclose(sd["m2"], published["m2"])
    # the next publication (completed update) is picked up again
    norm.update(rng.normal(size=(5, 3)))
    assert norm.state_dict()["count"] == norm._stats[0]


def test_trainer_obs_norm_end_to_end(tmp_path):
    """Pendulum-v1 through the host single-env path with --obs-norm: stats
    fold once per observed env step at collection time, training batches
    and acting/eval consume normalized obs, and the meta file persists the
    statistics for resume."""
    pytest.importorskip("gymnasium")
    from train import build_parser, config_from_args
    from d4pg_tpu.runtime import Trainer

    args = build_parser().parse_args(
        [
            "--env", "Pendulum-v1",
            "--obs-norm",
            "--num-envs", "1",
            "--total-steps", "30",
            "--warmup", "40",
            "--eval-interval", "30",
            "--eval-episodes", "1",
            "--max-steps", "50",
            "--checkpoint-interval", "30",
            "--bsize", "16",
            "--no-concurrent-eval",
            "--log-dir", str(tmp_path / "run"),
        ]
    )
    cfg = config_from_args(args)
    cfg = dataclasses.replace(
        cfg, agent=dataclasses.replace(cfg.agent, hidden_sizes=(32, 32))
    )
    trainer = Trainer(cfg)
    trainer.warmup()
    # stats ingest at COLLECTION time: warmup already observed env steps
    assert trainer.obs_norm is not None
    assert trainer.obs_norm.count == trainer.env_steps > 0
    trainer.train(total_steps=30)
    trainer.close()
    # one stats fold per observed env step, never per sampled batch
    # (PER resampling must not double-count — review round 5)
    assert trainer.obs_norm.count == trainer.env_steps
    import json, os

    meta = json.load(
        open(os.path.join(cfg.log_dir, "checkpoints", "trainer_meta.json"))
    )
    assert meta["obs_norm"]["count"] == trainer.env_steps


def test_on_device_rejects_obs_norm():
    """The guard lives in run_on_device itself, so programmatic configs
    (not just the CLI) are covered."""
    from d4pg_tpu.config import TrainConfig, apply_env_preset
    from d4pg_tpu.runtime.on_device import run_on_device

    cfg = apply_env_preset(TrainConfig(env="pendulum", obs_norm=True))
    with pytest.raises(ValueError, match="obs_norm"):
        run_on_device(cfg)
