"""End-to-end integration: D4PG demonstrably learns Pendulum.

A full solve (return > −300) needs ~30k+ grad steps — too slow for CI — so
this asserts a strong learning signal within a bounded budget: the trained
policy must beat a random-init policy by a wide margin, and the critic loss
must collapse. (SURVEY.md §4 sets the integration bar; the committed full
solve on TPU is `runs/pendulum_ondevice_tpu/` via `train.py --on-device`.)
"""

import dataclasses

import jax
import numpy as np
import pytest

from train import build_parser, config_from_args
from d4pg_tpu.runtime import Trainer, evaluate
from d4pg_tpu.envs import Pendulum
from d4pg_tpu.agent import create_train_state


@pytest.mark.slow
def test_d4pg_learns_pendulum(tmp_path):
    args = build_parser().parse_args(
        [
            "--env", "pendulum",
            "--total-steps", "6000",
            "--warmup", "2000",
            "--eval-interval", "2000",
            "--checkpoint-interval", "1000000",
            "--num-envs", "8",
            "--bsize", "128",
            "--n-step", "3",
            "--tau", "0.005",
            "--lr-actor", "5e-4",
            "--lr-critic", "5e-4",
            "--seed", "0",
            "--log-dir", str(tmp_path / "integ"),
        ]
    )
    cfg = config_from_args(args)
    cfg = dataclasses.replace(
        cfg,
        agent=dataclasses.replace(cfg.agent, hidden_sizes=(64, 64)),
        env_steps_per_train_step=2.0,
    )

    # random-init baseline
    base_state = create_train_state(cfg.agent, jax.random.PRNGKey(123))
    base = evaluate(
        cfg.agent, Pendulum(), base_state.actor_params, jax.random.PRNGKey(7), 10
    )

    trainer = Trainer(cfg)
    first_loss = None
    out = {}
    # train in chunks so we can watch the loss
    trainer.warmup()
    out = trainer.train(total_steps=6000)
    trainer.close()

    trained = evaluate(
        cfg.agent,
        Pendulum(),
        jax.device_get(trainer.state.actor_params),
        jax.random.PRNGKey(7),
        10,
    )
    improvement = trained["eval_return_mean"] - base["eval_return_mean"]
    assert improvement > 250.0, (
        f"no learning: random {base['eval_return_mean']:.0f} → "
        f"trained {trained['eval_return_mean']:.0f}"
    )
    # From ~2.5 at warmup end; the bound has ~10% headroom over typical
    # converged values — the exact trajectory shifts with PRNG consumption
    # (e.g. the device-side n-step collapse changed it by ~0.4%).
    assert out["critic_loss"] < 1.15, f"critic did not converge: {out['critic_loss']}"


@pytest.mark.slow
def test_pool_her_path_collects_and_learns(tmp_path):
    """The POOL HER path (goal-view pool + per-actor HindsightWriters) —
    the path the Fetch solves run on, which had no direct coverage until
    round 5 (the toy pointmass exercise goes through the pure-JAX branch).
    Asserts the full loop runs, the buffer receives relabeled copies
    (> raw transition count), and eval reports a success_rate scalar."""
    pytest.importorskip("gymnasium")
    pytest.importorskip("gymnasium_robotics")
    args = build_parser().parse_args(
        [
            "--env", "FetchReach-v4",
            "--her", "--n-step", "1",
            "--num-envs", "2",
            "--total-steps", "60",
            "--warmup", "40",
            "--eval-interval", "60",
            "--eval-episodes", "2",
            "--checkpoint-interval", "1000000",
            "--bsize", "32",
            "--random-eps", "0.3",
            "--action-l2", "1.0",
            "--no-concurrent-eval",
            "--log-dir", str(tmp_path / "her_pool"),
        ]
    )
    cfg = config_from_args(args)
    cfg = dataclasses.replace(
        cfg,
        agent=dataclasses.replace(cfg.agent, hidden_sizes=(32, 32)),
        pool_start_method="fork",  # spawn costs ~30 s/child on the 1-core CI host
    )
    trainer = Trainer(cfg)
    trainer.warmup()
    out = trainer.train(total_steps=150)
    trainer.close()
    # Relabel invariant, robust to unflushed partials: HindsightWriter only
    # flushes at episode boundaries, so at most 2 envs x 50 steps are
    # pending; everything flushed was written ~5x (original + k=4 future
    # relabels, minus n-step edges). With env_steps >= ~190 here,
    # 5 * (env_steps - 100) > env_steps always — a buffer merely tracking
    # raw steps (HER silently off) fails this by a wide margin.
    raw = trainer.env_steps
    assert len(trainer.buffer) > raw, (
        f"HER must ADD relabeled copies: buffer {len(trainer.buffer)} "
        f"<= raw env steps {raw} (partials can hold back <= 100)"
    )
    assert "success_rate" in out
